#!/usr/bin/env bash
# Tier-1 verify — the ONE blessed entrypoint for builders and CI.
# This encodes the ROADMAP.md "Tier-1 verify" command verbatim; if the
# command there changes, change it here (and nowhere else).
set -o pipefail

# fast pre-test gate: jaxlint + compileall fail in seconds where a broken
# import would cost minutes of pytest collection on this 2-core container
bash "$(dirname "$0")/lint.sh" || exit 1

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

# non-fatal serving-bench smoke: keeps the --steady-state leg runnable
# (compile-cache-warm after the suite, so this is fast); failures are
# reported but never flip the tier-1 verdict
bash "$(dirname "$0")/bench_smoke.sh" \
    || echo "WARNING: bench_smoke.sh failed (non-fatal for tier-1)"

exit $rc
