#!/usr/bin/env python
"""Validate emitted span traces against the Chrome-trace schema.

Run over a trace file or a ``DSTPU_TRACE`` directory (every ``trace*.json``
inside)::

    python scripts/trace_check.py <file-or-dir> \
        [--require train serve ckpt train/offload] \
        [--require-flows serve/req] [--expect-crash]

Checks per file:

- the JSON parses and carries a ``traceEvents`` list;
- every event has the required keys (``ph``/``name``/``pid``/``tid``, plus
  ``ts`` for non-metadata events) with sane types;
- per (pid, tid) track: timestamps are MONOTONIC (non-decreasing) and every
  ``B`` has a matching ``E`` (same name, LIFO order) — i.e. spans nest;
- counter events carry numeric args;
- FLOW events (``ph`` s/t/f — the request-flow chains binding one request's
  hops across lanes/threads, docs/OBSERVABILITY.md): every flow id carries
  exactly one ``s`` and one ``f``, never backwards (``t_f < t_s``), with
  every step inside ``[t_s, t_f]``, and every flow event BINDS — its ts
  falls inside some span on its own track (a dangling binding renders as a
  floating arrowhead in Perfetto and means an exporter bug).

``--require <prefix>...`` additionally asserts (across ALL checked files
together) that each prefix matches at least one span, and that the matched
spans cover at least as many DISTINCT tracks as there are prefixes — the
"spans from N subsystems on distinct tracks" acceptance gate.

``--require-flows <prefix>...`` asserts each prefix is touched by at least
one CROSS-LANE flow chain: a flow id whose bound spans cover >= 2 distinct
tracks with a bound span (or its track) named under the prefix — e.g.
``--require-flows serve/req`` demands a request whose causal chain actually
crosses lanes (router placement -> prefill -> decode stints / migration).

``--expect-crash`` asserts a parseable ``trace_crash.json`` (the flight
recorder's dump) exists in the directory and contains at least one span.

Exit 0 on success; 1 with a per-file error listing otherwise. Invoked
non-fatally from ``scripts/bench_smoke.sh`` after the traced bench legs
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Set, Tuple

Track = Tuple[int, int]


def check_events(events: list, errors: List[str], src: str = ""):
    """Schema + B/E + monotonicity checks over one event list. Returns
    ``(tracks, spans, flows)``: the track-name map {(pid, tid): name}, the
    closed span intervals [(track, name, ts_b, ts_e)], and the flow events
    [(id, ph, track, ts)] for the flow checks."""
    tracks: Dict[Track, str] = {}
    spans: List[Tuple[Track, str, float, float]] = []
    flows: List[Tuple[object, str, Track, float]] = []
    if not isinstance(events, list):
        errors.append(f"{src}: traceEvents is not a list")
        return tracks, spans, flows
    stacks: Dict[Track, List[Tuple[str, float]]] = {}
    last_ts: Dict[Track, float] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"{src}: event #{i} is not an object")
            continue
        for key in ("ph", "name", "pid", "tid"):
            if key not in ev:
                errors.append(f"{src}: event #{i} missing required key '{key}'")
        ph = ev.get("ph")
        tid_key = (ev.get("pid", 0), ev.get("tid", 0))
        if ph == "M":
            if ev.get("name") == "thread_name":
                tracks[tid_key] = str(ev.get("args", {}).get("name", ""))
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            errors.append(f"{src}: event #{i} ({ev.get('name')!r}) has no "
                          "numeric 'ts'")
            continue
        prev = last_ts.get(tid_key)
        if prev is not None and ts < prev:
            errors.append(f"{src}: track {tid_key} ts not monotonic at event "
                          f"#{i} ({ev.get('name')!r}): {ts} < {prev}")
        last_ts[tid_key] = ts
        if ph == "B":
            stacks.setdefault(tid_key, []).append((str(ev.get("name")), ts))
        elif ph == "E":
            stack = stacks.setdefault(tid_key, [])
            if not stack:
                errors.append(f"{src}: track {tid_key} has 'E' "
                              f"({ev.get('name')!r}) with no open 'B'")
            elif stack[-1][0] != ev.get("name"):
                errors.append(f"{src}: track {tid_key} 'E' {ev.get('name')!r} "
                              f"does not match open 'B' {stack[-1][0]!r}")
            else:
                name, ts_b = stack.pop()
                spans.append((tid_key, name, ts_b, ts))
        elif ph == "C":
            args = ev.get("args", {})
            if not args or not all(isinstance(v, (int, float))
                                   for v in args.values()):
                errors.append(f"{src}: counter #{i} ({ev.get('name')!r}) "
                              "lacks numeric args")
        elif ph in ("s", "t", "f"):
            if "id" not in ev:
                errors.append(f"{src}: flow event #{i} ({ph!r}) has no 'id'")
            else:
                flows.append((ev["id"], ph, tid_key, float(ts)))
        elif ph not in ("i", "X"):
            errors.append(f"{src}: event #{i} has unknown phase {ph!r}")
    for tid_key, stack in stacks.items():
        if stack:
            errors.append(f"{src}: track {tid_key} left unmatched 'B' events: "
                          f"{[n for n, _ in stack]}")
    return tracks, spans, flows


def check_flows(flows, spans, tracks, errors: List[str], src: str = ""):
    """Flow-chain validation over one file. Returns ``{flow id: (bound
    track keys, bound span/track names)}`` for the --require-flows gate."""
    by_track: Dict[Track, List[Tuple[float, float, str]]] = {}
    for tid_key, name, b, e in spans:
        by_track.setdefault(tid_key, []).append((b, e, name))
    chains: Dict[object, List[Tuple[float, str, Track]]] = {}
    for fid, ph, tid_key, ts in flows:
        chains.setdefault(fid, []).append((ts, ph, tid_key))
    info: Dict[object, Tuple[Set[Track], Set[str]]] = {}
    for fid, evs in chains.items():
        phs = [p for _, p, _ in evs]
        n_s, n_f = phs.count("s"), phs.count("f")
        if n_s != 1 or n_f != 1:
            errors.append(f"{src}: flow id {fid} has {n_s} 's' and {n_f} "
                          "'f' events (need exactly one of each)")
            continue
        ts_s = next(ts for ts, p, _ in evs if p == "s")
        ts_f = next(ts for ts, p, _ in evs if p == "f")
        if ts_f < ts_s:
            errors.append(f"{src}: flow id {fid} is BACKWARDS: "
                          f"f at {ts_f} < s at {ts_s}")
            continue
        bad_steps = [ts for ts, p, _ in evs if p == "t"
                     and not ts_s <= ts <= ts_f]
        if bad_steps:
            errors.append(f"{src}: flow id {fid} has step events outside "
                          f"[{ts_s}, {ts_f}]: {bad_steps}")
        bound_tracks: Set[Track] = set()
        bound_names: Set[str] = set()
        for ts, ph, tid_key in evs:
            hit = [name for b, e, name in by_track.get(tid_key, ())
                   if b <= ts <= e]
            if not hit:
                errors.append(f"{src}: flow id {fid} '{ph}' at {ts} on track "
                              f"{tid_key} binds to no span (dangling)")
                continue
            bound_tracks.add(tid_key)
            bound_names.update(hit)
            bound_names.add(tracks.get(tid_key, ""))
        info[fid] = (bound_tracks, bound_names)
    return info


def check_file(path: str, errors: List[str]):
    """Returns (events, tracks, spans, flow_info) after recording errors."""
    src = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        errors.append(f"{src}: unreadable/unparseable: {e}")
        return [], {}, [], {}
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        errors.append(f"{src}: missing top-level 'traceEvents'")
        return [], {}, [], {}
    events = doc["traceEvents"]
    tracks, spans, flows = check_events(events, errors, src=src)
    flow_info = check_flows(flows, spans, tracks, errors, src=src)
    return events, tracks, spans, flow_info


def span_names_by_track(events: list) -> Dict[Track, Set[str]]:
    out: Dict[Track, Set[str]] = {}
    for ev in events:
        if isinstance(ev, dict) and ev.get("ph") in ("B", "X"):
            key = (ev.get("pid", 0), ev.get("tid", 0))
            out.setdefault(key, set()).add(str(ev.get("name")))
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("target", help="a trace JSON file or a directory of them")
    ap.add_argument("--require", nargs="*", default=[],
                    help="span-name/track prefixes that must each be present, "
                         "on at least as many distinct tracks as prefixes")
    ap.add_argument("--require-flows", nargs="*", default=[],
                    help="prefixes that must each be touched by a CROSS-LANE "
                         "flow chain (>= 2 distinct bound tracks)")
    ap.add_argument("--expect-crash", action="store_true",
                    help="require a parseable trace_crash.json in the dir")
    ap.add_argument("--min-spans", type=int, default=1,
                    help="minimum total spans across the checked files")
    args = ap.parse_args()

    if os.path.isdir(args.target):
        paths = sorted(glob.glob(os.path.join(args.target, "trace*.json")))
        crash = os.path.join(args.target, "trace_crash.json")
    else:
        paths = [args.target]
        crash = os.path.join(os.path.dirname(args.target) or ".",
                             "trace_crash.json")
    if not paths:
        print(f"trace_check: no trace*.json under {args.target}")
        return 1

    errors: List[str] = []
    total_spans = 0
    total_flows = 0
    # (file, pid, tid) -> set of span names; track names per the same key
    span_map: Dict[Tuple[str, int, int], Set[str]] = {}
    track_names: Dict[Tuple[str, int, int], str] = {}
    flow_infos: List[Tuple[Set[Track], Set[str]]] = []
    for path in paths:
        events, tracks, _spans, flow_info = check_file(path, errors)
        by_track = span_names_by_track(events)
        for (pid, tid), names in by_track.items():
            key = (path, pid, tid)
            span_map[key] = names
            track_names[key] = tracks.get((pid, tid), "")
            total_spans += len(names)
        flow_infos.extend(flow_info.values())
        total_flows += len(flow_info)

    if total_spans < args.min_spans:
        errors.append(f"only {total_spans} distinct span names across "
                      f"{len(paths)} file(s); expected >= {args.min_spans}")

    if args.require:
        matched_tracks: Set[Tuple[str, int, int]] = set()
        for prefix in args.require:
            hits = {key for key, names in span_map.items()
                    if any(n.startswith(prefix) for n in names)
                    or track_names.get(key, "").startswith(prefix)}
            if not hits:
                errors.append(f"required subsystem prefix {prefix!r} matched "
                              "no spans in any checked trace")
            matched_tracks |= hits
        if len(matched_tracks) < len(args.require):
            errors.append(
                f"required subsystems span only {len(matched_tracks)} "
                f"distinct tracks; expected >= {len(args.require)}")

    for prefix in args.require_flows:
        if not any(len(tracks_) >= 2
                   and any(n.startswith(prefix) for n in names)
                   for tracks_, names in flow_infos):
            errors.append(f"--require-flows: no cross-lane flow chain "
                          f"(>= 2 bound tracks) touches prefix {prefix!r}")

    if args.expect_crash:
        if not os.path.exists(crash):
            errors.append(f"--expect-crash: {crash} does not exist")
        else:
            crash_errors: List[str] = []
            events, *_ = check_file(crash, crash_errors)
            n_spans = sum(1 for ev in events
                          if isinstance(ev, dict) and ev.get("ph") == "B")
            if crash_errors:
                errors.extend(crash_errors)
            elif n_spans == 0:
                errors.append(f"{os.path.basename(crash)}: flight recorder "
                              "dump contains no spans")

    if errors:
        for err in errors:
            print(f"trace_check: {err}")
        print(f"trace_check: FAIL ({len(errors)} error(s) across "
              f"{len(paths)} file(s))")
        return 1
    print(f"trace_check: OK — {len(paths)} file(s), {total_spans} distinct "
          f"span names, {len(span_map)} tracks, {total_flows} flow chains"
          + (", crash dump present" if args.expect_crash else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
