#!/usr/bin/env python
"""Validate emitted span traces against the Chrome-trace schema.

Run over a trace file or a ``DSTPU_TRACE`` directory (every ``trace*.json``
inside)::

    python scripts/trace_check.py <file-or-dir> \
        [--require train serve ckpt train/offload] [--expect-crash]

Checks per file:

- the JSON parses and carries a ``traceEvents`` list;
- every event has the required keys (``ph``/``name``/``pid``/``tid``, plus
  ``ts`` for non-metadata events) with sane types;
- per (pid, tid) track: timestamps are MONOTONIC (non-decreasing) and every
  ``B`` has a matching ``E`` (same name, LIFO order) — i.e. spans nest;
- counter events carry numeric args.

``--require <prefix>...`` additionally asserts (across ALL checked files
together) that each prefix matches at least one span, and that the matched
spans cover at least as many DISTINCT tracks as there are prefixes — the
"spans from N subsystems on distinct tracks" acceptance gate.

``--expect-crash`` asserts a parseable ``trace_crash.json`` (the flight
recorder's dump) exists in the directory and contains at least one span.

Exit 0 on success; 1 with a per-file error listing otherwise. Invoked
non-fatally from ``scripts/bench_smoke.sh`` after the traced bench legs
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Set, Tuple


def check_events(events: list, errors: List[str], src: str = "") -> Dict[Tuple[int, int], str]:
    """Schema + B/E + monotonicity checks over one event list. Returns the
    track-name map {(pid, tid): name} for subsystem coverage checks."""
    if not isinstance(events, list):
        errors.append(f"{src}: traceEvents is not a list")
        return {}
    tracks: Dict[Tuple[int, int], str] = {}
    stacks: Dict[Tuple[int, int], List[str]] = {}
    last_ts: Dict[Tuple[int, int], float] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"{src}: event #{i} is not an object")
            continue
        for key in ("ph", "name", "pid", "tid"):
            if key not in ev:
                errors.append(f"{src}: event #{i} missing required key '{key}'")
        ph = ev.get("ph")
        tid_key = (ev.get("pid", 0), ev.get("tid", 0))
        if ph == "M":
            if ev.get("name") == "thread_name":
                tracks[tid_key] = str(ev.get("args", {}).get("name", ""))
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            errors.append(f"{src}: event #{i} ({ev.get('name')!r}) has no "
                          "numeric 'ts'")
            continue
        prev = last_ts.get(tid_key)
        if prev is not None and ts < prev:
            errors.append(f"{src}: track {tid_key} ts not monotonic at event "
                          f"#{i} ({ev.get('name')!r}): {ts} < {prev}")
        last_ts[tid_key] = ts
        if ph == "B":
            stacks.setdefault(tid_key, []).append(str(ev.get("name")))
        elif ph == "E":
            stack = stacks.setdefault(tid_key, [])
            if not stack:
                errors.append(f"{src}: track {tid_key} has 'E' "
                              f"({ev.get('name')!r}) with no open 'B'")
            elif stack[-1] != ev.get("name"):
                errors.append(f"{src}: track {tid_key} 'E' {ev.get('name')!r} "
                              f"does not match open 'B' {stack[-1]!r}")
            else:
                stack.pop()
        elif ph == "C":
            args = ev.get("args", {})
            if not args or not all(isinstance(v, (int, float))
                                   for v in args.values()):
                errors.append(f"{src}: counter #{i} ({ev.get('name')!r}) "
                              "lacks numeric args")
        elif ph not in ("i", "X"):
            errors.append(f"{src}: event #{i} has unknown phase {ph!r}")
    for tid_key, stack in stacks.items():
        if stack:
            errors.append(f"{src}: track {tid_key} left unmatched 'B' events: "
                          f"{stack}")
    return tracks


def span_names_by_track(events: list, tracks: Dict[Tuple[int, int], str]
                        ) -> Dict[Tuple[int, int], Set[str]]:
    out: Dict[Tuple[int, int], Set[str]] = {}
    for ev in events:
        if isinstance(ev, dict) and ev.get("ph") in ("B", "X"):
            key = (ev.get("pid", 0), ev.get("tid", 0))
            out.setdefault(key, set()).add(str(ev.get("name")))
    return out


def check_file(path: str, errors: List[str]):
    """Returns (events, tracks) or ([], {}) after recording errors."""
    src = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        errors.append(f"{src}: unreadable/unparseable: {e}")
        return [], {}
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        errors.append(f"{src}: missing top-level 'traceEvents'")
        return [], {}
    events = doc["traceEvents"]
    tracks = check_events(events, errors, src=src)
    return events, tracks


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("target", help="a trace JSON file or a directory of them")
    ap.add_argument("--require", nargs="*", default=[],
                    help="span-name/track prefixes that must each be present, "
                         "on at least as many distinct tracks as prefixes")
    ap.add_argument("--expect-crash", action="store_true",
                    help="require a parseable trace_crash.json in the dir")
    ap.add_argument("--min-spans", type=int, default=1,
                    help="minimum total spans across the checked files")
    args = ap.parse_args()

    if os.path.isdir(args.target):
        paths = sorted(glob.glob(os.path.join(args.target, "trace*.json")))
        crash = os.path.join(args.target, "trace_crash.json")
    else:
        paths = [args.target]
        crash = os.path.join(os.path.dirname(args.target) or ".",
                             "trace_crash.json")
    if not paths:
        print(f"trace_check: no trace*.json under {args.target}")
        return 1

    errors: List[str] = []
    total_spans = 0
    # (file, pid, tid) -> set of span names; track names per the same key
    span_map: Dict[Tuple[str, int, int], Set[str]] = {}
    track_names: Dict[Tuple[str, int, int], str] = {}
    for path in paths:
        events, tracks = check_file(path, errors)
        by_track = span_names_by_track(events, tracks)
        for (pid, tid), names in by_track.items():
            key = (path, pid, tid)
            span_map[key] = names
            track_names[key] = tracks.get((pid, tid), "")
            total_spans += len(names)

    if total_spans < args.min_spans:
        errors.append(f"only {total_spans} distinct span names across "
                      f"{len(paths)} file(s); expected >= {args.min_spans}")

    if args.require:
        matched_tracks: Set[Tuple[str, int, int]] = set()
        for prefix in args.require:
            hits = {key for key, names in span_map.items()
                    if any(n.startswith(prefix) for n in names)
                    or track_names.get(key, "").startswith(prefix)}
            if not hits:
                errors.append(f"required subsystem prefix {prefix!r} matched "
                              "no spans in any checked trace")
            matched_tracks |= hits
        if len(matched_tracks) < len(args.require):
            errors.append(
                f"required subsystems span only {len(matched_tracks)} "
                f"distinct tracks; expected >= {len(args.require)}")

    if args.expect_crash:
        if not os.path.exists(crash):
            errors.append(f"--expect-crash: {crash} does not exist")
        else:
            crash_errors: List[str] = []
            events, _ = check_file(crash, crash_errors)
            n_spans = sum(1 for ev in events
                          if isinstance(ev, dict) and ev.get("ph") == "B")
            if crash_errors:
                errors.extend(crash_errors)
            elif n_spans == 0:
                errors.append(f"{os.path.basename(crash)}: flight recorder "
                              "dump contains no spans")

    if errors:
        for err in errors:
            print(f"trace_check: {err}")
        print(f"trace_check: FAIL ({len(errors)} error(s) across "
              f"{len(paths)} file(s))")
        return 1
    print(f"trace_check: OK — {len(paths)} file(s), {total_spans} distinct "
          f"span names, {len(span_map)} tracks"
          + (", crash dump present" if args.expect_crash else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
