#!/usr/bin/env python
"""Per-request waterfall: where did ONE request's latency go?

Reads span traces (a ``DSTPU_TRACE`` directory, a single ``trace_*.json``,
or a ``trace_merge.py`` output), collects every span carrying a
``trace_id`` arg — the request-flow chain the serving stack stamps at
submit and threads through router placement, prefill, KV handoff, decode
stints, preemption/restore and failover migration — and renders the chain
as an ASCII waterfall plus a per-phase attribution summary (the offline
twin of ``RequestHandle.timeline()``; docs/OBSERVABILITY.md "SLO-miss
attribution")::

    python scripts/request_autopsy.py /tmp/run_traces --trace-id 1048577
    python scripts/request_autopsy.py /tmp/run_traces          # worst chain
    python scripts/request_autopsy.py "$DSTPU_TRACE" --smoke   # CI gate

With no ``--trace-id``, the WORST chain (largest submit-to-last-hop
window) is picked — on an SLO-investigation that is usually the request
you want. ``--list`` prints every chain's window instead. ``--smoke``
(wired into ``scripts/bench_smoke.sh``) asserts at least one multi-hop
chain exists in the traces and renders the worst one; exit 1 otherwise.

Timestamps are clock-aligned across files via the exporters' ``clockSync``
anchors (the same correction ``trace_merge.py`` applies), so a chain whose
hops span subprocess workers still renders as one causal timeline.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

BAR_WIDTH = 44


class Hop:
    __slots__ = ("name", "track", "t0", "t1", "args")

    def __init__(self, name, track, t0, t1, args):
        self.name = name
        self.track = track
        self.t0 = t0
        self.t1 = t1
        self.args = args


def collect(paths: List[str]) -> Dict[object, List[Hop]]:
    """{trace_id: [hops]} across the given files, clock-aligned."""
    chains: Dict[object, List[Hop]] = {}
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"request_autopsy: skipping {path}: {e}", file=sys.stderr)
            continue
        events = doc.get("traceEvents") or []
        sync = doc.get("clockSync") or {}
        off = (float(sync["unix_us"]) - float(sync["perf_us"])
               if "unix_us" in sync and "perf_us" in sync else 0.0)
        tracks: Dict[Tuple[int, int], str] = {}
        stacks: Dict[Tuple[int, int], list] = {}
        for ev in events:
            if not isinstance(ev, dict):
                continue
            ph = ev.get("ph")
            key = (ev.get("pid", 0), ev.get("tid", 0))
            if ph == "M":
                if ev.get("name") == "thread_name":
                    tracks[key] = str(ev.get("args", {}).get("name", ""))
            elif ph == "B":
                stacks.setdefault(key, []).append(ev)
            elif ph == "E":
                stack = stacks.get(key)
                if not stack:
                    continue
                b = stack.pop()
                args = b.get("args") or {}
                tid_val = args.get("trace_id")
                if tid_val is None:
                    continue
                chains.setdefault(tid_val, []).append(
                    Hop(str(b.get("name")), tracks.get(key, str(key)),
                        float(b.get("ts", 0.0)) + off,
                        float(ev.get("ts", 0.0)) + off, args))
    for hops in chains.values():
        hops.sort(key=lambda h: (h.t0, h.t1))
    return chains


def render(trace_id, hops: List[Hop]) -> str:
    t_min = min(h.t0 for h in hops)
    t_max = max(h.t1 for h in hops)
    window = max(t_max - t_min, 1e-9)
    cls = next((h.args.get("cls") for h in hops if "cls" in h.args), None)
    lines = [f"request autopsy — trace_id {trace_id}"
             + (f" (class {cls})" if cls else ""),
             f"window: {window / 1e3:.2f} ms over {len(hops)} hops "
             f"on {len({h.track for h in hops})} lanes", ""]
    name_w = max(len(h.name) for h in hops)
    track_w = max(len(h.track) for h in hops)
    for h in hops:
        lo = int(BAR_WIDTH * (h.t0 - t_min) / window)
        hi = max(lo + 1, int(round(BAR_WIDTH * (h.t1 - t_min) / window)))
        bar = " " * lo + "#" * (hi - lo)
        lines.append(f"  {h.name:<{name_w}}  {h.track:<{track_w}}  "
                     f"{(h.t0 - t_min) / 1e3:9.2f} ms  "
                     f"{(h.t1 - h.t0) / 1e3:9.2f} ms  |{bar:<{BAR_WIDTH}}|")
    # per-phase attribution: serve/req/* stints summed by phase (the
    # offline ledger view; cross-lane control spans are listed, not
    # summed). serve/req/handoff is import WORK nested inside its
    # enclosing handoff_wait/migration stint on the same lane — summing
    # it too would double-count the overlap, so it stays a hop row only.
    phases: Dict[str, float] = {}
    for h in hops:
        if h.name.startswith("serve/req/") and h.name != "serve/req/handoff":
            phases[h.name[len("serve/req/"):]] = \
                phases.get(h.name[len("serve/req/"):], 0.0) + (h.t1 - h.t0)
    if phases:
        total = sum(phases.values())
        lines.append("")
        lines.append(f"  phase attribution ({total / 1e3:.2f} ms attributed):")
        for phase, us in sorted(phases.items(), key=lambda kv: -kv[1]):
            lines.append(f"    {phase:<14} {us / 1e3:9.2f} ms  "
                         f"{100.0 * us / total:5.1f}%")
        dom = max(phases, key=lambda p: phases[p])
        lines.append(f"    dominant phase: {dom}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("target", help="trace JSON file or DSTPU_TRACE directory")
    ap.add_argument("--trace-id", type=int, default=None,
                    help="autopsy this request (default: the worst chain)")
    ap.add_argument("--list", action="store_true",
                    help="list every chain's window instead of rendering one")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: require >= 1 multi-hop chain, render the "
                         "worst")
    args = ap.parse_args()

    if os.path.isdir(args.target):
        # skip the merged file (its events duplicate the inputs) and the
        # crash dump (a mid-run snapshot of the same rings the final
        # trace_<pid>.json re-exports — including it double-counts stints)
        paths = sorted(
            p for p in glob.glob(os.path.join(args.target, "trace*.json"))
            if os.path.basename(p) not in ("trace_merged.json",
                                           "trace_crash.json"))
    else:
        paths = [args.target]
    if not paths:
        print(f"request_autopsy: no trace*.json under {args.target}")
        return 1
    chains = collect(paths)
    if args.trace_id is not None:
        hops = chains.get(args.trace_id)
        if not hops:
            print(f"request_autopsy: no spans carry trace_id "
                  f"{args.trace_id} (known: {sorted(chains)[:20]}...)")
            return 1
        print(render(args.trace_id, hops))
        return 0
    if not chains:
        print("request_autopsy: no request chains (spans with a trace_id "
              "arg) in the given traces")
        return 1
    windows = {tid: max(h.t1 for h in hops) - min(h.t0 for h in hops)
               for tid, hops in chains.items()}
    if args.list:
        for tid in sorted(windows, key=lambda t: -windows[t]):
            hops = chains[tid]
            print(f"  trace_id {tid}: {windows[tid] / 1e3:9.2f} ms, "
                  f"{len(hops)} hops, "
                  f"{len({h.track for h in hops})} lanes")
        return 0
    if args.smoke:
        multi = {tid for tid, hops in chains.items() if len(hops) >= 2}
        if not multi:
            print("request_autopsy: SMOKE FAIL — no multi-hop request "
                  "chain in the traces")
            return 1
        worst = max(multi, key=lambda t: windows[t])
        print(render(worst, chains[worst]))
        print(f"\nrequest_autopsy: smoke OK — {len(chains)} chains, "
              f"{len(multi)} multi-hop")
        return 0
    worst = max(windows, key=lambda t: windows[t])
    print(render(worst, chains[worst]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
