#!/usr/bin/env python
"""Clock-align and merge per-process trace files into one timeline.

Subprocess bench workers (and any multi-process run) each export their own
``trace_<pid>.json`` with timestamps from their OWN ``time.perf_counter()``
epoch — loading two of them into Perfetto shows two unrelated time axes.
Each exporter embeds a ``clockSync`` anchor (one simultaneous
``(perf_counter, unix time)`` pair, microseconds); this script shifts every
file's events onto the shared wall-clock axis, rebases the merged timeline
to start near zero, stitches request-flow chains that CROSS files (a flow
id seen in several files gets exactly one global ``s`` at its earliest hop
and one ``f`` at its latest — per-file chain ends become steps), and writes
one merged Chrome-trace JSON::

    python scripts/trace_merge.py <trace-dir> [-o merged.json]
    python scripts/trace_merge.py a.json b.json -o merged.json

Tracks cannot collide across files (each file's events carry its pid), and
per-track event ORDER is preserved (a constant shift keeps intra-file order
under the stable sort), so the merged file passes the same
``scripts/trace_check.py`` gates as its inputs — including the flow checks.
Files missing ``clockSync`` (pre-merge traces) merge UNSHIFTED with a
warning: correct only when they came from one process.

Caveat: flow ids are pid-prefixed per-process counters — unique across
the processes of one run, but pids (hence ids) recycle across machine
lifetimes, so merge one run's files at a time or chains from different
runs sharing an id may stitch together.

Exit 0 on success; the merged path prints on stdout.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Tuple

#: sort rank at equal timestamps: close-before-open keeps adjacent spans
#: nesting, metadata first, flows after the B they bind to (the same tie
#: discipline the exporter uses)
_PH_RANK = {"M": -1, "E": 0, "B": 1}


def load(path: str) -> Tuple[dict, List[dict]]:
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: missing traceEvents")
    return doc, events


def merge(paths: List[str]) -> dict:
    files: List[Tuple[str, dict, List[dict]]] = []
    for path in paths:
        doc, events = load(path)
        files.append((path, doc, events))
    # clock alignment: perf-based ts + (unix - perf) anchor = wall-clock us
    offsets: Dict[str, float] = {}
    for path, doc, _events in files:
        sync = doc.get("clockSync")
        if isinstance(sync, dict) and "perf_us" in sync and "unix_us" in sync:
            offsets[path] = float(sync["unix_us"]) - float(sync["perf_us"])
        else:
            offsets[path] = 0.0
            print(f"trace_merge: WARNING {os.path.basename(path)} has no "
                  "clockSync anchor; merging unshifted", file=sys.stderr)
    merged: List[Tuple[float, int, int, int, dict]] = []
    flow_events: Dict[object, List[int]] = {}   # id -> merged indices
    flow_files: Dict[object, set] = {}          # id -> source files
    idx = 0
    for fno, (path, _doc, events) in enumerate(files):
        off = offsets[path]
        for ev in events:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            if "ts" in ev and isinstance(ev["ts"], (int, float)):
                ev["ts"] = ev["ts"] + off
            ph = ev.get("ph")
            ts = ev.get("ts", float("-inf")) if ph != "M" else float("-inf")
            merged.append((ts, _PH_RANK.get(ph, 2), fno, idx, ev))
            if ph in ("s", "t", "f") and "id" in ev:
                flow_events.setdefault(ev["id"], []).append(len(merged) - 1)
                flow_files.setdefault(ev["id"], set()).add(fno)
            idx += 1
    # stitch cross-file chains: exactly one global s (earliest hop) and one
    # global f (latest); everything between becomes a step. Single-file
    # chains are already well-formed — leave them untouched.
    for fid, positions in flow_events.items():
        if len(flow_files.get(fid, ())) < 2:
            continue
        positions.sort(key=lambda p: (merged[p][0], merged[p][2],
                                      merged[p][3]))
        for k, p in enumerate(positions):
            ev = merged[p][4]
            if k == 0:
                ev["ph"] = "s"
                ev.pop("bp", None)
            elif k == len(positions) - 1:
                ev["ph"] = "f"
                ev["bp"] = "e"
            else:
                ev["ph"] = "t"
                ev.pop("bp", None)
    # stable order: ts, tie rank, then source order — intra-file relative
    # order of same-ts same-rank events is preserved (constant shift)
    merged.sort(key=lambda item: item[:4])
    events_out = [ev for _, _, _, _, ev in merged]
    # rebase near zero for readability (metadata events carry no ts)
    t0 = min((ev["ts"] for ev in events_out
              if isinstance(ev.get("ts"), (int, float))), default=0.0)
    for ev in events_out:
        if isinstance(ev.get("ts"), (int, float)):
            ev["ts"] = ev["ts"] - t0
    return {"traceEvents": events_out, "displayTimeUnit": "ms",
            "mergedFrom": [os.path.basename(p) for p in paths]}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("targets", nargs="+",
                    help="a trace directory (merges every trace_<pid>.json "
                         "inside) or explicit trace JSON files")
    ap.add_argument("-o", "--output", default=None,
                    help="merged output path (default: trace_merged.json "
                         "next to the inputs)")
    args = ap.parse_args()

    if len(args.targets) == 1 and os.path.isdir(args.targets[0]):
        d = args.targets[0]
        paths = sorted(p for p in glob.glob(os.path.join(d, "trace_*.json"))
                       if os.path.basename(p) not in ("trace_crash.json",
                                                      "trace_merged.json"))
        out = args.output or os.path.join(d, "trace_merged.json")
    else:
        paths = list(args.targets)
        out = args.output or os.path.join(
            os.path.dirname(paths[0]) or ".", "trace_merged.json")
    if not paths:
        print(f"trace_merge: no trace_*.json under {args.targets[0]}")
        return 1
    try:
        doc = merge(paths)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_merge: {e}")
        return 1
    with open(out, "w") as f:
        json.dump(doc, f)
    n = len(doc["traceEvents"])
    print(f"trace_merge: {len(paths)} file(s) -> {out} ({n} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
