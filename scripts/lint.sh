#!/usr/bin/env bash
# Fast pre-test gate (seconds, not minutes on this 2-core container):
#   1. compileall  — broken imports/syntax fail immediately
#   2. jaxlint     — jit/sharding/donation hazards (docs/JAXLINT.md)
# Run from anywhere; operates on the repo this script lives in.
set -euo pipefail
cd "$(dirname "$0")/.."

# pure host-side analysis: never let the lint step grab a TPU
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

python -m compileall -q deepspeed_tpu
python -m deepspeed_tpu.tools.jaxlint deepspeed_tpu
echo "lint: OK"
