#!/usr/bin/env bash
# Fast pre-test gate (seconds, not minutes on this 2-core container):
#   1. compileall  — broken imports/syntax fail immediately
#   2. jaxlint     — jit/sharding/donation hazards (docs/JAXLINT.md)
#   3. threadlint  — lock order / blocking-under-lock / cross-thread
#                    writes (docs/THREADLINT.md)
# The two linters run CONCURRENTLY — they are independent read-only
# analyses, and back-to-back they would blow the seconds budget on this
# 2-core container.
#
#   --changed   lint only the .py files the working tree touches vs HEAD
#               (tracked modifications + untracked files), compileall on
#               exactly those. jaxlint is per-file and gets just the
#               diff; threadlint is whole-program — role propagation and
#               the lock graph cross file boundaries — so ANY changed
#               .py still reruns it over the full tree.
# Run from anywhere; operates on the repo this script lives in.
set -euo pipefail
cd "$(dirname "$0")/.."

# pure host-side analysis: never let the lint step grab a TPU
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

run_both() {   # $1: jaxlint targets (newline-separated), both gates must pass
    local jl_rc=0 tl_rc=0
    xargs -d '\n' python -m deepspeed_tpu.tools.jaxlint <<<"$1" &
    local jl=$!
    python -m deepspeed_tpu.tools.threadlint deepspeed_tpu &
    local tl=$!
    wait "$jl" || jl_rc=$?
    wait "$tl" || tl_rc=$?
    return $(( jl_rc > tl_rc ? jl_rc : tl_rc ))
}

if [[ "${1:-}" == "--changed" ]]; then
    changed=$( { git diff --name-only --diff-filter=d HEAD -- '*.py';
                 git ls-files --others --exclude-standard -- '*.py'; } \
               | sort -u )
    if [[ -z "$changed" ]]; then
        echo "lint: no changed .py files"
        echo "lint: OK"
        exit 0
    fi
    xargs -d '\n' python -m compileall -q <<<"$changed"
    run_both "$changed"
    echo "lint: OK (changed: $(wc -l <<<"$changed") file(s))"
    exit 0
fi

python -m compileall -q deepspeed_tpu
run_both "deepspeed_tpu"
echo "lint: OK"
