#!/usr/bin/env bash
# Bench smoke: keeps the serving (serving_bench.py --steady-state) and
# training (train_bench.py) pipeline legs RUNNABLE on a CPU-only box (tiny
# models, tiny sizes, <60 s each warm) so neither can rot between hardware
# rounds.
#
# Exit status reflects the legs' own correctness gates (serving:
# byte-identical greedy streams + one-token-row per-step transfer; training:
# byte-identical loss streams + zero warm-loop compiles). Throughput numbers
# at these sizes are smoke, not signal — real numbers come from the full legs
# (docs/SERVING.md, docs/TRAINING.md). tier1.sh invokes this NON-FATALLY
# after pytest.
#
# Every leg runs with span tracing ON (DSTPU_TRACE -> docs/OBSERVABILITY.md),
# so the byte-equality / zero-recompile gates double as "tracing changes
# nothing" gates; trace_check.py then validates the emitted timelines —
# Chrome-trace schema, four subsystems on distinct tracks, and the --preempt
# kill's flight-recorder dump.
set -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

TRACE_DIR="$(mktemp -d "${TMPDIR:-/tmp}/dstpu_trace.XXXXXX")"
trap 'rm -rf "$TRACE_DIR"' EXIT
export DSTPU_TRACE="$TRACE_DIR"

timeout -k 10 300 python benchmarks/serving_bench.py --steady-state \
    --seqs 4 --prompt 16 --gen 24 || exit 1

# SLO-aware frontend leg (docs/SERVING.md "Frontend"): a few dozen Poisson
# arrivals against the persistent server, gating stream byte-equality vs
# direct pipeline runs, zero steady-state compiles, and one forced
# preempt-offload-restore cycle; emits serve/req per-request trace lanes
timeout -k 10 300 python benchmarks/serving_bench.py --frontend --smoke \
    || exit 1

# quantized-KV leg (docs/SERVING.md "Quantized KV"): the same seeded
# Poisson workload against an fp32 pool and an int8 pool sized from ONE
# byte budget, both with prefix cache AND spec decode enabled — gating
# byte-identical quantized streams across cache-hit / spec-on-off /
# preempt-offload-restore paths, zero timed compiles, and the bytes/token
# + pool-blocks capacity drop (goodput medians gate full-size, BENCH_r15)
timeout -k 10 600 python benchmarks/serving_bench.py --frontend --smoke \
    --kv-dtype int8 || exit 1

# speculative-decoding leg (docs/SERVING.md "Speculative decoding"):
# spec-off DecodePipeline vs draft-and-verify SpecDecodePipeline on one
# warmed engine, gating byte-identical greedy streams, zero compiles across
# the (bucket, k) verify grid, and allocator blocks back to baseline after
# reject-heavy runs; emits serve/spec trace lanes (smoke: correctness
# gates only — the >=1.5x repetitive-leg ratio runs full-size, BENCH_r12)
timeout -k 10 300 python benchmarks/serving_bench.py --spec --smoke \
    --spec-k 7 || exit 1

# flash-decoding long-context leg (docs/SERVING.md "Attention kernels"):
# few sequences x long ctx on ONE engine warmed across the pow2 split
# ladder — split=1 (chunk-serial) vs auto rung selection, gating identical
# token streams, zero timed compiles, allocator baseline and ladder
# engagement; emits the serve/attn rung-selection trace lane trace_check
# requires below (the >=1.3x op-level split-K bar runs full-size,
# BENCH_r17)
timeout -k 10 300 python benchmarks/serving_bench.py --long-context \
    --smoke || exit 1

# multi-replica router leg (docs/SERVING.md "Multi-replica &
# disaggregation"): 2 replicas behind a ServingRouter on a seeded
# shared-prefix Poisson stream, correctness gates only — every checked
# stream byte-identical to a direct single-frontend run, at least one
# forced prefill->decode KV handoff over the page fabric, zero
# steady-state compiles on every replica; emits serve/router trace lanes.
# DSTPU_LOCKSAN=1 arms the runtime lock-order sanitizer
# (docs/THREADLINT.md): the leg additionally gates zero observed
# acquisition cycles and static-graph coverage of every observed edge,
# with the byte-equality / zero-compile gates unchanged — the sanitized
# locks must not alter behavior
DSTPU_LOCKSAN=1 timeout -k 10 300 \
    python benchmarks/serving_bench.py --router --smoke || exit 1

# multi-tenant LoRA leg (docs/SERVING.md "Multi-tenant LoRA"): a seeded
# Poisson mix drawing tenants from more registered adapters than the
# adapter pool holds — correctness gates only (byte-identical mixed-batch
# streams vs direct per-adapter runs, zero compiles across adapter churn,
# allocator + adapter pool at baseline; the >=1.5x goodput-vs-naive gate
# runs full-size, BENCH_r18); the cold-adapter fault-ins emit the
# serve/lora trace lane trace_check requires below
timeout -k 10 300 python benchmarks/serving_bench.py --lora --smoke \
    || exit 1

# fault-tolerance leg (docs/SERVING.md "Failure semantics"): 2 replicas
# behind a health-monitored router replay a seeded Poisson stream while
# fault injection kills one serving loop and stalls the other — gating
# byte-identical non-shed streams vs uninterrupted references, detection of
# both failure modes, migration, self-healing rejoin with zero compiles,
# and allocator baseline on every replica; the injected raise also leaves
# the flight-recorder dump trace_check verifies below. Runs lock-order
# sanitized (DSTPU_LOCKSAN=1) — the failover/rejoin storm is the stack's
# richest locking workload, and the injected raise's crash dump carries
# the locksan report (docs/OBSERVABILITY.md)
DSTPU_LOCKSAN=1 timeout -k 10 300 \
    python benchmarks/serving_bench.py --chaos --smoke || exit 1

timeout -k 10 300 python benchmarks/train_bench.py --smoke || exit 1

# offloaded-optimizer pipeline leg: serial vs overlapped host step through
# the same engine, gating byte-identical loss streams + zero warm compiles
timeout -k 10 300 python benchmarks/train_bench.py --smoke --offload || exit 1

# preemption-tolerance leg (docs/ELASTICITY.md): kill a subprocess run at a
# non-checkpoint step AND mid-checkpoint-write, resume each onto a different
# simulated device count, gating byte-identical resumed loss streams + torn
# checkpoint fallback + zero post-resume-warmup compiles. The kills also
# exercise the tracer's flight recorder (trace_crash.json).
timeout -k 10 300 python benchmarks/train_bench.py --smoke --preempt || exit 1

# tracer-overhead leg: trace-off vs trace-on through the same pipelined
# loop; correctness gates here, the <=5% bar runs full-size (BENCH_r10)
timeout -k 10 300 python benchmarks/train_bench.py --smoke --trace-overhead \
    || exit 1

# ZeRO-3 collective-schedule leg (docs/TRAINING.md "ZeRO-3 collective
# schedule"): prefetch depth 0 vs 1/2 over an 8-way forced-host fsdp mesh —
# gating byte-identical loss streams across depths, zero timed compiles,
# and span-measured gather/compute overlap (zero at depth 0, nonzero at
# depth >= 1); emits the train/zero3 trace lanes trace_check requires below
# (the >=1.15x steps/sec bar applies on async-collective hardware, BENCH_r16)
timeout -k 10 300 python benchmarks/train_bench.py --smoke --zero3-overlap \
    || exit 1

# colocated-rollout leg (docs/TRAINING.md "Colocated rollout"): one
# train+serve pair on the same devices — the WeightBridge's device-resident
# reshard vs the universal-checkpoint round-trip (byte-equal weights),
# >=3 in-place swaps into a warmed engine (zero new compiles, post-swap
# greedy streams byte-identical to a freshly built engine, KV allocator at
# baseline), and the full RolloutLoop vs rebuild-per-update (byte-identical
# rollouts); emits the train/rollout trace lanes trace_check requires below
# (the >=5x sync bar runs full-size, BENCH_r19)
timeout -k 10 300 python benchmarks/rollout_bench.py --smoke || exit 1

# serving-side tracer/attribution overhead leg (docs/OBSERVABILITY.md):
# the same router workload with flow tracing + phase attribution ON vs
# OFF; correctness gates here (byte-identical streams, zero compiles),
# the <=2% bar runs full-size (BENCH_r16)
timeout -k 10 300 python benchmarks/serving_bench.py --trace-overhead \
    --smoke || exit 1

# the timelines the legs above emitted: schema-valid, spans from the train
# pipeline, decode pipeline, serving-frontend request lanes, speculative
# decode, multi-replica router, checkpoint, and offload subsystems on
# distinct tracks, cross-lane request flow chains (--require-flows: the
# router/chaos legs bind each request's hops by trace_id), plus a
# parseable flight-recorder dump from the --preempt kills
timeout -k 10 120 python scripts/trace_check.py "$TRACE_DIR" \
    --require train serve serve/req serve/spec serve/router serve/health \
    serve/lora serve/attn ckpt train/offload train/zero3 train/rollout \
    --require-flows serve/req \
    --expect-crash || exit 1

# clock-align + merge the per-process trace files into one timeline; the
# merged file must pass the same flow-aware checks (stitched chains keep
# exactly one s/f per id)
timeout -k 10 120 python scripts/trace_merge.py "$TRACE_DIR" \
    -o "$TRACE_DIR/trace_merged.json" || exit 1
timeout -k 10 120 python scripts/trace_check.py \
    "$TRACE_DIR/trace_merged.json" --require-flows serve/req || exit 1

# per-request waterfall over the emitted traces: at least one multi-hop
# request chain must exist and render (the SLO-miss debugging workflow,
# docs/OBSERVABILITY.md "SLO-miss attribution")
timeout -k 10 120 python scripts/request_autopsy.py "$TRACE_DIR" --smoke \
    || exit 1
