#!/usr/bin/env bash
# Bench smoke: keeps the serving (serving_bench.py --steady-state) and
# training (train_bench.py) pipeline legs RUNNABLE on a CPU-only box (tiny
# models, tiny sizes, <60 s each warm) so neither can rot between hardware
# rounds.
#
# Exit status reflects the legs' own correctness gates (serving:
# byte-identical greedy streams + one-token-row per-step transfer; training:
# byte-identical loss streams + zero warm-loop compiles). Throughput numbers
# at these sizes are smoke, not signal — real numbers come from the full legs
# (docs/SERVING.md, docs/TRAINING.md). tier1.sh invokes this NON-FATALLY
# after pytest.
set -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

timeout -k 10 300 python benchmarks/serving_bench.py --steady-state \
    --seqs 4 --prompt 16 --gen 24 || exit 1

timeout -k 10 300 python benchmarks/train_bench.py --smoke || exit 1

# offloaded-optimizer pipeline leg: serial vs overlapped host step through
# the same engine, gating byte-identical loss streams + zero warm compiles
timeout -k 10 300 python benchmarks/train_bench.py --smoke --offload || exit 1

# preemption-tolerance leg (docs/ELASTICITY.md): kill a subprocess run at a
# non-checkpoint step AND mid-checkpoint-write, resume each onto a different
# simulated device count, gating byte-identical resumed loss streams + torn
# checkpoint fallback + zero post-resume-warmup compiles
timeout -k 10 300 python benchmarks/train_bench.py --smoke --preempt
