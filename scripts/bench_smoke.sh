#!/usr/bin/env bash
# Steady-state serving-bench smoke: keeps benchmarks/serving_bench.py
# --steady-state RUNNABLE on a CPU-only box (tiny model, tiny sizes, <60 s
# warm) so the decode-pipeline leg can't rot between hardware rounds.
#
# Exit status reflects the leg's own correctness gates (byte-identical greedy
# streams between the per-token loop and the pipeline; one-token-row per-step
# transfer). Throughput numbers at these sizes are smoke, not signal — real
# numbers come from the full leg (docs/SERVING.md). tier1.sh invokes this
# NON-FATALLY after pytest.
set -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

timeout -k 10 300 python benchmarks/serving_bench.py --steady-state \
    --seqs 4 --prompt 16 --gen 24
