"""Serve a HuggingFace transformers model on TPU via the injection policies.

Mirrors the reference's flagship usage: ``deepspeed.init_inference(model,
tensor_parallel=...)`` over a HF torch model.  Here the per-architecture
policies (``module_inject/``) convert the torch weights logit-exactly to the
TPU model zoo (13 families: gpt2, bert, llama, mistral, mixtral, qwen2, opt,
falcon, phi, gpt_neox, gpt_neo, gptj, bloom), and the engine TP-shards them
over the mesh.

Run (uses a tiny random llama so it works without downloads):
    python examples/hf_inference.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp


def main():
    import torch
    import transformers

    import deepspeed_tpu

    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(transformers.LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=256))

    # exactly the reference call shape; accepts a model instance or local path
    engine = deepspeed_tpu.init_inference(
        hf_model, dtype="bf16",
        tensor_parallel={"tp_size": 1},
        replace_with_kernel_inject=True)   # accepted for parity; Pallas is default

    prompt = np.random.RandomState(0).randint(0, 512, size=(2, 16))
    out = engine.generate(jnp.asarray(prompt, jnp.int32), max_new_tokens=8)
    print("generated token ids:", np.asarray(out)[:, -8:].tolist())

    # v2 continuous-batching engine over the same converted weights
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.module_inject import convert_hf_model
    module, _cfg, variables = convert_hf_model(hf_model, dtype=jnp.bfloat16)
    v2 = InferenceEngineV2(model=module, model_parameters=variables["params"],
                           family="llama",
                           config={"state_manager": {
                               "max_tracked_sequences": 4,
                               "max_ragged_sequence_count": 4,
                               "max_ragged_batch_size": 64,
                               "max_context": 128}})
    outs = v2.generate([list(map(int, p)) for p in prompt], max_new_tokens=8)
    print("v2 continuous batching:", [o[-8:] for o in outs])


if __name__ == "__main__":
    main()
