"""Llama ZeRO-3 with hpZ + host-offloaded optimizer (ZeRO-Offload/Infinity).

    PYTHONPATH=. XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/zero3_offload_llama.py

Swap "device": "cpu" for {"device": "nvme", "nvme_path": "/tmp/nvme"} to spill
optimizer state to local SSD through the native async-I/O engine.
"""

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

CONFIG = {
    "train_batch_size": 8,
    "train_micro_batch_size_per_gpu": 1,
    "bf16": {"enabled": True},
    "zero_optimization": {
        "stage": 3,
        "zero_hpz_partition_size": 4,         # ZeRO++ secondary partition
        "offload_optimizer": {"device": "cpu"},
        "stage3_param_persistence_threshold": 0,
    },
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
    "mesh": {"data": 1, "fsdp": 8},
}


def main():
    model = LlamaForCausalLM(LlamaConfig.tiny(hidden_size=128,
                                              intermediate_size=256))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=CONFIG)
    rng = np.random.default_rng(0)
    for step in range(10):
        batch = {"input_ids": rng.integers(0, 256, (8, 32)).astype(np.int32)}
        loss = engine.train_batch(batch)
    print(f"final loss {float(loss):.4f} "
          f"(hpZ mesh: {dict(engine.topology.sizes)})")
    engine.destroy()


if __name__ == "__main__":
    main()
