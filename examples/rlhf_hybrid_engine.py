"""RLHF actor loop with the hybrid engine: generate rollouts, then train.

    PYTHONPATH=. XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/rlhf_hybrid_engine.py
"""

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

CONFIG = {
    "train_batch_size": 8,
    "train_micro_batch_size_per_gpu": 1,
    "bf16": {"enabled": True},
    "zero_optimization": {"stage": 3},
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
    "mesh": {"data": 1, "fsdp": 8},
    "hybrid_engine": {"enabled": True, "max_out_tokens": 64},
}


def main():
    model = LlamaForCausalLM(LlamaConfig.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=CONFIG)
    rng = np.random.default_rng(0)
    for rl_round in range(3):
        engine.eval()
        rollout = engine.generate(np.array([[1, 9, 4]], np.int32),
                                  max_new_tokens=8)
        engine.train()
        # (a real loop scores the rollout and builds a PPO batch here)
        loss = engine.train_batch(
            {"input_ids": rng.integers(0, 256, (8, 16)).astype(np.int32)})
        print(f"round {rl_round}: rollout {np.asarray(rollout).shape}, "
              f"loss {float(loss):.4f} "
              f"(gen {engine.generate_time*1e3:.0f}ms, "
              f"train {engine.train_time*1e3:.0f}ms)")


if __name__ == "__main__":
    main()
