"""TP-sharded inference with int8 weight-only quantization (init_inference).

    PYTHONPATH=. XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/inference_v1_tp.py
"""

import numpy as np
import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    model = LlamaForCausalLM(LlamaConfig.tiny(hidden_size=128,
                                              intermediate_size=256))
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    engine = ds.init_inference(model, model_parameters=params, config={
        "dtype": "float32",
        "tensor_parallel": {"tp_size": 2},
        "quant": {"enabled": True, "bits": 8, "group_size": 64},
    })
    out = engine.generate(np.array([[1, 17, 42]], np.int32), max_new_tokens=8)
    print("generated:", np.asarray(out).tolist())


if __name__ == "__main__":
    main()
