"""Mixtral-style MoE training with expert parallelism.

    PYTHONPATH=. XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/moe_mixtral.py
"""

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

CONFIG = {
    "train_batch_size": 8,
    "train_micro_batch_size_per_gpu": 2,
    "zero_optimization": {"stage": 1},
    "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
    "mesh": {"data": -1, "expert": 2},   # 2-way expert parallelism
}


def main():
    model = MixtralForCausalLM(MixtralConfig.tiny(num_local_experts=4,
                                                  num_experts_per_tok=2))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=CONFIG)
    rng = np.random.default_rng(0)
    for step in range(10):
        batch = {"input_ids": rng.integers(0, 256, (8, 32)).astype(np.int32)}
        loss = engine.train_batch(batch)
    print(f"final loss {float(loss):.4f} (includes router aux loss)")


if __name__ == "__main__":
    main()
