"""Train GPT-2 with ZeRO + bf16 (the minimum end-to-end slice).

Run (any host; 8 virtual devices make a test mesh):
    PYTHONPATH=. XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_gpt2_zero.py

DeepSpeed users: the config dict below is a DeepSpeed config — same keys.
"""

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

CONFIG = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "bf16": {"enabled": True},
    "zero_optimization": {"stage": 2},
    "optimizer": {"type": "AdamW", "params": {"lr": 3e-4, "weight_decay": 0.01}},
    "scheduler": {"type": "WarmupLR",
                  "params": {"warmup_min_lr": 0, "warmup_max_lr": 3e-4,
                             "warmup_num_steps": 10}},
    "gradient_clipping": 1.0,
    "steps_per_print": 5,
    "mesh": {"data": -1},  # absorb all devices into data parallelism
}


def main():
    model = GPT2LMHead(GPT2Config(vocab_size=1024, n_positions=128, n_embd=128,
                                  n_layer=4, n_head=4, remat=True))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=CONFIG)

    rng = np.random.default_rng(0)
    for step in range(20):
        batch = {"input_ids": rng.integers(0, 1024, (16, 128)).astype(np.int32)}
        loss = engine.train_batch(batch)
    engine.save_checkpoint("/tmp/gpt2_ckpt")
    print(f"final loss {float(loss):.4f}; checkpoint saved to /tmp/gpt2_ckpt")


if __name__ == "__main__":
    main()
