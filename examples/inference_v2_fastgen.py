"""FastGen-style continuous batching: paged KV + Dynamic SplitFuse.

    PYTHONPATH=. XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/inference_v2_fastgen.py
"""

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    model = LlamaForCausalLM(LlamaConfig.tiny())
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    # small blocks keep the demo snappy on the CPU Pallas interpreter; on a
    # real TPU the defaults (block_size 128) are the right shapes
    cfg = RaggedInferenceEngineConfig.load({
        "state_manager": {"max_tracked_sequences": 8,
                          "max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 64, "max_context": 64},
        "kv_cache": {"block_size": 8, "num_blocks": 64},
        # radix-tree prefix reuse: repeated system prompts / few-shot headers
        # skip prefill for every cached whole block (logit-exact)
        "prefix_cache": {"enabled": True},
    })
    engine = InferenceEngineV2(model=model, config=cfg, model_parameters=params)
    rng = np.random.default_rng(0)
    system = rng.integers(3, 250, (16,)).tolist()      # shared "system prompt"
    prompts = [system + rng.integers(3, 250, (n,)).tolist() for n in (5, 19, 11)]
    # first request warms the radix tree; the rest adopt the system prompt's
    # KV pages at admission (tokens_saved counts the skipped prefill)
    outs = engine.generate(prompts[:1], max_new_tokens=8)
    outs += engine.generate(prompts[1:], max_new_tokens=8)
    for i, o in enumerate(outs):
        print(f"seq {i}: {len(prompts[i])} prompt tokens -> "
              f"{len(o) - len(prompts[i])} new: {o[len(prompts[i]):]}")
    st = engine.prefix_cache.stats
    print(f"prefix cache: hit_rate={st.hit_rate:.2f} "
          f"tokens_saved={st.tokens_saved} evictions={st.evictions}")


if __name__ == "__main__":
    main()
