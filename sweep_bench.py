"""One-off perf sweep on the real chip (not part of the package)."""
import itertools
import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def run_one(bs, remat, policy, flash_min, steps=8, warmup=2):
    import deepspeed_tpu.ops.attention as att
    att.FLASH_MIN_SEQ = flash_min
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    cfg = GPT2Config(vocab_size=50257, n_positions=1024, n_embd=1024,
                     n_layer=24, n_head=16, dtype=jnp.bfloat16, remat=remat,
                     remat_policy=policy)
    seq = 1024
    model = GPT2LMHead(cfg)
    ds_config = {
        "train_batch_size": bs,
        "steps_per_print": 0,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
    }
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 50257, size=(bs, seq)).astype(np.int32)}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config)
    for _ in range(warmup):
        float(engine.train_batch(batch))
    t0 = time.perf_counter()
    for _ in range(steps):
        float(engine.train_batch(batch))
    dt = time.perf_counter() - t0
    return bs * seq * steps / dt


def main():
    combos = [
        # (bs, remat, policy, flash_min_seq)
        (32, True, None, 4096),              # current baseline
        (32, True, "dots_with_no_batch_dims_saveable", 4096),
        (48, True, "dots_with_no_batch_dims_saveable", 4096),
        (32, True, None, 1024),              # flash attention on
        (48, True, None, 1024),
        (64, True, None, 1024),
        (48, True, "dots_with_no_batch_dims_saveable", 1024),
        (64, True, "dots_with_no_batch_dims_saveable", 1024),
        (96, True, None, 1024),
        (32, False, None, 1024),   # 9: no remat, flash
        (48, False, None, 1024),   # 10
        (24, False, None, 1024),   # 11
        (64, True, "attn_out_saveable", 1024),  # 12
        (48, True, "attn_out_saveable", 1024),  # 13
        (64, True, "offload_attn_out", 1024),   # 14
        (80, True, None, 1024),                 # 15
    ]
    if len(sys.argv) > 1:
        sel = [int(x) for x in sys.argv[1].split(",")]
        combos = [combos[i] for i in sel]
    for bs, remat, policy, fmin in combos:
        try:
            tps = run_one(bs, remat, policy, fmin)
            print(json.dumps({"bs": bs, "remat": remat, "policy": policy,
                              "flash_min": fmin, "tok_s": round(tps, 1)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"bs": bs, "remat": remat, "policy": policy,
                              "flash_min": fmin,
                              "error": str(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
