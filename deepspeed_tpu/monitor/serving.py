"""Serving-pipeline observability: per-step timing/transfer counters.

The double-buffered decode pipeline (``inference/v2/pipeline.py``) overlaps
three things per generated token — the device step's dispatch, the host's
drain of the PREVIOUS step's token row, and the host-side build of the NEXT
step's descriptors. Whether that overlap actually happens is invisible from
throughput alone (a loop can hit its tokens/sec while secretly serialising),
so the pipeline accounts every step's wall time into the four phases below
and this module turns the totals into ``monitor/`` events
(``MonitorMaster.write_events`` ``(name, value, step)`` shape, the same
contract ``PrefixCacheStats.events`` follows).

These counters are per-window aggregations over the SAME measured intervals
the span tracer records as ``serve/decode/*`` timeline spans
(``monitor/trace.py``, docs/OBSERVABILITY.md): the pipeline takes one set of
``perf_counter`` pairs per step and feeds both, so the dashboard numbers and
the Perfetto trace can never disagree about what was measured.

Phase semantics (per step):

- ``dispatch``: host time spent enqueueing the fused decode program (jax
  async dispatch — this is NOT device execution time).
- ``fetch_drain``: host time blocked waiting for the previous step's token
  row to arrive. The transfer itself was started asynchronously right after
  that step's dispatch, so in a healthy host-bound loop this is ~0; it grows
  exactly when the device is the bottleneck (which is where you want to be).
- ``host_build``: scheduler bookkeeping + building the next step's
  descriptors (with pre-reserved KV blocks this is two array increments).
- ``bubble``: the step's wall time not attributed to the three phases above
  (callback work, GC, interpreter noise). Persistent growth here means the
  host loop — not the device or the transfer — is eating the pipeline.

``fetch_bytes`` counts exactly what crossed device->host per step; the
steady-state bench asserts it equals one int32 row per bucket slot
(4 * bucket bytes), the invariant that keeps decode transfer-bound work off
the per-token critical path.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from deepspeed_tpu.monitor.monitor import Event


@dataclass
class PipelineStats:
    """Aggregate counters for one engine's decode pipelines (cumulative
    across runs; ``reset()`` between measurement windows)."""

    steps: int = 0
    tokens: int = 0                  # live (recorded) tokens drained
    dispatch_ms: float = 0.0
    host_build_ms: float = 0.0
    fetch_drain_ms: float = 0.0
    bubble_ms: float = 0.0
    fetch_bytes: int = 0
    last_fetch_bytes: int = 0        # bytes of the most recent per-step drain
    #: per-step wall times (ms) of the MOST RECENT run only — the bench reads
    #: p50/p99 per-token latency from here; DecodePipeline.run clears it at
    #: run start (the scalar fields above stay cumulative)
    step_wall_ms: List[float] = field(default_factory=list)

    def record_step(self, dispatch_s: float, drain_s: float, build_s: float,
                    wall_s: float, fetch_bytes: int, live_tokens: int) -> None:
        self.steps += 1
        self.tokens += live_tokens
        self.dispatch_ms += 1e3 * dispatch_s
        self.fetch_drain_ms += 1e3 * drain_s
        self.host_build_ms += 1e3 * build_s
        self.bubble_ms += 1e3 * max(0.0, wall_s - dispatch_s - drain_s
                                    - build_s)
        self.fetch_bytes += int(fetch_bytes)
        self.last_fetch_bytes = int(fetch_bytes)
        self.step_wall_ms.append(1e3 * wall_s)

    def reset(self) -> None:
        self.steps = 0
        self.tokens = 0
        self.dispatch_ms = 0.0
        self.host_build_ms = 0.0
        self.fetch_drain_ms = 0.0
        self.bubble_ms = 0.0
        self.fetch_bytes = 0
        self.last_fetch_bytes = 0
        self.step_wall_ms = []

    @property
    def fetch_bytes_per_step(self) -> float:
        return self.fetch_bytes / self.steps if self.steps else 0.0

    def events(self, step: int = 0) -> List[Event]:
        """Monitor-ready ``(name, value, step)`` tuples; per-step averages so
        dashboards stay comparable across runs of different lengths."""
        n = max(1, self.steps)
        return [
            ("inference/v2/pipeline/steps", float(self.steps), step),
            ("inference/v2/pipeline/tokens", float(self.tokens), step),
            ("inference/v2/pipeline/dispatch_ms_per_step",
             self.dispatch_ms / n, step),
            ("inference/v2/pipeline/host_build_ms_per_step",
             self.host_build_ms / n, step),
            ("inference/v2/pipeline/fetch_drain_ms_per_step",
             self.fetch_drain_ms / n, step),
            ("inference/v2/pipeline/bubble_ms_per_step",
             self.bubble_ms / n, step),
            ("inference/v2/pipeline/fetch_bytes_per_step",
             float(self.fetch_bytes_per_step), step),
        ]


@dataclass
class SpecDecodeStats:
    """Aggregate counters for one engine's speculative-decode pipelines
    (``inference/v2/spec/pipeline.py``; cumulative across runs, ``reset()``
    between measurement windows). Per-window aggregations over the SAME
    measured intervals the tracer records as ``serve/spec/*`` spans — one
    set of perf pairs per step feeds both (docs/OBSERVABILITY.md).

    Semantics per verify step: ``proposed`` counts draft tokens offered,
    ``accepted`` the ones the verify forward confirmed, ``tokens`` what was
    actually emitted (accepted + one bonus token per live row); the
    acceptance rate is accepted/proposed and the amortization lever is
    tokens/steps — how many stream tokens each full-model forward pays for.
    ``draft_ms`` is host time in the n-gram proposer (the draft-match cost
    speculation adds to the host loop); ``verify_ms`` covers dispatch +
    the blocking accept-row drain (the spec step trades PR 3's one-step-late
    overlap for k-token amortization — the next draft needs this step's
    accepted tokens, so the drain cannot ride one step behind)."""

    steps: int = 0
    rows: int = 0                    # live rows scored across steps
    proposed: int = 0
    accepted: int = 0
    tokens: int = 0                  # emitted (accepted + bonus) tokens
    draft_ms: float = 0.0
    verify_ms: float = 0.0
    fetch_bytes: int = 0
    #: replica label (set by ``serving/cluster.py``): when not None, event
    #: names become ``serve/spec/<replica>/...`` so N replicas fanning into
    #: one monitor backend stay distinguishable (never cleared by reset())
    replica: Optional[str] = None

    def record_step(self, rows: int, proposed: int, accepted: int,
                    tokens: int, draft_s: float, verify_s: float,
                    fetch_bytes: int) -> None:
        self.steps += 1
        self.rows += rows
        self.proposed += proposed
        self.accepted += accepted
        self.tokens += tokens
        self.draft_ms += 1e3 * draft_s
        self.verify_ms += 1e3 * verify_s
        self.fetch_bytes += int(fetch_bytes)

    def reset(self) -> None:
        self.steps = 0
        self.rows = 0
        self.proposed = 0
        self.accepted = 0
        self.tokens = 0
        self.draft_ms = 0.0
        self.verify_ms = 0.0
        self.fetch_bytes = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    @property
    def tokens_per_step(self) -> float:
        return self.tokens / self.steps if self.steps else 0.0

    def events(self, step: int = 0) -> List[Event]:
        """``serve/spec/*`` monitor events (docs/SERVING.md glossary);
        replica-labelled (``serve/spec/<replica>/*``) under a cluster."""
        n = max(1, self.steps)
        pre = "serve/spec" if self.replica is None \
            else f"serve/spec/{self.replica}"
        return [
            (f"{pre}/steps", float(self.steps), step),
            (f"{pre}/proposed", float(self.proposed), step),
            (f"{pre}/accepted", float(self.accepted), step),
            (f"{pre}/tokens", float(self.tokens), step),
            (f"{pre}/acceptance_rate", self.acceptance_rate, step),
            (f"{pre}/tokens_per_step", self.tokens_per_step, step),
            (f"{pre}/draft_ms_per_step", self.draft_ms / n, step),
            (f"{pre}/verify_ms_per_step", self.verify_ms / n, step),
            (f"{pre}/fetch_bytes_per_step",
             self.fetch_bytes / n, step),
        ]


@dataclass
class AttnSplitStats:
    """Aggregate counters for the flash-decoding split ladder
    (``engine_v2._attn_rung``; docs/SERVING.md "Attention kernels").
    Per-window aggregations over the SAME ``perf_counter`` pairs the tracer
    records as ``serve/attn/select`` spans — one stamp pair per rung choice
    feeds both (docs/OBSERVABILITY.md), so the dashboard's selection-cost
    number and the timeline can never disagree.

    Semantics per selection: ``selects`` counts rung choices made on the
    hot path; ``splits`` sums the chosen rung so splits/select is the
    average grid-parallelism decode ran at; ``merged_steps`` counts
    choices that landed on a rung > 1 — steps whose attention ran split-K
    partials plus an LSE merge pass (rung 1 is the chunk-serial program:
    no partials, no merge); ``max_live_ctx`` high-waters the admission
    signal the rung is keyed on; ``select_ms`` is host time inside the
    rung choice (scheduler scan + clamp — the overhead the ladder adds to
    every step)."""

    selects: int = 0
    splits: int = 0
    merged_steps: int = 0
    max_live_ctx: int = 0
    select_ms: float = 0.0
    #: replica label (``serving/cluster.py``): when not None, event names
    #: become ``serve/attn/<replica>/...`` (never cleared by reset())
    replica: Optional[str] = None

    def record(self, rung: int, live_ctx: int, select_s: float) -> None:
        self.selects += 1
        self.splits += int(rung)
        if rung > 1:
            self.merged_steps += 1
        self.max_live_ctx = max(self.max_live_ctx, int(live_ctx))
        self.select_ms += 1e3 * select_s

    def reset(self) -> None:
        self.selects = 0
        self.splits = 0
        self.merged_steps = 0
        self.max_live_ctx = 0
        self.select_ms = 0.0

    @property
    def splits_per_select(self) -> float:
        return self.splits / self.selects if self.selects else 0.0

    def events(self, step: int = 0) -> List[Event]:
        """``serve/attn/*`` monitor events (docs/OBSERVABILITY.md taxonomy);
        replica-labelled (``serve/attn/<replica>/*``) under a cluster."""
        n = max(1, self.selects)
        pre = "serve/attn" if self.replica is None \
            else f"serve/attn/{self.replica}"
        return [
            (f"{pre}/selects", float(self.selects), step),
            (f"{pre}/splits_per_select", self.splits_per_select, step),
            (f"{pre}/merged_steps", float(self.merged_steps), step),
            (f"{pre}/max_live_ctx", float(self.max_live_ctx), step),
            (f"{pre}/select_ms_per_step", self.select_ms / n, step),
        ]


#: latency samples retained per class (completed requests only); percentiles
#: below compute over this sliding window
SAMPLE_WINDOW = 4096


class _ClassCounters:
    """Per-priority-class frontend counters + bounded latency windows."""

    __slots__ = ("submitted", "admitted", "completed", "shed", "cancelled",
                 "slo_met", "tokens", "ttft_ms", "tbt_ms")

    def __init__(self):
        self.submitted = 0
        self.admitted = 0
        self.completed = 0
        self.shed = 0
        self.cancelled = 0
        self.slo_met = 0
        self.tokens = 0
        self.ttft_ms: Deque[float] = deque(maxlen=SAMPLE_WINDOW)
        self.tbt_ms: Deque[float] = deque(maxlen=SAMPLE_WINDOW)


class FrontendStats:
    """Aggregate counters for one ``ServingFrontend``
    (``inference/v2/serving/frontend.py``): per-class TTFT/TBT percentile
    windows, queue depth, preemption/offload traffic, shed counts — the
    ``serve/frontend/*`` monitor surface. Mutated only on the frontend's
    engine thread (single writer); the latency samples come from the SAME
    ``perf_counter`` stamps the per-request ``serve/req/*`` trace spans are
    built from, so the dashboard and the timeline can never disagree.

    ``replica`` (set by ``serving/cluster.py``): when not None, event names
    become ``serve/frontend/<replica>/...`` — N replicas' frontends fanning
    into ONE monitor backend (one CSV) previously interleaved
    indistinguishable rows."""

    def __init__(self, class_names: List[str],
                 replica: Optional[str] = None):
        self.replica = replica
        self.classes: Dict[str, _ClassCounters] = {
            name: _ClassCounters() for name in class_names}
        self.queue_depth = 0               # gauge: pending after last round
        # KV-pool gauges (set_kv_pool at frontend build; residency refreshed
        # per admission round) — the serve/frontend/kv/* surface that makes
        # an int8 pool's capacity doubling observable next to the latency
        # counters it buys (docs/SERVING.md "Quantized KV"). Static facts
        # are config-derived, not timed, so the stats-equals-spans invariant
        # is untouched; the per-round residency gauges mirror to trace
        # counters from the same refresh point.
        self.kv_pool_dtype_bits = 0
        self.kv_bytes_per_token = 0.0
        self.kv_pool_tokens = 0
        self.kv_max_context = 0
        self.kv_block_size = 0
        self.kv_free_blocks = 0            # gauge: after last admission round
        self.kv_resident_seqs = 0          # gauge: tracked sequences
        self.preemptions = 0               # victims preempted (any mechanism)
        self.recompute_preemptions = 0     # ... of which fell back to recompute
        self.restores = 0
        self.offload_bytes = 0             # KV bytes moved device -> host
        self.restore_bytes = 0             # KV bytes moved host -> device
        self.forced_sheds = 0              # reject-only emergency sheds
        # SLO-miss attribution (docs/OBSERVABILITY.md "SLO-miss
        # attribution"): every finished-but-missed request bucketed by the
        # DOMINANT phase of its ledger (the same perf stamps the serve/req
        # spans record) — the serve/slo/* surface that answers "where did
        # the missed requests' time go" per replica
        self.slo_missed = 0
        self.slo_missed_by_phase: Dict[str, int] = {}
        self.slo_missed_by_class: Dict[str, int] = {}
        self.slo_attr_consistent = 0       # ledger summed to client latency

    # -- recording (engine thread) ------------------------------------- #

    def set_kv_pool(self, dtype_bits: int, bytes_per_token: float,
                    pool_tokens: int, max_context: int,
                    block_size: int) -> None:
        """Static KV-pool facts (one call at frontend construction)."""
        self.kv_pool_dtype_bits = int(dtype_bits)
        self.kv_bytes_per_token = float(bytes_per_token)
        self.kv_pool_tokens = int(pool_tokens)
        self.kv_max_context = int(max_context)
        self.kv_block_size = int(block_size)

    def record_submit(self, cls: str) -> None:
        self.classes[cls].submitted += 1

    def record_admit(self, cls: str) -> None:
        self.classes[cls].admitted += 1

    def record_shed(self, cls: str) -> None:
        self.classes[cls].shed += 1

    def record_cancel(self, cls: str) -> None:
        self.classes[cls].cancelled += 1

    def record_slo_miss(self, cls: str, phase: str,
                        consistent: bool) -> None:
        """One finished request that missed its class SLO, attributed to
        the dominant phase of its ledger; ``consistent`` = the ledger's
        stints summed to the client-measured latency (small epsilon)."""
        self.slo_missed += 1
        self.slo_missed_by_phase[phase] = \
            self.slo_missed_by_phase.get(phase, 0) + 1
        self.slo_missed_by_class[cls] = \
            self.slo_missed_by_class.get(cls, 0) + 1
        self.slo_attr_consistent += bool(consistent)

    def record_complete(self, cls: str, ttft_ms: Optional[float],
                        tbt_ms: List[float], tokens: int,
                        slo_met: bool) -> None:
        c = self.classes[cls]
        c.completed += 1
        c.tokens += tokens
        c.slo_met += bool(slo_met)
        if ttft_ms is not None:
            c.ttft_ms.append(float(ttft_ms))
        c.tbt_ms.extend(float(x) for x in tbt_ms)

    # -- reporting ------------------------------------------------------ #

    def events(self, step: int = 0) -> List[Event]:
        """``serve/frontend/*`` monitor events: global gauges/counters plus
        per-class completion and latency percentiles (docs/SERVING.md
        glossary); replica-labelled (``serve/frontend/<replica>/*``) under
        a cluster."""
        import numpy as np
        base = "serve/frontend" if self.replica is None \
            else f"serve/frontend/{self.replica}"
        # how many MORE max_context-length sequences the free pool could
        # hold right now — the headroom number an int8 pool's capacity
        # doubling moves (same HBM budget -> more blocks -> more headroom).
        # Counted in whole BLOCKS: a sequence's last partial block still
        # consumes a full block, so free_tokens // max_context would
        # overstate headroom whenever max_context % block_size != 0
        headroom = (self.kv_free_blocks
                    // -(-self.kv_max_context // self.kv_block_size)
                    if self.kv_max_context and self.kv_block_size else 0)
        out: List[Event] = [
            (f"{base}/queue_depth", float(self.queue_depth), step),
            (f"{base}/kv/pool_dtype_bits",
             float(self.kv_pool_dtype_bits), step),
            (f"{base}/kv/bytes_per_token",
             float(self.kv_bytes_per_token), step),
            (f"{base}/kv/pool_tokens", float(self.kv_pool_tokens), step),
            (f"{base}/kv/free_blocks", float(self.kv_free_blocks), step),
            (f"{base}/kv/resident_seqs",
             float(self.kv_resident_seqs), step),
            (f"{base}/kv/resident_seq_headroom", float(headroom), step),
            (f"{base}/preemptions", float(self.preemptions), step),
            (f"{base}/recompute_preemptions",
             float(self.recompute_preemptions), step),
            (f"{base}/restores", float(self.restores), step),
            (f"{base}/offload_bytes", float(self.offload_bytes), step),
            (f"{base}/restore_bytes", float(self.restore_bytes), step),
            (f"{base}/forced_sheds", float(self.forced_sheds), step),
        ]
        for name, c in self.classes.items():
            pre = f"{base}/{name}"
            out += [
                (f"{pre}/completed", float(c.completed), step),
                (f"{pre}/shed", float(c.shed), step),
                (f"{pre}/cancelled", float(c.cancelled), step),
                (f"{pre}/tokens", float(c.tokens), step),
                (f"{pre}/slo_met_fraction",
                 c.slo_met / c.completed if c.completed else 0.0, step),
            ]
            for label, win in (("ttft", c.ttft_ms), ("tbt", c.tbt_ms)):
                if win:
                    xs = np.asarray(win, np.float64)
                    out += [
                        (f"{pre}/{label}_p50_ms",
                         float(np.percentile(xs, 50)), step),
                        (f"{pre}/{label}_p95_ms",
                         float(np.percentile(xs, 95)), step),
                    ]
        # serve/slo/*: SLO-miss attribution rollup (snapshot the dicts —
        # the engine thread inserts first-seen phase keys while a bench
        # thread reads)
        slo_base = "serve/slo" if self.replica is None \
            else f"serve/slo/{self.replica}"
        by_phase = dict(self.slo_missed_by_phase)
        by_class = dict(self.slo_missed_by_class)
        out.append((f"{slo_base}/missed", float(self.slo_missed), step))
        out.append((f"{slo_base}/attr_consistent",
                    float(self.slo_attr_consistent), step))
        for phase, n in sorted(by_phase.items()):
            out.append((f"{slo_base}/dominant/{phase}", float(n), step))
        for cls, n in sorted(by_class.items()):
            out.append((f"{slo_base}/by_class/{cls}", float(n), step))
        return out


#: detection-latency samples retained (sliding window, like SAMPLE_WINDOW)
_DETECT_WINDOW = 256


class HealthStats:
    """Aggregate counters for one router's ``HealthMonitor``
    (``inference/v2/serving/health.py``) — the ``serve/health/*`` monitor
    surface (docs/SERVING.md "Failure semantics"). Per-window aggregations
    over the SAME ``perf_counter`` stamps the tracer records as
    ``serve/health/{detect,migrate,rejoin}`` spans — one set of perf pairs
    feeds both (docs/OBSERVABILITY.md), so the dashboard and the timeline
    can never disagree about when a failure was detected or how long a
    rejoin warmup took. Mutated only on the health-monitor thread (single
    writer); readers see monotone counters."""

    def __init__(self, replica_names: Optional[List[str]] = None):
        #: replica -> current health state name (gauge-ish, for dashboards)
        self.states: Dict[str, str] = {
            n: "healthy" for n in (replica_names or [])}
        self.transitions: Dict[str, int] = {}   # "suspect->down" -> count
        self.liveness_downs = 0                 # died loop / worker
        self.stall_downs = 0                    # wedged: progress deadline
        self.detect_ms: Deque[float] = deque(maxlen=_DETECT_WINDOW)
        self.migrations = 0                     # requests moved off a corpse
        self.salvaged = 0                       # ... via offloaded-KV import
        self.reprefilled = 0                    # ... via history re-prefill
        self.salvaged_tokens = 0                # history tokens NOT recomputed
        self.reprefilled_tokens = 0             # history tokens recomputed
        self.salvaged_bytes = 0                 # KV bytes imported from host
        self.migration_sheds = 0                # no survivor could fund it
        self.migration_cancels = 0              # cancel landed mid-migration
        self.handoffs_replanned = 0             # queued handoffs re-targeted
        self.rejoins = 0
        self.rejoin_warmup_ms = 0.0             # cumulative warmup wall

    # -- recording (health-monitor thread) ------------------------------ #

    def record_transition(self, replica: str, old: str, new: str) -> None:
        self.states[replica] = new
        key = f"{old}->{new}"
        self.transitions[key] = self.transitions.get(key, 0) + 1

    def record_detection(self, kind: str, latency_s: float) -> None:
        if kind == "stall":
            self.stall_downs += 1
        else:
            self.liveness_downs += 1
        self.detect_ms.append(1e3 * latency_s)

    def record_migration(self, mode: str, history_tokens: int,
                         nbytes: int = 0) -> None:
        self.migrations += 1
        if mode == "salvage":
            self.salvaged += 1
            self.salvaged_tokens += int(history_tokens)
            self.salvaged_bytes += int(nbytes)
        else:
            self.reprefilled += 1
            self.reprefilled_tokens += int(history_tokens)

    def record_rejoin(self, warmup_s: float) -> None:
        self.rejoins += 1
        self.rejoin_warmup_ms += 1e3 * warmup_s

    # -- reporting ------------------------------------------------------- #

    def events(self, step: int = 0) -> List[Event]:
        """``serve/health/*`` monitor events (docs/SERVING.md glossary).
        Snapshots the dicts/deque first: a monitor backend reads on a
        bench/user thread while the health thread inserts first-seen
        transition keys — iterating the live dict would race."""
        import numpy as np
        transitions = dict(self.transitions)
        states = dict(self.states)
        detect = list(self.detect_ms)
        out: List[Event] = [
            ("serve/health/transitions",
             float(sum(transitions.values())), step),
            ("serve/health/liveness_downs", float(self.liveness_downs), step),
            ("serve/health/stall_downs", float(self.stall_downs), step),
            ("serve/health/migrations", float(self.migrations), step),
            ("serve/health/salvaged", float(self.salvaged), step),
            ("serve/health/reprefilled", float(self.reprefilled), step),
            ("serve/health/salvaged_tokens",
             float(self.salvaged_tokens), step),
            ("serve/health/reprefilled_tokens",
             float(self.reprefilled_tokens), step),
            ("serve/health/salvaged_bytes", float(self.salvaged_bytes), step),
            ("serve/health/migration_sheds",
             float(self.migration_sheds), step),
            ("serve/health/migration_cancels",
             float(self.migration_cancels), step),
            ("serve/health/handoffs_replanned",
             float(self.handoffs_replanned), step),
            ("serve/health/rejoins", float(self.rejoins), step),
            ("serve/health/rejoin_warmup_ms",
             float(self.rejoin_warmup_ms), step),
        ]
        if detect:
            xs = np.asarray(detect, np.float64)
            out.append(("serve/health/detect_p50_ms",
                        float(np.percentile(xs, 50)), step))
            out.append(("serve/health/detect_p95_ms",
                        float(np.percentile(xs, 95)), step))
        for name, state in states.items():
            # numeric gauge per replica: healthy=0 suspect=1 down=2
            # draining=3 rejoining=4 (dashboards can't plot strings)
            code = {"healthy": 0, "suspect": 1, "down": 2,
                    "draining": 3, "rejoining": 4}.get(state, -1)
            out.append((f"serve/health/state/{name}", float(code), step))
        return out


class _AdapterCounters:
    """Per-adapter LoRA serving counters (one per registered adapter)."""

    __slots__ = ("active", "resident", "evictions", "faults", "acquires",
                 "hits", "swap_in_bytes", "swap_out_bytes")

    def __init__(self):
        self.active = 0            # gauge: in-flight requests bound to it
        self.resident = 0          # gauge: 0/1 device residency
        self.evictions = 0
        self.faults = 0            # device fault-ins (from host/master)
        self.acquires = 0
        self.hits = 0              # acquires served without a fault
        self.swap_in_bytes = 0     # host -> device (fault/restore)
        self.swap_out_bytes = 0    # device -> host (evict)


class LoraStats:
    """Aggregate counters for one engine's LoRA adapter registry
    (``inference/v2/lora/registry.py``) — the ``serve/lora/*`` monitor
    surface (docs/SERVING.md "Multi-tenant LoRA"). Per-window aggregations
    over the SAME ``perf_counter`` stamps the tracer records as
    ``serve/lora/{fault,swap}`` timeline spans — one set of perf pairs per
    fault-in/evict feeds both (docs/OBSERVABILITY.md), so the dashboard's
    swap traffic and the Perfetto lanes can never disagree. Mutated only on
    the registry's calling thread (the frontend's engine thread — single
    writer); ``events()`` snapshots the dict before iterating."""

    def __init__(self):
        self.adapters: Dict[str, _AdapterCounters] = {}
        self.fault_ms = 0.0        # cumulative fault-in wall (incl. scatter)
        self.swap_ms = 0.0         # cumulative evict wall (incl. gather)

    def _c(self, name: str) -> _AdapterCounters:
        return self.adapters.setdefault(name, _AdapterCounters())

    # -- recording (registry thread) ------------------------------------- #

    def record_acquire(self, name: str, hit: bool) -> None:
        c = self._c(name)
        c.acquires += 1
        c.hits += bool(hit)
        c.active += 1

    def record_release(self, name: str) -> None:
        self._c(name).active -= 1

    def record_fault(self, name: str, nbytes: int, dt_s: float) -> None:
        c = self._c(name)
        c.faults += 1
        c.swap_in_bytes += int(nbytes)
        c.resident = 1
        self.fault_ms += 1e3 * dt_s

    def record_evict(self, name: str, nbytes: int, dt_s: float) -> None:
        c = self._c(name)
        c.evictions += 1
        c.swap_out_bytes += int(nbytes)
        c.resident = 0
        self.swap_ms += 1e3 * dt_s

    def set_resident(self, name: str, resident: bool) -> None:
        self._c(name).resident = int(bool(resident))

    def drop(self, name: str) -> None:
        """Forget an unregistered adapter's gauges (counters are lost with
        it — an unregister mid-window is rare enough not to matter)."""
        self.adapters.pop(name, None)

    # -- reporting -------------------------------------------------------- #

    @property
    def hit_fraction(self) -> float:
        acq = sum(c.acquires for c in self.adapters.values())
        hits = sum(c.hits for c in self.adapters.values())
        return hits / acq if acq else 0.0

    def events(self, step: int = 0) -> List[Event]:
        """``serve/lora/*`` monitor events (docs/SERVING.md glossary):
        registry-wide rollups plus the per-adapter breakdown."""
        adapters = dict(self.adapters)
        out: List[Event] = [
            ("serve/lora/registered", float(len(adapters)), step),
            ("serve/lora/resident",
             float(sum(c.resident for c in adapters.values())), step),
            ("serve/lora/active",
             float(sum(c.active for c in adapters.values())), step),
            ("serve/lora/faults",
             float(sum(c.faults for c in adapters.values())), step),
            ("serve/lora/evictions",
             float(sum(c.evictions for c in adapters.values())), step),
            ("serve/lora/swap_in_bytes",
             float(sum(c.swap_in_bytes for c in adapters.values())), step),
            ("serve/lora/swap_out_bytes",
             float(sum(c.swap_out_bytes for c in adapters.values())), step),
            ("serve/lora/hit_fraction", self.hit_fraction, step),
            ("serve/lora/fault_ms", self.fault_ms, step),
            ("serve/lora/swap_ms", self.swap_ms, step),
        ]
        for name, c in sorted(adapters.items()):
            pre = f"serve/lora/{name}"
            out += [
                (f"{pre}/active", float(c.active), step),
                (f"{pre}/resident", float(c.resident), step),
                (f"{pre}/evictions", float(c.evictions), step),
                (f"{pre}/faults", float(c.faults), step),
                (f"{pre}/swap_bytes",
                 float(c.swap_in_bytes + c.swap_out_bytes), step),
                (f"{pre}/hit_fraction",
                 c.hits / c.acquires if c.acquires else 0.0, step),
            ]
        return out


class RouterStats:
    """Aggregate counters for one ``ServingRouter``
    (``inference/v2/serving/router.py``) — the ``serve/router/*`` monitor
    surface. Placement counters (routed per replica, cache-hit blocks,
    rebalances, router-level sheds) plus the disaggregation handoff traffic,
    and per-class CLUSTER rollups computed from the registered replicas'
    :class:`FrontendStats` at ``events()`` time — the cluster-goodput view
    that no single replica's counters can provide. Placement counters are
    mutated under the router's lock (submit may be called from any client
    thread); the rollup only reads."""

    def __init__(self, replica_names: List[str], class_names: List[str]):
        self.routed: Dict[str, int] = {n: 0 for n in replica_names}
        self.cache_hit_blocks = 0          # blocks cached at the CHOSEN replica
        self.cache_hit_requests = 0        # requests routed onto a warm prefix
        self.rebalances = 0                # cache-best replica overridden
        self.router_sheds: Dict[str, int] = {c: 0 for c in class_names}
        self.handoffs = 0                  # prefill->decode sequences moved
        self.handoff_bytes = 0             # KV bytes over the page fabric
        self.handoff_failures = 0          # retry budgets exhausted (shed)
        self._frontends: List[FrontendStats] = []

    def register_frontend(self, stats: FrontendStats) -> None:
        self._frontends.append(stats)

    def events(self, step: int = 0) -> List[Event]:
        """``serve/router/*`` monitor events (docs/SERVING.md "Multi-replica
        & disaggregation" glossary)."""
        out: List[Event] = [
            ("serve/router/routed",
             float(sum(self.routed.values())), step),
            ("serve/router/cache_hit_blocks",
             float(self.cache_hit_blocks), step),
            ("serve/router/cache_hit_requests",
             float(self.cache_hit_requests), step),
            ("serve/router/rebalances", float(self.rebalances), step),
            ("serve/router/sheds",
             float(sum(self.router_sheds.values())), step),
            ("serve/router/handoffs", float(self.handoffs), step),
            ("serve/router/handoff_bytes", float(self.handoff_bytes), step),
            ("serve/router/handoff_failures",
             float(self.handoff_failures), step),
        ]
        for name, n in self.routed.items():
            out.append((f"serve/router/routed/{name}", float(n), step))
        # cluster-level SLO-miss attribution rollup: sum the replicas'
        # serve/slo buckets — "what phase is eating the cluster's misses"
        # in one row set (docs/OBSERVABILITY.md "SLO-miss attribution")
        missed = consistent = 0
        by_phase: Dict[str, int] = {}
        for fs in self._frontends:
            missed += fs.slo_missed
            consistent += fs.slo_attr_consistent
            for phase, n in dict(fs.slo_missed_by_phase).items():
                by_phase[phase] = by_phase.get(phase, 0) + n
        out.append(("serve/slo/cluster/missed", float(missed), step))
        out.append(("serve/slo/cluster/attr_consistent",
                    float(consistent), step))
        for phase, n in sorted(by_phase.items()):
            out.append((f"serve/slo/cluster/dominant/{phase}",
                        float(n), step))
        # per-class cluster rollup: sum over every registered replica
        for cls in self.router_sheds:
            completed = shed = tokens = slo = 0
            for fs in self._frontends:
                c = fs.classes.get(cls)
                if c is None:
                    continue
                completed += c.completed
                shed += c.shed
                tokens += c.tokens
                slo += c.slo_met
            shed += self.router_sheds[cls]
            pre = f"serve/router/{cls}"
            out += [
                (f"{pre}/completed", float(completed), step),
                (f"{pre}/shed", float(shed), step),
                (f"{pre}/tokens", float(tokens), step),
                (f"{pre}/slo_met_fraction",
                 slo / completed if completed else 0.0, step),
            ]
        return out
