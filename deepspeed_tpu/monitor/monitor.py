"""Event monitoring: fan-out of ``(name, value, step)`` tuples to backends.

Parity: ``deepspeed/monitor/monitor.py:29 MonitorMaster`` — a single object the
engine writes event lists to, which forwards them to every enabled backend
(TensorBoard / Weights & Biases / CSV). Backends are constructed from the config
tree (``tensorboard`` / ``wandb`` / ``csv_monitor`` sections) and only rank 0 of
the process (host) writes, matching the reference's rank-0 gating.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    """Abstract backend. Parity: ``deepspeed/monitor/monitor.py:16 Monitor``."""

    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, event_list: Iterable[Event]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    """Parity: ``deepspeed/monitor/tensorboard.py``. Uses
    ``torch.utils.tensorboard`` when importable; degrades to disabled otherwise
    (this image has torch but may lack the tensorboard wheel)."""

    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if not self.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception as e:  # pragma: no cover - env without tensorboard
            logger.warning(f"tensorboard unavailable ({e}); TensorBoardMonitor disabled")
            self.enabled = False
            return
        import os
        log_dir = os.path.join(config.output_path or ".", config.job_name)
        self.summary_writer = SummaryWriter(log_dir=log_dir)

    def write_events(self, event_list: Iterable[Event]) -> None:
        if not self.enabled or self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, float(value), int(step))
        self.summary_writer.flush()

    def close(self):
        if self.summary_writer is not None:
            self.summary_writer.flush()
            self.summary_writer.close()
            self.summary_writer = None


class WandbMonitor(Monitor):
    """Parity: ``deepspeed/monitor/wandb.py``. Gated on the wandb package."""

    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if not self.enabled:
            return
        try:
            import wandb
        except Exception as e:  # pragma: no cover - env without wandb
            logger.warning(f"wandb unavailable ({e}); WandbMonitor disabled")
            self.enabled = False
            return
        self._wandb = wandb
        wandb.init(project=config.project, group=config.group, entity=config.team)

    def write_events(self, event_list: Iterable[Event]) -> None:
        if not self.enabled or self._wandb is None:
            return
        for name, value, step in event_list:
            self._wandb.log({name: float(value)}, step=int(step))

    def close(self):
        if self._wandb is not None:
            self._wandb.finish()
            self._wandb = None


class CsvMonitor(Monitor):
    """Parity: ``deepspeed/monitor/csv_monitor.py`` — one CSV file per event
    name under ``output_path/job_name/``."""

    def __init__(self, config):
        super().__init__(config)
        self._files = {}
        self.log_dir = None
        if not self.enabled:
            return
        import os
        self.log_dir = os.path.join(config.output_path or ".", config.job_name)
        os.makedirs(self.log_dir, exist_ok=True)

    def _file_for(self, name: str):
        import os
        if name not in self._files:
            # event names like Train/Samples/lr -> Train_Samples_lr.csv
            fname = name.replace("/", "_") + ".csv"
            path = os.path.join(self.log_dir, fname)
            new = not os.path.exists(path)
            f = open(path, "a")
            if new:
                f.write("step,value\n")
            self._files[name] = f
        return self._files[name]

    def write_events(self, event_list: Iterable[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in event_list:
            f = self._file_for(name)
            f.write(f"{int(step)},{float(value)}\n")
            f.flush()

    def close(self):
        for f in self._files.values():
            f.close()
        self._files = {}


class MonitorMaster(Monitor):
    """Fan-out master. Parity: ``deepspeed/monitor/monitor.py:29``.

    Only the process-rank-0 host writes (in single-controller JAX there is one
    Python process per host; events are identical across hosts since metrics are
    fully reduced on device)."""

    def __init__(self, config):
        # config here is the full DeepSpeedTPUConfig
        from deepspeed_tpu.monitor.export import PrometheusExporter
        import deepspeed_tpu.comm as dist
        self._is_rank0 = dist.get_rank() == 0
        self.tb_monitor = TensorBoardMonitor(config.tensorboard)
        self.wandb_monitor = WandbMonitor(config.wandb)
        self.csv_monitor = CsvMonitor(config.csv_monitor)
        # live telemetry (monitor/export.py): configs predating the section
        # (tests building partial trees) degrade to a disabled exporter.
        # Only rank 0 BINDS — writes are rank-0-gated below, so an exporter
        # on any other rank would serve a live-but-forever-empty /metrics
        # (and race rank 0 for a fixed port on shared hosts)
        prom_cfg = getattr(config, "prometheus", None)
        self.prom_monitor = PrometheusExporter(
            prom_cfg if (prom_cfg is not None and self._is_rank0)
            else type("_Off", (), {"enabled": False})())
        self.enabled = (self.tb_monitor.enabled or self.wandb_monitor.enabled
                        or self.csv_monitor.enabled
                        or self.prom_monitor.enabled)

    def write_events(self, event_list: Iterable[Event]) -> None:
        if not self.enabled or not self._is_rank0:
            return
        event_list = list(event_list)
        self.tb_monitor.write_events(event_list)
        self.wandb_monitor.write_events(event_list)
        self.csv_monitor.write_events(event_list)
        self.prom_monitor.write_events(event_list)

    def close(self):
        """Flush and close every backend. ``engine.destroy()`` calls this
        AFTER draining the deferred metric queue, so the final step's events
        are on disk (not buffered in a dangling file handle or an unflushed
        SummaryWriter) without the caller ever touching ``drain_metrics()``
        — the PR 4 deferred-drain footgun, closed. Idempotent. The live
        exporter closes FIRST: its final snapshot (``metrics.prom``) is
        drained before the CSV files shut."""
        self.prom_monitor.close()
        self.tb_monitor.close()
        self.wandb_monitor.close()
        self.csv_monitor.close()
