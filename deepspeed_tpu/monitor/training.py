"""Training step-loop observability: per-step timing/queue counters.

The async training loop (``runtime/data_pipeline.py`` + the engine's deferred
metric drain) overlaps four things per global step — dequeuing the next
staged batch, the fused step's dispatch, the host-side staging of batch k+N
(in the PrefetchLoader producer), and the drain of step k-1's metrics.
Whether that overlap happens is invisible from steps/sec alone (a loop can
hit its throughput while secretly serialising), so ``train_batch`` accounts
every step's wall time into the phases below and this module turns the
totals into ``monitor/`` events (``MonitorMaster.write_events``
``(name, value, step)`` shape — the same contract ``PipelineStats`` and
``PrefixCacheStats`` follow on the serving side).

Every stat class here aggregates the SAME measured intervals the span
tracer records as timeline spans (``train/step/*``, ``train/offload/*``,
``ckpt/*`` — ``monitor/trace.py``, docs/OBSERVABILITY.md): one set of
``perf_counter`` pairs per site feeds both the window aggregate and the
Perfetto track, so a dashboard number always has a matching span to zoom
into.

Phase semantics (per step):

- ``enqueue_wait``: host time blocked on the prefetch queue. Unlike every
  other phase this one is ALLOWED to grow: it is where the host waits when
  the device is the bottleneck, which is the healthy steady state. It is a
  problem only when ``queue_depth`` is simultaneously 0 — then the producer
  (collate + device_put), not the device, is what the host is waiting for.
- ``host_build``: synchronous staging on the caller's thread — collate,
  curriculum truncation, PLD injection, the sharded device_put. Near-zero
  when prefetching (the producer does it); the whole per-step tax when not.
- ``dispatch``: host time enqueueing the fused train step (jax async
  dispatch — NOT device execution time).
- ``drain``: host time materialising DEFERRED metrics (step k-1's
  loss/lr/grad_norm, fetched one step late while step k runs). Under
  ``wall_clock_breakdown`` this becomes the step's full sync.
- ``queue_depth``: prefetch queue occupancy at dequeue time. Persistently 0
  with prefetch enabled means the producer is the bottleneck; persistently
  full means the device is (the healthy steady state).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List

from deepspeed_tpu.monitor.monitor import Event

#: step_wall_ms window — bounded so a long-lived engine (record_step fires on
#: EVERY train_batch, forever) cannot grow host memory without bound; the
#: serving twin clears its list per run, the training loop has no run scope
WALL_WINDOW = 512


@dataclass
class TrainPipelineStats:
    """Aggregate counters for one engine's training loop (cumulative;
    ``reset()`` between measurement windows)."""

    steps: int = 0
    enqueue_wait_ms: float = 0.0
    host_build_ms: float = 0.0
    dispatch_ms: float = 0.0
    drain_ms: float = 0.0
    queue_depth_sum: int = 0
    prefetched_steps: int = 0        # steps fed by an already-staged batch
    #: wall times (ms) of the most recent ``WALL_WINDOW`` steps — a bounded
    #: p50/p99 latency window (``list(...)`` it for np.percentile)
    step_wall_ms: Deque[float] = field(
        default_factory=lambda: deque(maxlen=WALL_WINDOW))

    def record_step(self, wait_s: float, build_s: float, dispatch_s: float,
                    drain_s: float, wall_s: float, queue_depth: int = 0,
                    prefetched: bool = False) -> None:
        self.steps += 1
        self.enqueue_wait_ms += 1e3 * wait_s
        self.host_build_ms += 1e3 * build_s
        self.dispatch_ms += 1e3 * dispatch_s
        self.drain_ms += 1e3 * drain_s
        self.queue_depth_sum += int(queue_depth)
        self.prefetched_steps += int(bool(prefetched))
        self.step_wall_ms.append(1e3 * wall_s)

    def reset(self) -> None:
        self.steps = 0
        self.enqueue_wait_ms = 0.0
        self.host_build_ms = 0.0
        self.dispatch_ms = 0.0
        self.drain_ms = 0.0
        self.queue_depth_sum = 0
        self.prefetched_steps = 0
        self.step_wall_ms = deque(maxlen=WALL_WINDOW)

    def events(self, step: int = 0) -> List[Event]:
        """Monitor-ready ``(name, value, step)`` tuples; per-step averages so
        dashboards stay comparable across runs of different lengths."""
        n = max(1, self.steps)
        return [
            ("train/pipeline/steps", float(self.steps), step),
            ("train/pipeline/enqueue_wait_ms_per_step",
             self.enqueue_wait_ms / n, step),
            ("train/pipeline/host_build_ms_per_step",
             self.host_build_ms / n, step),
            ("train/pipeline/dispatch_ms_per_step",
             self.dispatch_ms / n, step),
            ("train/pipeline/drain_ms_per_step", self.drain_ms / n, step),
            ("train/pipeline/queue_depth", self.queue_depth_sum / n, step),
            ("train/pipeline/prefetched_fraction",
             self.prefetched_steps / n, step),
        ]


@dataclass
class CheckpointStats:
    """Rolling-checkpoint observability (``checkpoint/rolling.py`` +
    ``save_checkpoint``; emitted at print boundaries beside
    TrainPipelineStats as ``train/ckpt/*``).

    Phase semantics (per save):

    - ``snapshot``: device->host materialisation of the state flats — the
      ONLY phase on the step loop's critical path when the async engine
      writes. Growing snapshot time means the state grew or the transfer
      link is contended, not that the disk is slow.
    - ``commit``: writer drain + manifest + ``latest`` flip, on the
      background committer (async engine) or inline (native engine).
    - ``backpressure``: host time the step loop blocked because
      ``rolling.max_pending`` snapshots were still uncommitted — nonzero
      means the disk/writers cannot keep up with the cadence (raise
      ``every_n_steps``, add writers, or accept the stall).
    - ``queue_depth``: checkpoint-engine writer queue occupancy sampled at
      each save submit.
    - ``retries``: cumulative bounded-retry count from the writer path
      (``CheckpointEngine.retries``).
    - ``pruned``: rolling tags deleted by retention.
    """

    saves: int = 0
    snapshot_ms: float = 0.0
    commit_ms: float = 0.0
    backpressure_ms: float = 0.0
    queue_depth_sum: int = 0
    retries: int = 0
    pruned: int = 0

    def record_save(self, snapshot_s: float, backpressure_s: float = 0.0,
                    queue_depth: int = 0) -> None:
        self.saves += 1
        self.snapshot_ms += 1e3 * snapshot_s
        self.backpressure_ms += 1e3 * backpressure_s
        self.queue_depth_sum += int(queue_depth)

    def record_commit(self, commit_s: float, pruned: int = 0) -> None:
        self.commit_ms += 1e3 * commit_s
        self.pruned += int(pruned)

    def reset(self) -> None:
        self.saves = 0
        self.snapshot_ms = 0.0
        self.commit_ms = 0.0
        self.backpressure_ms = 0.0
        self.queue_depth_sum = 0
        self.retries = 0
        self.pruned = 0

    def events(self, step: int = 0) -> List[Event]:
        n = max(1, self.saves)
        return [
            ("train/ckpt/saves", float(self.saves), step),
            ("train/ckpt/snapshot_ms_per_save", self.snapshot_ms / n, step),
            ("train/ckpt/commit_ms_per_save", self.commit_ms / n, step),
            ("train/ckpt/backpressure_ms_per_save",
             self.backpressure_ms / n, step),
            ("train/ckpt/writer_queue_depth", self.queue_depth_sum / n, step),
            ("train/ckpt/retries", float(self.retries), step),
            ("train/ckpt/pruned_tags", float(self.pruned), step),
        ]


@dataclass
class OffloadPipelineStats:
    """Phase counters for the offloaded optimizer's fetch/step/upload group
    pipeline (``runtime/zero/offload.py step_groups`` + the engine's upload
    lane; docs/TRAINING.md "Offloaded optimizer pipeline").

    Phase semantics (accumulated over every group of every step):

    - ``fetch``: host time blocked draining a group's grads D2H. Small in
      steady state — every group's transfer is queued up front, so group g's
      drain overlaps group g-1's kernel. Growing fetch with upload near zero
      means the link, not the host kernel, is the bottleneck.
    - ``kernel``: host optimizer wall time (chunked across the worker pool).
      The phase the other three exist to hide.
    - ``upload``: upload-lane wall time (concat + cast + async device_put of
      a finished group's master). Runs on its own worker, overlapping later
      groups' kernels.
    - ``swap``: NVMe-mode only — time the state swapper's ``run`` spent
      outside the step function (read waits, write drains). The pure IO cost
      of the nvme tier over the cpu tier.
    - ``upload_depth``: pending uploads observed at each group completion;
      persistently high means H2D (or the merge) is the bottleneck.
    """

    steps: int = 0
    groups: int = 0
    fetch_ms: float = 0.0
    kernel_ms: float = 0.0
    upload_ms: float = 0.0
    swap_ms: float = 0.0
    upload_depth_sum: int = 0

    #: phase name -> attribute, the ``add(phase, seconds)`` contract shared
    #: with ``HostOffloadOptimizer.step_groups``'s ``record`` callback
    _PHASES = {"fetch": "fetch_ms", "kernel": "kernel_ms",
               "upload": "upload_ms", "swap": "swap_ms"}

    def add(self, phase: str, seconds: float) -> None:
        attr = self._PHASES[phase]
        setattr(self, attr, getattr(self, attr) + 1e3 * seconds)

    def record_step(self, groups: int, depth_sum: int = 0) -> None:
        self.steps += 1
        self.groups += int(groups)
        self.upload_depth_sum += int(depth_sum)

    def reset(self) -> None:
        self.steps = 0
        self.groups = 0
        self.fetch_ms = 0.0
        self.kernel_ms = 0.0
        self.upload_ms = 0.0
        self.swap_ms = 0.0
        self.upload_depth_sum = 0

    def events(self, step: int = 0) -> List[Event]:
        n = max(1, self.steps)
        g = max(1, self.groups)
        return [
            ("train/offload/steps", float(self.steps), step),
            ("train/offload/groups_per_step", self.groups / n, step),
            ("train/offload/fetch_ms_per_group", self.fetch_ms / g, step),
            ("train/offload/kernel_ms_per_group", self.kernel_ms / g, step),
            ("train/offload/upload_ms_per_group", self.upload_ms / g, step),
            ("train/offload/swap_ms_per_step", self.swap_ms / n, step),
            ("train/offload/upload_depth", self.upload_depth_sum / g, step),
        ]


@dataclass
class Zero3CommStats:
    """Collective-schedule counters for the explicit ZeRO-3 prefetch path
    (``runtime/zero/prefetch.py``; docs/TRAINING.md "ZeRO-3 collective
    schedule"). Aggregated from the SAME ``jax.debug.callback`` stamps that
    become the ``train/zero3/{gather,free,reduce_scatter}`` tracer spans
    (PR 7 stats-equals-spans discipline) — one ``record_step`` per drained
    training-step segment.

    Phase semantics (per training step):

    - ``fwd_gather``: summed wall time of the forward bucketed all-gathers
      (wave w's stamp pair ``gather_start`` -> ``gather_end``; the start
      stamp sits on the tie barrier's output, so the window opens exactly
      when the schedule *allows* the gather, ``depth`` waves early).
    - ``bwd_gather``: the reverse-order backward re-gathers, tied to each
      wave's incoming cotangent.
    - ``reduce_scatter``: grad reduction windows (wave backward's activation
      cotangent ready -> sharded param grads ready). Logical name — on
      XLA:CPU the op lowers to a true ``reduce-scatter`` via the bucketed
      gather's transpose; the implicit path would have been all-reduce+slice.
    - ``overlap``: gather wall time intersected with OTHER waves' residency
      windows (``gather_end`` -> ``free``, i.e. compute on already-gathered
      waves). A serial gather-then-compute schedule (depth 0) measures ~0;
      lookahead opens it. ``overlap_frac`` = overlap / total gather time.
    - ``gather_bytes_per_step``: static plan bytes (fwd + bwd re-gather) —
      what the schedule moves, for bytes/s math against the wall numbers.
    """

    steps: int = 0
    waves: int = 0
    fwd_gather_ms: float = 0.0
    bwd_gather_ms: float = 0.0
    reduce_scatter_ms: float = 0.0
    overlap_ms: float = 0.0
    overlap_frac_sum: float = 0.0
    gather_bytes: int = 0

    def record_step(self, *, fwd_gather_s: float, bwd_gather_s: float,
                    reduce_scatter_s: float, overlap_s: float,
                    overlap_frac: float, gather_bytes: int,
                    n_waves: int) -> None:
        self.steps += 1
        self.waves += int(n_waves)
        self.fwd_gather_ms += 1e3 * fwd_gather_s
        self.bwd_gather_ms += 1e3 * bwd_gather_s
        self.reduce_scatter_ms += 1e3 * reduce_scatter_s
        self.overlap_ms += 1e3 * overlap_s
        self.overlap_frac_sum += overlap_frac
        self.gather_bytes = int(gather_bytes)

    def reset(self) -> None:
        self.steps = 0
        self.waves = 0
        self.fwd_gather_ms = 0.0
        self.bwd_gather_ms = 0.0
        self.reduce_scatter_ms = 0.0
        self.overlap_ms = 0.0
        self.overlap_frac_sum = 0.0
        self.gather_bytes = 0

    def events(self, step: int = 0) -> List[Event]:
        n = max(1, self.steps)
        return [
            ("train/zero3/steps", float(self.steps), step),
            ("train/zero3/waves_per_step", self.waves / n, step),
            ("train/zero3/fwd_gather_ms_per_step", self.fwd_gather_ms / n, step),
            ("train/zero3/bwd_gather_ms_per_step", self.bwd_gather_ms / n, step),
            ("train/zero3/reduce_scatter_ms_per_step",
             self.reduce_scatter_ms / n, step),
            ("train/zero3/overlap_ms_per_step", self.overlap_ms / n, step),
            ("train/zero3/overlap_frac", self.overlap_frac_sum / n, step),
            ("train/zero3/gather_bytes_per_step", float(self.gather_bytes), step),
        ]


@dataclass
class RolloutStats:
    """Colocated-rollout loop counters (``runtime/colocated.py``;
    docs/TRAINING.md "Colocated rollout"). Aggregated from the SAME
    ``perf_counter`` stamp pairs that become the
    ``train/rollout/{sync,swap,generate}`` tracer spans (PR 7
    stats-equals-spans discipline) — one ``record_*`` call per span, so
    every dashboard aggregate has a matching timeline span to zoom into.

    Phase semantics (per rollout round):

    - ``sync``: the WeightBridge's device-resident reshard — one jitted
      program from the training engine's sharded optimizer view to the
      serving engine's layout (dispatch + ``block_until_ready``). Moves
      ``sync_bytes`` of serving-layout weights per round without a host
      round-trip; compare against ``ckpt/*`` spans for the disk-path cost
      this replaces.
    - ``swap``: in-place rebind of the live serving engine's weights at a
      run boundary — quiesce (recompute-preempt / shed) of in-flight
      decode, weight-version bump, prefix-cache flush. ``preempted`` and
      ``shed`` count the quiesce casualties; on a drained engine both
      are 0 and the swap is O(validation).
    - ``generate``: the serving leg of the round — submitting prompts and
      draining rollouts that feed the next train batch.
    """

    rounds: int = 0
    sync_ms: float = 0.0
    swap_ms: float = 0.0
    generate_ms: float = 0.0
    sync_bytes: int = 0
    preempted: int = 0
    shed: int = 0
    requests: int = 0
    tokens: int = 0
    weight_version: int = 0

    def record_sync(self, seconds: float, *, nbytes: int = 0) -> None:
        self.rounds += 1
        self.sync_ms += 1e3 * seconds
        self.sync_bytes = int(nbytes)

    def record_swap(self, seconds: float, *, version: int = 0,
                    preempted: int = 0, shed: int = 0) -> None:
        self.swap_ms += 1e3 * seconds
        self.weight_version = int(version)
        self.preempted += int(preempted)
        self.shed += int(shed)

    def record_generate(self, seconds: float, *, requests: int = 0,
                        tokens: int = 0) -> None:
        self.generate_ms += 1e3 * seconds
        self.requests += int(requests)
        self.tokens += int(tokens)

    def reset(self) -> None:
        self.rounds = 0
        self.sync_ms = 0.0
        self.swap_ms = 0.0
        self.generate_ms = 0.0
        self.sync_bytes = 0
        self.preempted = 0
        self.shed = 0
        self.requests = 0
        self.tokens = 0
        self.weight_version = 0

    def events(self, step: int = 0) -> List[Event]:
        n = max(1, self.rounds)
        return [
            ("train/rollout/rounds", float(self.rounds), step),
            ("train/rollout/sync_ms_per_round", self.sync_ms / n, step),
            ("train/rollout/swap_ms_per_round", self.swap_ms / n, step),
            ("train/rollout/generate_ms_per_round", self.generate_ms / n, step),
            ("train/rollout/sync_bytes", float(self.sync_bytes), step),
            ("train/rollout/preempted", float(self.preempted), step),
            ("train/rollout/shed", float(self.shed), step),
            ("train/rollout/requests", float(self.requests), step),
            ("train/rollout/tokens", float(self.tokens), step),
            ("train/rollout/weight_version", float(self.weight_version), step),
        ]
