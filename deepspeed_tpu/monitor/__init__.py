"""Monitoring backends (parity: ``deepspeed/monitor/``)."""

from deepspeed_tpu.monitor.monitor import (CsvMonitor, Monitor, MonitorMaster,
                                           TensorBoardMonitor, WandbMonitor)

__all__ = ["Monitor", "MonitorMaster", "TensorBoardMonitor", "WandbMonitor",
           "CsvMonitor"]
