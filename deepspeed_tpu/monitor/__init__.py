"""Monitoring backends (parity: ``deepspeed/monitor/``), the per-subsystem
pipeline counters (``serving.PipelineStats`` / ``training.*Stats``), the
span tracer (``trace.tracer`` — the Perfetto-exportable timeline the counters
are per-window aggregations of; docs/OBSERVABILITY.md), and the live
Prometheus-text telemetry exporter (``export.PrometheusExporter``)."""

from deepspeed_tpu.monitor.export import (PrometheusExporter, TelemetryPump,
                                          sanitize_metric_name)
from deepspeed_tpu.monitor.monitor import (CsvMonitor, Monitor, MonitorMaster,
                                           TensorBoardMonitor, WandbMonitor)
from deepspeed_tpu.monitor.serving import PipelineStats
from deepspeed_tpu.monitor.trace import Tracer, tracer
from deepspeed_tpu.monitor.training import (CheckpointStats,
                                            OffloadPipelineStats,
                                            RolloutStats,
                                            TrainPipelineStats,
                                            Zero3CommStats)

__all__ = ["Monitor", "MonitorMaster", "TensorBoardMonitor", "WandbMonitor",
           "CsvMonitor", "PrometheusExporter", "TelemetryPump",
           "sanitize_metric_name", "PipelineStats", "TrainPipelineStats",
           "OffloadPipelineStats", "CheckpointStats", "Zero3CommStats",
           "RolloutStats", "Tracer", "tracer"]
