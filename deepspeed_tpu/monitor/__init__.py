"""Monitoring backends (parity: ``deepspeed/monitor/``), the per-subsystem
pipeline counters (``serving.PipelineStats`` / ``training.*Stats``), and the
span tracer (``trace.tracer`` — the Perfetto-exportable timeline the counters
are per-window aggregations of; docs/OBSERVABILITY.md)."""

from deepspeed_tpu.monitor.monitor import (CsvMonitor, Monitor, MonitorMaster,
                                           TensorBoardMonitor, WandbMonitor)
from deepspeed_tpu.monitor.serving import PipelineStats
from deepspeed_tpu.monitor.trace import Tracer, tracer
from deepspeed_tpu.monitor.training import (CheckpointStats,
                                            OffloadPipelineStats,
                                            TrainPipelineStats)

__all__ = ["Monitor", "MonitorMaster", "TensorBoardMonitor", "WandbMonitor",
           "CsvMonitor", "PipelineStats", "TrainPipelineStats",
           "OffloadPipelineStats", "CheckpointStats", "Tracer", "tracer"]
