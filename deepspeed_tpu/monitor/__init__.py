"""Monitoring backends (parity: ``deepspeed/monitor/``) plus the serving
pipeline's per-step counters (``serving.PipelineStats``)."""

from deepspeed_tpu.monitor.monitor import (CsvMonitor, Monitor, MonitorMaster,
                                           TensorBoardMonitor, WandbMonitor)
from deepspeed_tpu.monitor.serving import PipelineStats

__all__ = ["Monitor", "MonitorMaster", "TensorBoardMonitor", "WandbMonitor",
           "CsvMonitor", "PipelineStats"]
