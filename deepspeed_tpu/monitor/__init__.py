"""Monitoring backends (parity: ``deepspeed/monitor/``) plus the serving
pipeline's per-step counters (``serving.PipelineStats``) and the training
loop's (``training.TrainPipelineStats``)."""

from deepspeed_tpu.monitor.monitor import (CsvMonitor, Monitor, MonitorMaster,
                                           TensorBoardMonitor, WandbMonitor)
from deepspeed_tpu.monitor.serving import PipelineStats
from deepspeed_tpu.monitor.training import (CheckpointStats,
                                            OffloadPipelineStats,
                                            TrainPipelineStats)

__all__ = ["Monitor", "MonitorMaster", "TensorBoardMonitor", "WandbMonitor",
           "CsvMonitor", "PipelineStats", "TrainPipelineStats",
           "OffloadPipelineStats", "CheckpointStats"]
