"""Live cluster telemetry: a pull-based Prometheus-text snapshot endpoint.

The monitor stack so far is write-side only: ``MonitorMaster`` fans
``(name, value, step)`` events to TensorBoard / W&B / CSV files, which a
live dashboard cannot scrape — watching a serving cluster meant tailing
CSVs. This module adds the pull side: :class:`PrometheusExporter` is a
fourth ``MonitorMaster`` backend that keeps the LATEST value of every event
name in memory and serves them as Prometheus text exposition format
(version 0.0.4) from a tiny embedded HTTP endpoint (``GET /metrics``).
Everything already flowing through the event path — per-replica health
state (``serve/health/state/<replica>``), queue depth and KV-pool residency
(``serve/frontend/<replica>/*``), goodput rollups (``serve/router/*``),
SLO-miss attribution (``serve/slo/*``) — becomes scrapeable without
touching a CSV file.

Design constraints, matching the rest of ``monitor/``:

- **zero overhead when disabled**: a disabled exporter starts no thread,
  binds no socket, and ``write_events`` is a one-branch no-op;
- **no work on the event path beyond a dict store**: rendering happens at
  scrape time on the HTTP thread, never on the thread writing events;
- **rank-0 gating is the master's** (``MonitorMaster.write_events``), same
  as every other backend;
- **close drains the snapshot first**: ``close()`` writes a final
  ``metrics.prom`` snapshot (when ``output_path`` is configured) BEFORE the
  server stops — a run's last state survives the teardown, and
  ``MonitorMaster.close`` orders this ahead of the CSV close.

Metric names sanitize ``/``-namespaced event names into the Prometheus
grammar (``serve/frontend/r0/queue_depth`` ->
``dstpu_serve_frontend_r0_queue_dep``... see :func:`sanitize_metric_name`);
every metric is exported as a gauge carrying the last written value and its
step. :class:`TelemetryPump` is the optional push loop: a daemon thread
that periodically calls ``write_monitor_events(master, step)`` on whatever
sources it is given (engines, frontends, a ``ServingRouter``), so a
scraped endpoint stays fresh without the serving loops knowing about it.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.monitor.monitor import Event, Monitor
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.threads import make_lock, thread_role

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str, prefix: str = "dstpu") -> str:
    """Map an event name onto the Prometheus metric-name grammar
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``): every illegal character becomes ``_``
    and the configured prefix guards against a leading digit."""
    return f"{prefix}_{_NAME_RE.sub('_', name)}" if prefix \
        else _NAME_RE.sub("_", name)


class PrometheusExporter(Monitor):
    """Pull-based Prometheus-text snapshot endpoint over the monitor event
    path. ``write_events`` stores the latest value per name (one dict store
    per event, under a lock); ``GET /metrics`` on the embedded HTTP server
    renders the snapshot at scrape time. ``port=0`` binds an ephemeral port
    (tests; read it back from ``.port``)."""

    def __init__(self, config):
        super().__init__(config)
        self._lock = make_lock("monitor.prom.registry")
        self._values: Dict[str, Tuple[float, int]] = {}
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self.addr = getattr(config, "addr", "127.0.0.1")
        self.port = int(getattr(config, "port", 0) or 0)
        self.prefix = getattr(config, "prefix", "dstpu")
        self._snapshot_dir = ""
        if not self.enabled:
            return
        import os
        out = getattr(config, "output_path", "") or ""
        if out:
            self._snapshot_dir = os.path.join(
                out, getattr(config, "job_name", "") or "")
            os.makedirs(self._snapshot_dir, exist_ok=True)
        self._start_server()

    # -- event path ----------------------------------------------------- #

    def write_events(self, event_list: Iterable[Event]) -> None:
        if not self.enabled:
            return
        with self._lock:
            for name, value, step in event_list:
                self._values[name] = (float(value), int(step))

    # -- scrape side ---------------------------------------------------- #

    def render(self) -> str:
        """The Prometheus text exposition (format 0.0.4) of the current
        snapshot — what ``GET /metrics`` serves and what the close-time
        ``metrics.prom`` file contains. Every metric is a gauge; the event
        step rides along as a second ``<metric>_step`` gauge so a dashboard
        can tell how fresh a rollup is without a label-cardinality cost."""
        with self._lock:
            values = dict(self._values)
        lines: List[str] = []
        for name in sorted(values):
            value, step = values[name]
            metric = sanitize_metric_name(name, self.prefix)
            lines.append(f"# HELP {metric} {name}")
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {value!r}")
            lines.append(f"{metric}_step {step}")
        lines.append("")
        return "\n".join(lines)

    @property
    def url(self) -> Optional[str]:
        return f"http://{self.addr}:{self.port}/metrics" \
            if self._server is not None else None

    def _start_server(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (http.server API)
                if self.path.split("?")[0].rstrip("/") not in ("",
                                                               "/metrics"):
                    self.send_error(404)
                    return
                body = exporter.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):    # scrapes must not spam stderr
                pass

        try:
            self._server = ThreadingHTTPServer((self.addr, self.port),
                                               _Handler)
        except OSError as e:       # port taken: degrade, never kill the run
            logger.warning(f"prometheus exporter cannot bind "
                           f"{self.addr}:{self.port} ({e}); disabled")
            self.enabled = False
            self._server = None
            return
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dstpu-prom-export", daemon=True)
        self._thread.start()
        logger.info(f"prometheus exporter serving on {self.url}")

    def close(self):
        """Write the final snapshot (``metrics.prom``) and stop the server.
        Idempotent; ``MonitorMaster.close`` calls this BEFORE the CSV close
        so the drained snapshot is on disk with the rest of the run."""
        if self._snapshot_dir and self._values:
            import os
            try:
                with open(os.path.join(self._snapshot_dir,
                                       "metrics.prom"), "w") as f:
                    f.write(self.render())
            except OSError as e:  # a failing snapshot must not mask teardown
                logger.warning(f"prometheus snapshot write failed: {e}")
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join()
            self._thread = None


class TelemetryPump:
    """Optional push loop feeding a monitor from live sources: a daemon
    thread that every ``interval_s`` calls
    ``source.write_monitor_events(monitor, step)`` for each source (an
    engine, a frontend, a ``ServingRouter`` — anything with that surface),
    with ``step`` incrementing per tick. The serving loops stay oblivious;
    a scraped :class:`PrometheusExporter` (or any backend) stays fresh.
    ``close()`` runs one final pump so the last tick's state is never
    lost."""

    def __init__(self, monitor, sources, interval_s: float = 1.0):
        self.monitor = monitor
        self.sources = list(sources)
        self.interval_s = float(interval_s)
        self.step = 0  # threadlint: guarded-by=monitor.telemetry.step
        self._step_lock = make_lock("monitor.telemetry.step")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def pump_once(self) -> int:
        """One synchronous fan-in tick; returns the step it stamped. The
        step is RESERVED under its lock up front (the pump thread and a
        caller-side final drain both tick — threadlint TL003), so
        concurrent ticks stamp distinct steps; the slow source fan-in
        itself runs unlocked."""
        with self._step_lock:
            step = self.step
            self.step += 1
        for src in self.sources:
            try:
                src.write_monitor_events(self.monitor, step)
            except Exception as e:   # telemetry must never kill serving
                logger.warning(f"telemetry pump source "
                               f"{type(src).__name__} failed: {e}")
        return step

    def start(self) -> "TelemetryPump":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="dstpu-telemetry", daemon=True)
        self._thread.start()
        return self

    @thread_role("dstpu-telemetry")
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.pump_once()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.pump_once()           # final drain: the last state lands

    def __enter__(self) -> "TelemetryPump":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
