"""Unified span tracing: one Perfetto-exportable timeline across every lane.

PRs 3-6 turned the hot paths into overlapped async pipelines (double-buffered
decode, multi-step train dispatch, fetch/step/upload offload groups, rolling
async checkpoints), but observability stayed flat ``(name, value, step)``
aggregates — you could see that a bubble existed, never *where* it sat
relative to a dispatch, a D2H drain, upload-lane work, or a committer stall.
This module is the timeline: every pipeline lane records **spans** (named
intervals with monotonic-clock endpoints) into a per-thread preallocated ring
buffer, and an exporter writes Chrome-trace/Perfetto JSON where each lane
(step loop, prefetch producer, host-Adam workers, upload lane, AIO swapper,
checkpoint writers, committer) is its own named track — the overlap structure
becomes visually auditable in https://ui.perfetto.dev.

Design constraints (the regimes PRs 3-6 gated must survive tracing ON):

- **zero device syncs**: spans only ever read ``time.perf_counter()``; no
  recording path touches a jax array. jaxlint JL008 statically polices that
  span context managers in hot-path modules never *enclose* a blocking fetch
  outside the policed drain names, so tracing can't quietly reintroduce the
  per-step host sync the async loops removed.
- **no allocation-heavy formatting on the hot path**: a record is one small
  tuple stored into a preallocated slot (``ring[i % cap] = rec``); names are
  interned literals at the call sites; all JSON formatting happens at export
  time, off the steady-state loop.
- **bounded memory**: each thread keeps only the newest ``ring_size`` spans.
  That bound is also the **flight recorder** — after a crash the rings hold
  the final steps' timeline, dumped to ``trace_crash.json`` by the
  fault-injection kill/raise hooks and fatal engine teardown (and the normal
  rings export from an atexit hook), so a preempted or wedged run leaves a
  readable timeline (pairs with ``train_bench.py --preempt``).
- **true no-op when disabled**: ``add()`` is a two-instruction early return
  and ``span()`` hands back a shared no-op context manager; hot-path call
  sites additionally guard on ``tracer.enabled`` so disabled runs don't even
  stamp clocks for the trace.

Two recording APIs, matching two call-site shapes:

- ``tracer.add(name, t0, t1, lane=..., **args)`` — record a COMPLETED span
  from ``perf_counter`` timestamps the call site already took for its stats
  counters. This is the hot-path form: the five ``monitor/`` stat classes
  and the tracer aggregate the *same* measured intervals (one clock, one
  measurement — the stats are per-window aggregations of exactly the spans
  the timeline shows, not a parallel set of hand-rolled timers).
- ``with tracer.span(name, lane=..., **args):`` — context-manager form for
  worker lanes (producers, writers, committers, kernel chunks) where the
  span IS the timing.

``instant(name)`` marks a point event (faults, admissions); ``counter(name,
value)`` records a Perfetto counter track sample (queue depths).

Tracks: by default a span lands on its recording THREAD's track (threads in
this tree are descriptively named: ``dstpu-prefetch``, ``dstpu-hostopt_*``,
``dstpu-offload-upload``, ``ckpt-writer_*``, ``dstpu-ckpt-commit``). A
``lane="train/step"`` argument overrides the track name — used by the main
thread, which multiplexes several logical lanes (dispatch/drain phases,
checkpoint snapshots) that should render as their own rows. Lanes are scoped
per thread (two threads recording the same lane name get two tracks), so B/E
nesting within a track is always well-formed.

Enable via ``DSTPU_TRACE=<dir>`` (arms in ``deepspeed_tpu.initialize`` and
the v2 inference engine) or ``config.monitor.trace`` — docs/OBSERVABILITY.md
walks the taxonomy, Perfetto workflow, and overhead numbers.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.threads import make_lock

_ENV_VAR = "DSTPU_TRACE"
_ENV_RING = "DSTPU_TRACE_RING"
_ENV_REQ_LANES = "DSTPU_TRACE_REQ_LANES"

#: default spans retained per thread (the flight-recorder window)
DEFAULT_RING_SIZE = 16384

#: per-request ``serve/req/u<uid>`` lanes exported under their OWN track —
#: beyond this window (newest by last activity), retired requests' lanes are
#: recycled onto a bounded pool of ``serve/req/recycled/<k>`` tracks (the
#: exporter-side mirror of the dead-ring sweep: a long serving run must not
#: grow one timeline row per uid forever)
DEFAULT_REQ_LANE_WINDOW = 64

#: lanes subject to the recycling window
_REQ_LANE_RE = re.compile(r"^serve/req/u\d+$")

# record kinds (Chrome trace phase at export: span -> B/E pair)
_SPAN, _INSTANT, _COUNTER = "X", "i", "C"


#: dead threads' rings retained for export/crash dumps (a finished prefetch
#: producer's spans must still reach the timeline) — beyond this, the OLDEST
#: dead rings are pruned at ring registration so thread churn (per-epoch
#: producers, rebuilt writer pools) cannot grow memory without bound
MAX_DEAD_RINGS = 32


class _Ring:
    """One thread's preallocated record ring. Single writer (the owning
    thread), lock-free: ``buf[idx % cap] = rec; idx += 1``. Readers (export)
    snapshot racily — a slot is either an old record or a new one, never a
    torn value (CPython list-slot stores are atomic)."""

    __slots__ = ("buf", "idx", "cap", "thread_name", "thread_id", "thread")

    def __init__(self, cap: int, thread: threading.Thread):
        self.buf: List[Optional[tuple]] = [None] * cap
        self.idx = 0
        self.cap = cap
        self.thread_name = thread.name
        self.thread_id = thread.ident or 0
        self.thread = thread   # liveness probe for dead-ring pruning

    def add(self, rec: tuple) -> None:
        self.buf[self.idx % self.cap] = rec
        self.idx += 1

    def snapshot(self) -> List[tuple]:
        """Records in insertion order, oldest kept first (newest ``cap``)."""
        n = self.idx
        if n <= self.cap:
            return [r for r in self.buf[:n] if r is not None]
        i = n % self.cap
        return [r for r in self.buf[i:] + self.buf[:i] if r is not None]


class _NoopSpan:
    """Shared do-nothing context manager handed out while tracing is
    disabled — zero per-call allocation on the disabled path."""

    __slots__ = ()
    seconds = 0.0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


class Span:
    """Context manager recording one interval on exit; ``.seconds`` is valid
    after exit (call sites may feed it to their stats counters)."""

    __slots__ = ("_tracer", "name", "lane", "args", "t0", "t1")

    def __init__(self, tracer: "Tracer", name: str, lane: Optional[str],
                 args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.lane = lane
        self.args = args
        self.t0 = 0.0
        self.t1 = 0.0

    def __enter__(self) -> "Span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.t1 = time.perf_counter()
        self._tracer._record((_SPAN, self.name, self.t0, self.t1, self.lane,
                              self.args))
        return False

    @property
    def seconds(self) -> float:
        return self.t1 - self.t0


class Tracer:
    """The process-wide tracer (module singleton: :data:`tracer`)."""

    def __init__(self):
        self.enabled = False
        self.trace_dir = ""
        self.ring_size = DEFAULT_RING_SIZE
        self.req_lane_window = DEFAULT_REQ_LANE_WINDOW
        self._rings: List[_Ring] = []
        self._local = threading.local()
        self._reg_lock = make_lock("monitor.trace.registry")
        self._atexit_installed = False
        self._crash_path: Optional[str] = None
        # one simultaneous (perf_counter, unix) pair: trace_merge.py maps
        # every file's perf-based timestamps onto one wall-clock axis with it
        self._clock_sync = (time.perf_counter(), time.time())

    # ------------------------------------------------------------------ #
    # configuration
    # ------------------------------------------------------------------ #

    def configure(self, trace_dir: str = "", enabled: Optional[bool] = None,
                  ring_size: Optional[int] = None,
                  req_lane_window: Optional[int] = None) -> "Tracer":
        """Enable (or reconfigure) tracing. ``trace_dir`` nonempty implies
        enabled and is where the exporter + flight recorder write; an empty
        dir with ``enabled=True`` records rings without an export target
        (tests, in-process overhead measurement). ``req_lane_window`` bounds
        how many per-request ``serve/req/u<uid>`` lanes export under their
        own track (older ones recycle onto a pooled track set)."""
        if trace_dir:
            self.trace_dir = trace_dir
        if ring_size:
            self.ring_size = max(16, int(ring_size))
        if req_lane_window is not None:
            self.req_lane_window = max(0, int(req_lane_window))
        if enabled is None:
            enabled = bool(trace_dir) or self.enabled
        self.enabled = bool(enabled)
        if self.enabled and not self._atexit_installed:
            self._atexit_installed = True
            atexit.register(self._atexit_export)
        return self

    def reset(self) -> None:
        """Drop every ring and disable (tests). Threads re-register their
        rings lazily on the next record."""
        with self._reg_lock:
            self._rings = []
        self._local = threading.local()
        self.enabled = False
        self.trace_dir = ""
        self.ring_size = DEFAULT_RING_SIZE
        self.req_lane_window = DEFAULT_REQ_LANE_WINDOW
        self._crash_path = None
        self._clock_sync = (time.perf_counter(), time.time())

    # ------------------------------------------------------------------ #
    # recording
    # ------------------------------------------------------------------ #

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = _Ring(self.ring_size, threading.current_thread())
            self._local.ring = ring
            with self._reg_lock:
                # registration is the rare, already-locked path: prune the
                # OLDEST dead rings beyond the retention bound here so
                # thread churn never grows the registry without bound
                dead = [r for r in self._rings if not r.thread.is_alive()]
                if len(dead) > MAX_DEAD_RINGS:
                    drop = set(map(id, dead[:len(dead) - MAX_DEAD_RINGS]))
                    self._rings = [r for r in self._rings
                                   if id(r) not in drop]
                self._rings.append(ring)
        return ring

    def _record(self, rec: tuple) -> None:
        if self.enabled:
            self._ring().add(rec)

    def register_thread(self) -> None:
        """Pre-register the calling thread's ring so later records are
        lock-free appends. A thread's FIRST record otherwise acquires the
        registry lock at whatever call site it happens to land on — callers
        that record under their own locks use this to keep the registry
        acquisition outside them (lock-order hygiene; the locksan bench gate
        demands every observed acquisition order be statically explained)."""
        if self.enabled:
            self._ring()

    def add(self, name: str, t0: float, t1: float, lane: Optional[str] = None,
            **args: Any) -> None:
        """Record a completed span from ``time.perf_counter()`` endpoints the
        call site already measured (the zero-extra-clock hot-path form)."""
        if not self.enabled:
            return
        self._ring().add((_SPAN, name, t0, t1, lane, args or None))

    def span(self, name: str, lane: Optional[str] = None, **args: Any):
        """Context manager recording ``name`` over the with-body. Returns a
        shared no-op when disabled."""
        if not self.enabled:
            return _NOOP
        return Span(self, name, lane, args or None)

    def instant(self, name: str, lane: Optional[str] = None, **args: Any) -> None:
        if not self.enabled:
            return
        now = time.perf_counter()
        self._ring().add((_INSTANT, name, now, now, lane, args or None))

    def counter(self, name: str, value: float, lane: Optional[str] = None) -> None:
        if not self.enabled:
            return
        now = time.perf_counter()
        self._ring().add((_COUNTER, name, now, now, lane,
                          {"value": float(value)}))

    # ------------------------------------------------------------------ #
    # aggregation (the stats classes' view of the same measurements)
    # ------------------------------------------------------------------ #

    def summary(self) -> Dict[str, Tuple[int, float]]:
        """``{span name: (count, total seconds)}`` over everything currently
        retained — the derived-aggregation view the monitor stat classes
        mirror per window (tests cross-check the two against each other)."""
        out: Dict[str, Tuple[int, float]] = {}
        with self._reg_lock:
            rings = list(self._rings)
        for ring in rings:
            for rec in ring.snapshot():
                if rec[0] != _SPAN:
                    continue
                _, name, t0, t1, _, _ = rec
                cnt, tot = out.get(name, (0, 0.0))
                out[name] = (cnt + 1, tot + (t1 - t0))
        return out

    def iter_records(self) -> Iterator[tuple]:
        """Snapshot every retained raw record ``(kind, name, t0, t1, lane,
        args)`` across all rings — benches and tests assert on request flow
        chains (spans sharing a ``trace_id`` arg) without exporting."""
        with self._reg_lock:
            rings = list(self._rings)
        for ring in rings:
            for rec in ring.snapshot():
                yield rec

    # ------------------------------------------------------------------ #
    # export
    # ------------------------------------------------------------------ #

    def _recycle_req_lanes(self, snaps) -> Dict[str, str]:
        """Remap retired ``serve/req/u<uid>`` lanes onto a bounded
        recycled-track pool. Keep/retire is decided over the UNION of all
        rings (a request's lane is written from several threads — engine,
        prefill worker, health; a per-ring window would keep a named track
        in one ring while recycling the same uid in another, splitting one
        request across rows and growing named rows O(window x rings)): the
        newest ``req_lane_window`` lanes (by last recorded activity
        anywhere) keep their name; every older lane is greedily
        interval-packed onto ``serve/req/recycled/<k>`` such that no two
        time-overlapping requests share a slot — per-thread tracks render
        a subset of a slot's lanes, so B/E nesting stays well-formed.
        Mirrors the dead-ring sweep: a long run's timeline stays bounded
        in named rows, not one per uid forever."""
        out: Dict[str, str] = {}
        window = self.req_lane_window
        extents: Dict[str, Tuple[float, float]] = {}
        for _ring, snap in snaps:
            for rec in snap:
                lane = rec[4]
                if not lane or not _REQ_LANE_RE.match(lane):
                    continue
                t0, t1 = rec[2], rec[3]
                if t1 <= t0:           # match the exporter's epsilon E
                    t1 = t0 + 1e-9
                lo, hi = extents.get(lane, (t0, t1))
                extents[lane] = (min(lo, t0), max(hi, t1))
        if len(extents) <= window:
            return out
        by_recent = sorted(extents, key=lambda l: extents[l][1],
                           reverse=True)
        keep = set(by_recent[:window])
        retired = sorted((l for l in extents if l not in keep),
                         key=lambda l: extents[l][0])
        pools: List[float] = []            # last span end per recycled track
        for lane in retired:
            lo, hi = extents[lane]
            slot = None
            for k, end in enumerate(pools):
                if end <= lo:              # equal-ts boundary is safe: the
                    slot = k               # sort ties close E before B
                    break
            if slot is None:
                pools.append(hi)
                slot = len(pools) - 1
            else:
                pools[slot] = max(pools[slot], hi)
            out[lane] = f"serve/req/recycled/{slot}"
        return out

    def _events(self) -> List[dict]:
        """Chrome-trace event list: metadata naming each track, then B/E
        pairs (plus instants/counters), globally sorted so every track's
        stack nests. Tie rules at equal ts: E closes before B opens, longer
        B's open first (outer before inner), and record order breaks the
        remaining ties — zero-duration spans (coarse perf_counter ticks)
        get an epsilon-long E so a span's end can never sort ahead of its
        own begin.

        Spans whose args carry a ``trace_id`` additionally emit Perfetto
        FLOW events (``ph`` s/t/f, one chain per trace_id) binding the
        request's hops — router placement, prefill, KV handoff, decode
        stints, failover migration — into one causal chain across lanes and
        threads (and, through ``scripts/trace_merge.py``, across files)."""
        pid = os.getpid()
        with self._reg_lock:
            rings = list(self._rings)
        snaps = [(ring, ring.snapshot()) for ring in rings]
        lane_map = self._recycle_req_lanes(snaps)
        tids: Dict[Tuple[int, Optional[str]], int] = {}
        meta: List[dict] = [{"ph": "M", "name": "process_name", "pid": pid,
                             "tid": 0, "args": {"name": "deepspeed_tpu"}}]
        body: List[Tuple[float, int, float, int, dict]] = []
        # trace_id -> [(t0, record idx, tid, ts_us)] of its spans
        flows: Dict[Any, List[Tuple[float, int, int, float]]] = {}

        def tid_for(ring: _Ring, lane: Optional[str]) -> int:
            key = (ring.thread_id, lane)
            tid = tids.get(key)
            if tid is None:
                tid = len(tids) + 1
                tids[key] = tid
                meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                             "tid": tid,
                             "args": {"name": lane or ring.thread_name}})
            return tid

        idx = 0
        for ring, snap in snaps:
            for rec in snap:
                kind, name, t0, t1, lane, args = rec
                if lane is not None and lane_map:
                    lane = lane_map.get(lane, lane)
                tid = tid_for(ring, lane)
                ts0 = t0 * 1e6
                if kind == _SPAN and args and "trace_id" in args:
                    flows.setdefault(args["trace_id"], []).append(
                        (t0, idx, tid, ts0))
                if kind == _SPAN:
                    # coarse clocks can stamp t1 == t0; the E must still
                    # land strictly after its own B
                    if t1 <= t0:
                        t1 = t0 + 1e-9
                    dur = t1 - t0
                    b = {"ph": "B", "name": name, "pid": pid, "tid": tid,
                         "ts": ts0}
                    if args:
                        b["args"] = args
                    # equal (ts, dur) B's: LATER record first — a nested CM
                    # records the inner span before the outer, so record
                    # order descending puts the outer's B ahead
                    body.append((ts0, 1, -dur, -idx, b))
                    # equal-ts E's: earlier record first (inner closed first)
                    body.append((t1 * 1e6, 0, 0.0, idx,
                                 {"ph": "E", "name": name, "pid": pid,
                                  "tid": tid, "ts": t1 * 1e6}))
                elif kind == _INSTANT:
                    ev = {"ph": "i", "s": "t", "name": name, "pid": pid,
                          "tid": tid, "ts": ts0}
                    if args:
                        ev["args"] = args
                    body.append((ts0, 2, 0.0, idx, ev))
                else:  # counter
                    body.append((ts0, 2, 0.0, idx,
                                 {"ph": "C", "name": name, "pid": pid,
                                  "tid": tid, "ts": ts0, "args": args or {}}))
                idx += 1
        # flow chains: one s -> t... -> f sequence per trace_id, each event
        # anchored at its hop-span's begin (rank 2: it sorts after the B it
        # binds to). Single-hop ids emit nothing — a chain needs two ends.
        for flow_id, hops in flows.items():
            if len(hops) < 2:
                continue
            hops.sort(key=lambda h: (h[0], h[1]))
            last = len(hops) - 1
            for k, (_t0, ridx, tid, ts0) in enumerate(hops):
                ph = "s" if k == 0 else ("f" if k == last else "t")
                ev = {"ph": ph, "id": int(flow_id), "name": "serve/req",
                      "cat": "flow", "pid": pid, "tid": tid, "ts": ts0}
                if ph == "f":
                    ev["bp"] = "e"     # bind to the enclosing slice
                body.append((ts0, 2, 0.0, ridx, ev))
        body.sort(key=lambda item: item[:4])
        return meta + [ev for _, _, _, _, ev in body]

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome-trace JSON; returns the path (None when tracing
        is disabled or there is nowhere to write). Idempotent — call at
        teardown and from atexit; later calls overwrite with a superset."""
        if not self.enabled and path is None:
            return None
        if path is None:
            if not self.trace_dir:
                return None
            path = os.path.join(self.trace_dir, f"trace_{os.getpid()}.json")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": self._events(),
                       "displayTimeUnit": "ms",
                       "clockSync": self._clock_sync_doc()}, f)
        os.replace(tmp, path)
        return path

    def _clock_sync_doc(self) -> dict:
        """One simultaneous (perf_counter, unix) anchor in microseconds —
        ``scripts/trace_merge.py`` uses it to clock-align trace files from
        different processes (each process's perf_counter has its own epoch)
        onto a single merged timeline."""
        perf_s, unix_s = self._clock_sync
        return {"perf_us": perf_s * 1e6, "unix_us": unix_s * 1e6,
                "pid": os.getpid()}

    def crash_dump(self, reason: str = "") -> Optional[str]:
        """Flight-recorder dump: write the retained rings to
        ``trace_crash.json`` in the trace dir. Called on injected kills
        (BEFORE ``os._exit``, which skips atexit), on :class:`InjectedFault`
        raises, and on fatal engine teardown. First reason wins — a cascade
        of secondary failures must not overwrite the original timeline."""
        if not self.enabled or not self.trace_dir:
            return None
        if self._crash_path is not None:
            return self._crash_path
        path = os.path.join(self.trace_dir, "trace_crash.json")
        try:
            events = self._events()
            if reason:
                events.append({"ph": "i", "s": "g", "name": f"crash: {reason}",
                               "pid": os.getpid(), "tid": 0,
                               "ts": time.perf_counter() * 1e6})
            os.makedirs(self.trace_dir, exist_ok=True)
            doc = {"traceEvents": events, "displayTimeUnit": "ms",
                   "clockSync": self._clock_sync_doc()}
            # when the lock-order sanitizer is armed, its acquisition
            # graph/cycle/blocking report rides the same dump: the one
            # postmortem a wedged or crashing run leaves behind
            # (docs/THREADLINT.md)
            from deepspeed_tpu.utils import locksan
            if locksan.enabled():
                doc["locksan"] = locksan.report()
            with open(path, "w") as f:
                json.dump(doc, f)
        except Exception as e:  # a failing dump must never mask the crash
            logger.warning(f"trace crash dump failed: {type(e).__name__}: {e}")
            return None
        self._crash_path = path
        logger.warning(f"flight recorder dumped to {path}"
                       + (f" ({reason})" if reason else ""))
        return path

    def _atexit_export(self) -> None:
        try:
            self.export()
        except Exception as e:  # pragma: no cover - depends on dying disk
            logger.warning(f"trace export at exit failed: "
                           f"{type(e).__name__}: {e}")


#: the process-wide tracer every instrumentation site records through
tracer = Tracer()


def install_from_env() -> Tracer:
    """Arm the tracer from ``$DSTPU_TRACE`` (a directory; no-op when unset).
    Called by ``deepspeed_tpu.initialize`` and the v2 inference engine so
    subprocess benches trace without touching user code; idempotent — an
    already-configured tracer wins."""
    if tracer.enabled:
        return tracer
    trace_dir = os.environ.get(_ENV_VAR, "").strip()
    if trace_dir:
        ring = int(os.environ.get(_ENV_RING, "0") or 0)
        lanes = os.environ.get(_ENV_REQ_LANES, "").strip()
        tracer.configure(trace_dir=trace_dir,
                         ring_size=ring or None,
                         req_lane_window=int(lanes) if lanes else None)
        logger.info(f"span tracing ARMED from ${_ENV_VAR}: {trace_dir}")
    return tracer
