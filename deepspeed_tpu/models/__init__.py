"""Model zoo: the BASELINE config ladder families (gpt2, llama/mistral, mixtral,
gpt-neox) plus the inference-container families (opt, falcon, phi, bert) —
matching the reference's model coverage (module_inject/containers,
inference/v2/model_implementations)."""

from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
from deepspeed_tpu.models.decoder import (DecoderConfig, DecoderLM,
                                          init_decoder_cache)
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, init_cache
from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
from deepspeed_tpu.models.diffusion import (DiffusionConfig,
                                            DiffusionPipeline,
                                            init_diffusion_inference)
