"""Model zoo for the BASELINE config ladder (gpt2, bert, llama, mixtral, neox)."""

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
