"""Model zoo for the BASELINE config ladder (gpt2, llama/mistral, mixtral)."""

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, init_cache
from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
