"""GPT-2 in flax.linen (BASELINE ladder config #1).

The reference has no in-repo GPT-2 (it trains HF/Megatron models through the
engine); this model zoo exists so the framework is runnable end-to-end standalone,
like the reference's ``tests/unit/simple_model.py`` fixtures but production-shaped.
Design: pre-LN transformer, learned positions, causal attention routed through
``deepspeed_tpu.ops.attention`` (jnp today, Pallas flash-attention when available).

The module maps a batch (dict with ``input_ids`` [B, T] and optional ``labels``)
to the mean next-token cross-entropy — matching the engine convention that
``model.apply(params, batch)`` returns the loss.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.ops.attention import dot_product_attention
from deepspeed_tpu.runtime.activation_checkpointing import apply_checkpointed_layers


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    mlp_ratio: int = 4
    dropout: float = 0.0
    eps: float = 1e-5        # HF GPT-2 layer_norm_epsilon
    dtype: Any = jnp.float32
    # activation checkpointing (parity: reference
    # runtime/activation_checkpointing/checkpointing.py; on TPU = jax.checkpoint
    # around each block, letting XLA re-materialise instead of storing activations)
    remat: bool = False
    remat_policy: Optional[str] = None
    # Ulysses sequence parallelism (parallel/ulysses.py): attention through
    # two all-to-alls on the 'seq' mesh axis; no-op when the mesh has no seq
    # axis. Requires n_head and T divisible by the seq axis size.
    sequence_parallel: bool = False
    # rows per chunk in the fused projection+CE loss (llama.py
    # chunked_causal_lm_loss). The head GEMM's M dim is chunk*(T-1): larger
    # chunks raise MXU efficiency, smaller bound the [chunk, T, V] transient.
    lm_loss_chunk: int = 4

    @classmethod
    def small(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        """Test-sized config (fixture-model analog of tests/unit/simple_model.py)."""
        defaults = dict(vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=4)
        defaults.update(kw)
        return cls(**defaults)


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, T, C = x.shape
        qkv = nn.Dense(3 * cfg.n_embd, dtype=cfg.dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        heads = lambda t: t.reshape(B, T, cfg.n_head, C // cfg.n_head)
        if cfg.sequence_parallel:
            from deepspeed_tpu.parallel.ulysses import sequence_parallel_attention
            out = sequence_parallel_attention(heads(q), heads(k), heads(v),
                                              causal=True)
        else:
            out = dot_product_attention(heads(q), heads(k), heads(v), causal=True)
        # tag for the selective remat policies ("attn_out_saveable"): saving
        # this [B, T, C] tensor lets backward skip recomputing the attention
        # kernel while everything else still rematerialises
        out = checkpoint_name(out.reshape(B, T, C), "attn_out")
        return nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(out)


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.Dense(cfg.mlp_ratio * cfg.n_embd, dtype=cfg.dtype, name="c_fc")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(h)


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.eps, dtype=cfg.dtype, name="ln_1")(x), deterministic)
        x = x + MLP(cfg, name="mlp")(nn.LayerNorm(epsilon=cfg.eps, dtype=cfg.dtype, name="ln_2")(x))
        return x


class GPT2LMHead(nn.Module):
    """Returns loss when batch has labels (or from shifted input_ids), else logits."""

    config: GPT2Config

    def setup(self):
        cfg = self.config
        self.wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, name="wte")
        self.wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype, name="wpe")
        self.blocks = [Block(cfg, name=f"h_{i}") for i in range(cfg.n_layer)]
        self.ln_f = nn.LayerNorm(epsilon=cfg.eps, dtype=cfg.dtype, name="ln_f")

    def __call__(self, batch, deterministic: bool = True):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        x = self.wte(input_ids) + self.wpe(jnp.arange(T)[None, :])
        pld_theta = batch.get("pld_theta") if isinstance(batch, dict) else None
        if pld_theta is not None:
            # progressive layer drop (engine-injected; parity: PLD hook
            # engine.py:1812 + runtime/progressive_layer_drop.py): deeper
            # layers drop with higher probability, whole-batch Bernoulli.
            # Composed INSIDE the checkpointed layer application so remat
            # still bounds activation memory.
            from deepspeed_tpu.runtime.progressive_layer_drop import (
                apply_layer_drop, pld_keep_prob)
            theta0 = pld_theta[0]
            key0 = batch["pld_rng"][0]

            def call_layer(mdl, h, i):
                x_new = mdl.blocks[i](h, deterministic)
                return apply_layer_drop(x_new, h,
                                        pld_keep_prob(i, cfg.n_layer, theta0),
                                        jax.random.fold_in(key0, i))

            def post_layer(x_new, h, i):
                return apply_layer_drop(x_new, h,
                                        pld_keep_prob(i, cfg.n_layer, theta0),
                                        jax.random.fold_in(key0, i))
        else:
            call_layer = lambda mdl, h, i: mdl.blocks[i](h, deterministic)
            post_layer = None
        # the scheduled ZeRO-3 walk lifts blocks to pure apply calls, which
        # cannot thread flax dropout RNGs — only offer it when deterministic
        x = apply_checkpointed_layers(self, x, call_layer, cfg.n_layer,
                                      cfg.remat, cfg.remat_policy,
                                      layers=self.blocks if deterministic else None,
                                      layer_args=(deterministic,),
                                      post_layer=post_layer)
        x = self.ln_f(x)

        if labels is None and isinstance(batch, dict) and "input_ids" in batch:
            labels = input_ids  # LM objective: predict next token of the same ids
        if labels is None:
            return self.wte.attend(x.astype(jnp.float32))  # tied head, fp32 logits
        # fused chunked projection+CE: the [B, T, V] logits never materialise
        # (see models/llama.py chunked_causal_lm_loss)
        from deepspeed_tpu.models.llama import chunked_causal_lm_loss
        return chunked_causal_lm_loss(x, self.wte.embedding, labels,
                                      batch_chunk=cfg.lm_loss_chunk)
