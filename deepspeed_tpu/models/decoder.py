"""Configurable decoder-only LM covering the OPT / Falcon / Phi / GPT-NeoX families.

Parity role: the reference serves these families through per-model containers and
implementations (``module_inject/containers/{opt,gptneox}.py``,
``inference/v2/model_implementations/{opt,falcon,phi}``). TPU-native re-design:
the families differ only in a handful of structural flags (norm type, activation,
rotary fraction vs learned positions, parallel residual, biases), so the zoo
carries ONE flax module — :class:`DecoderLM` — specialised by
:class:`DecoderConfig` classmethods, with canonical parameter names (``wq``,
``mlp/w_up``...) shared with the v2 ragged adapter (``inference/v2/ragged_model``).

Family structural facts encoded here:
  - **OPT**: pre-LN, learned positions offset by 2, ReLU MLP, biases everywhere,
    LM head tied to the embedding.
  - **Falcon (7B lineage)**: parallel attention+MLP off one layernorm, rotary,
    GELU, bias-free projections, (multi-query via num_key_value_heads).
  - **Phi (phi-2 lineage)**: parallel block off one layernorm, *partial* rotary
    (rotary_pct < 1), GELU, biases on projections.
  - **GPT-NeoX**: parallel residual with TWO norms (attn from ln1(x), MLP from
    ln2(x)), partial rotary, GELU, biases.
  - **GPT-J**: parallel block off one layernorm, partial *interleaved* rotary
    (matches this zoo's native convention), no attention biases, MLP biases,
    untied LM head with bias.
  - **BLOOM**: sequential pre-LN, ALiBi position bias (no rotary/learned
    positions), layernorm directly after the embedding, fused-qkv ancestry,
    tied LM head.

Call paths match the llama zoo protocol: ``__call__(batch) -> loss``,
``forward_logits``, ``decode(ids, cache, index)`` with the dense KV cache from
``init_decoder_cache`` (inference v1), plus the v2 ragged adapter below.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import (causal_lm_loss, repeat_kv,
                                        rope_frequencies, _window_bias)
from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.ops.attention import dot_product_attention, reference_attention
from deepspeed_tpu.runtime.activation_checkpointing import apply_checkpointed_layers


@dataclass
class DecoderConfig:
    family: str = "opt"
    vocab_size: int = 50272
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    num_key_value_heads: Optional[int] = None   # None -> MHA
    max_position_embeddings: int = 2048
    norm: str = "ln"                 # "ln" | "rms"
    activation: str = "relu"  # "relu" | "gelu" (tanh) | "gelu_exact" | "silu" | "swiglu"
    rope_theta: Optional[float] = None          # None -> no rotary
    rotary_pct: float = 1.0                     # fraction of head_dim that rotates
    learned_pos: bool = False
    pos_offset: int = 0              # OPT: positions offset by 2 in the table
    alibi: bool = False              # BLOOM: per-head linear position bias
    embed_norm: bool = False         # BLOOM: layernorm right after the embedding
    attn_scale: Optional[float] = None  # GPT-Neo: 1.0 (no 1/sqrt(D) scaling)
    local_window: Optional[int] = None  # GPT-Neo: sliding window for 'local' layers
    # per-layer attention kinds ("global" | "local"), e.g. GPT-Neo alternates;
    # None -> all global
    attention_layers: Optional[tuple] = None
    parallel_block: bool = False     # attn + mlp in one residual add
    parallel_dual_norm: bool = False # neox: MLP from ln2(x) instead of ln1(x)
    qkv_bias: bool = True
    out_bias: bool = True
    mlp_bias: bool = True
    tied_lm_head: bool = False
    head_bias: bool = False          # phi/gpt-j: bias on the LM head projection
    # Ulysses sequence parallelism (parallel/ulysses.py): attention through
    # two all-to-alls on the 'seq' mesh axis. Incompatible with ALiBi and
    # local-window layers (both need a bias the SP path doesn't carry).
    sequence_parallel: bool = False
    eps: float = 1e-5
    # fused projection+CE chunk rows (llama.py chunked_causal_lm_loss)
    lm_loss_chunk: int = 4
    dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: Optional[str] = None

    def __post_init__(self):
        if not self.sequence_parallel:
            return
        has_local = any(kind == "local" for kind in self.attention_layers or ())
        if self.alibi or self.local_window is not None or has_local:
            raise ValueError(
                "sequence_parallel is incompatible with alibi, local_window, "
                "and 'local' entries in attention_layers (the Ulysses path "
                "carries no attention bias); disable sequence_parallel or "
                "remove those settings")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self) -> int:
        return self.num_key_value_heads or self.num_attention_heads

    @property
    def rotary_dim(self) -> Optional[int]:
        if self.rope_theta is None:
            return None
        rd = int(self.head_dim * self.rotary_pct)
        return rd - rd % 2

    # ---- family presets (sizes per public model cards) -------------------- #

    @classmethod
    def opt_125m(cls, **kw):
        d = dict(family="opt", vocab_size=50272, hidden_size=768,
                 intermediate_size=3072, num_hidden_layers=12,
                 num_attention_heads=12, learned_pos=True, pos_offset=2,
                 activation="relu", tied_lm_head=True)
        d.update(kw); return cls(**d)

    @classmethod
    def opt_1b3(cls, **kw):
        d = dict(family="opt", vocab_size=50272, hidden_size=2048,
                 intermediate_size=8192, num_hidden_layers=24,
                 num_attention_heads=32, learned_pos=True, pos_offset=2,
                 activation="relu", tied_lm_head=True)
        d.update(kw); return cls(**d)

    @classmethod
    def falcon_7b(cls, **kw):
        d = dict(family="falcon", vocab_size=65024, hidden_size=4544,
                 intermediate_size=4 * 4544, num_hidden_layers=32,
                 num_attention_heads=71, num_key_value_heads=1,
                 rope_theta=10000.0, activation="gelu", parallel_block=True,
                 qkv_bias=False, out_bias=False, mlp_bias=False)
        d.update(kw); return cls(**d)

    @classmethod
    def phi_2(cls, **kw):
        d = dict(family="phi", vocab_size=51200, hidden_size=2560,
                 intermediate_size=10240, num_hidden_layers=32,
                 num_attention_heads=32, rope_theta=10000.0, rotary_pct=0.4,
                 activation="gelu", parallel_block=True)
        d.update(kw); return cls(**d)

    @classmethod
    def gpt_neox_20b(cls, **kw):
        d = dict(family="gpt_neox", vocab_size=50432, hidden_size=6144,
                 intermediate_size=24576, num_hidden_layers=44,
                 num_attention_heads=64, rope_theta=10000.0, rotary_pct=0.25,
                 activation="gelu", parallel_block=True, parallel_dual_norm=True)
        d.update(kw); return cls(**d)

    @classmethod
    def bloom_560m(cls, **kw):
        d = dict(family="bloom", vocab_size=250880, hidden_size=1024,
                 intermediate_size=4096, num_hidden_layers=24,
                 num_attention_heads=16, alibi=True, embed_norm=True,
                 activation="gelu", tied_lm_head=True)
        d.update(kw); return cls(**d)

    @classmethod
    def gptj_6b(cls, **kw):
        d = dict(family="gptj", vocab_size=50400, hidden_size=4096,
                 intermediate_size=16384, num_hidden_layers=28,
                 num_attention_heads=16, rope_theta=10000.0, rotary_pct=0.25,
                 activation="gelu", parallel_block=True, qkv_bias=False,
                 out_bias=False, head_bias=True)
        d.update(kw); return cls(**d)

    @classmethod
    def tiny(cls, family: str = "opt", **kw):
        base = {
            "opt": dict(learned_pos=True, pos_offset=2, activation="relu",
                        tied_lm_head=True),
            "falcon": dict(rope_theta=10000.0, activation="gelu",
                           parallel_block=True, qkv_bias=False, out_bias=False,
                           mlp_bias=False, num_key_value_heads=1),
            "phi": dict(rope_theta=10000.0, rotary_pct=0.5, activation="gelu",
                        parallel_block=True),
            "gpt_neox": dict(rope_theta=10000.0, rotary_pct=0.5, activation="gelu",
                             parallel_block=True, parallel_dual_norm=True),
            "bloom": dict(alibi=True, embed_norm=True, activation="gelu",
                          tied_lm_head=True),
            "gptj": dict(rope_theta=10000.0, rotary_pct=0.5, activation="gelu",
                         parallel_block=True, qkv_bias=False, out_bias=False,
                         head_bias=True),
            "gpt_neo": dict(learned_pos=True, activation="gelu",
                            qkv_bias=False, tied_lm_head=True, attn_scale=1.0,
                            local_window=8,
                            attention_layers=("global", "local")),
        }[family]
        d = dict(family=family, vocab_size=256, hidden_size=64,
                 intermediate_size=128, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=128)
        d.update(base); d.update(kw)
        return cls(**d)


class _Norm(nn.Module):
    kind: str
    eps: float
    dtype: Any

    @nn.compact
    def __call__(self, x):
        xf = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        if self.kind == "rms":
            var = jnp.mean(xf * xf, axis=-1, keepdims=True)
            y = xf * jax.lax.rsqrt(var + self.eps) * scale
        else:
            bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],))
            mean = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.var(xf, axis=-1, keepdims=True)
            y = (xf - mean) * jax.lax.rsqrt(var + self.eps) * scale + bias
        return y.astype(self.dtype)


def alibi_slopes(n_heads: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (geometric in 2^(-8/n), with the standard
    interpolation for non-power-of-two head counts). fp32, shape [H]."""
    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start ** (i + 1) for i in range(n)]
    if math.log2(n_heads).is_integer():
        s = pow2(n_heads)
    else:
        closest = 2 ** math.floor(math.log2(n_heads))
        s = pow2(closest) + pow2(2 * closest)[0::2][: n_heads - closest]
    return jnp.asarray(s, dtype=jnp.float32)


def alibi_bias(q_positions: jnp.ndarray, k_positions: jnp.ndarray,
               n_heads: int) -> jnp.ndarray:
    """Additive attention bias [B, H, Tq, Tk]: slope_h * (k_pos - q_pos).
    Shift-invariant per softmax row, so it matches the reference's
    key-absolute-position formulation exactly."""
    rel = (k_positions[:, None, None, :] - q_positions[:, None, :, None])
    return alibi_slopes(n_heads)[None, :, None, None] * rel.astype(jnp.float32)


def _partial_rope(x, positions, theta: float, rotary_dim: Optional[int]):
    """[B, T, H, D] with per-row positions [B, T]; rotates the first rotary_dim."""
    D = x.shape[-1]
    rd = rotary_dim or D
    xr, xp = x[..., :rd], x[..., rd:]
    freqs = rope_frequencies(rd, theta)
    angles = positions[..., None].astype(jnp.float32) * freqs
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1 = xr[..., 0::2].astype(jnp.float32)
    x2 = xr[..., 1::2].astype(jnp.float32)
    rot = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                    axis=-1).reshape(xr.shape).astype(x.dtype)
    return jnp.concatenate([rot, xp], axis=-1) if rd < D else rot


class _Mlp(nn.Module):
    config: DecoderConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        init = nn.initializers.normal(0.02)
        ff, hid = cfg.intermediate_size, cfg.hidden_size
        if cfg.activation == "swiglu":
            w_gate = self.param("w_gate", init, (hid, ff), jnp.float32)
            w_up = self.param("w_up", init, (hid, ff), jnp.float32)
            h = nn.silu(x @ w_gate.astype(cfg.dtype)) * (x @ w_up.astype(cfg.dtype))
        else:
            w_up = self.param("w_up", init, (hid, ff), jnp.float32)
            h = x @ w_up.astype(cfg.dtype)
            if cfg.mlp_bias:
                h = h + self.param("b_up", nn.initializers.zeros, (ff,), jnp.float32) \
                    .astype(cfg.dtype)
            if cfg.activation == "gelu":
                h = nn.gelu(h)
            elif cfg.activation == "gelu_exact":
                h = nn.gelu(h, approximate=False)
            elif cfg.activation == "silu":
                h = nn.silu(h)
            else:
                h = nn.relu(h)
        w_down = self.param("w_down", init, (ff, hid), jnp.float32)
        out = h @ w_down.astype(cfg.dtype)
        if cfg.mlp_bias and cfg.activation != "swiglu":
            out = out + self.param("b_down", nn.initializers.zeros, (hid,),
                                   jnp.float32).astype(cfg.dtype)
        return out


class DecoderBlock(nn.Module):
    config: DecoderConfig
    window: Optional[int] = None   # sliding-window span for 'local' layers

    def setup(self):
        cfg = self.config
        init = nn.initializers.normal(0.02)
        H, Hkv, D, hid = (cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim,
                          cfg.hidden_size)
        self.ln1 = _Norm(cfg.norm, cfg.eps, cfg.dtype, name="ln1")
        if not cfg.parallel_block or cfg.parallel_dual_norm:
            self.ln2 = _Norm(cfg.norm, cfg.eps, cfg.dtype, name="ln2")
        self.wq = self.param("wq", init, (hid, H * D), jnp.float32)
        self.wk = self.param("wk", init, (hid, Hkv * D), jnp.float32)
        self.wv = self.param("wv", init, (hid, Hkv * D), jnp.float32)
        self.wo = self.param("wo", init, (H * D, hid), jnp.float32)
        if cfg.qkv_bias:
            self.bq = self.param("bq", nn.initializers.zeros, (H * D,), jnp.float32)
            self.bk = self.param("bk", nn.initializers.zeros, (Hkv * D,), jnp.float32)
            self.bv = self.param("bv", nn.initializers.zeros, (Hkv * D,), jnp.float32)
        if cfg.out_bias:
            self.bo = self.param("bo", nn.initializers.zeros, (hid,), jnp.float32)
        self.mlp = _Mlp(cfg, name="mlp")

    def _qkv(self, h, positions):
        cfg = self.config
        B, T, _ = h.shape
        H, Hkv, D = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
        dt = cfg.dtype
        q = h @ self.wq.astype(dt)
        k = h @ self.wk.astype(dt)
        v = h @ self.wv.astype(dt)
        if cfg.qkv_bias:
            q = q + self.bq.astype(dt)
            k = k + self.bk.astype(dt)
            v = v + self.bv.astype(dt)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, Hkv, D)
        v = v.reshape(B, T, Hkv, D)
        if cfg.rope_theta is not None:
            q = _partial_rope(q, positions, cfg.rope_theta, cfg.rotary_dim)
            k = _partial_rope(k, positions, cfg.rope_theta, cfg.rotary_dim)
        return q, k, v

    def _proj_out(self, out, B, T):
        cfg = self.config
        y = out.reshape(B, T, -1) @ self.wo.astype(cfg.dtype)
        if cfg.out_bias:
            y = y + self.bo.astype(cfg.dtype)
        return y

    def _combine(self, x, h1, attn_out):
        cfg = self.config
        if cfg.parallel_block:
            mlp_in = self.ln2(x) if cfg.parallel_dual_norm else h1
            return x + attn_out + self.mlp(mlp_in)
        x = x + attn_out
        return x + self.mlp(self.ln2(x))

    def __call__(self, x, positions, attn_bias=None):
        cfg = self.config
        B, T, _ = x.shape
        h1 = self.ln1(x)
        q, k, v = self._qkv(h1, positions)
        if cfg.sequence_parallel:
            # Ulysses over the 'seq' mesh axis (parallel/ulysses.py); bias
            # variants (ALiBi/local windows) are rejected at config time
            from deepspeed_tpu.parallel.ulysses import sequence_parallel_attention
            out = sequence_parallel_attention(q, k, v, causal=True,
                                              softmax_scale=cfg.attn_scale)
        else:
            rep = cfg.num_attention_heads // cfg.kv_heads
            if self.window is not None:
                # local layer: banded causal bias (window includes causality)
                attn_bias = _window_bias(positions, positions, self.window)
            out = dot_product_attention(q, repeat_kv(k, rep), repeat_kv(v, rep),
                                        causal=True, bias=attn_bias,
                                        softmax_scale=cfg.attn_scale)
        out = checkpoint_name(out, "attn_out")
        return self._combine(x, h1, self._proj_out(out, B, T))

    def decode(self, x, positions, layer_cache, cache_index, attn_bias=None):
        """Dense-cache incremental step (v1 engine protocol, cf. llama.py).
        ``attn_bias`` is the shared [B, {1|H}, T, S] mask built once by the
        caller (window mask + optional ALiBi)."""
        cfg = self.config
        B, T, _ = x.shape
        h1 = self.ln1(x)
        q, k, v = self._qkv(h1, positions)
        if "k_scale" in layer_cache:
            # int8 dense-cache tier for the WHOLE decoder zoo (VERDICT r4
            # "do this" #9 — the tier was llama-lineage only): quantize on
            # append, dequant folded into the attention dots (handles the
            # per-head ALiBi bias, so BLOOM serves quantized too).
            from deepspeed_tpu.models.llama import (quantized_cache_append,
                                                    quantized_cache_attention)
            S = layer_cache["k"].shape[1]
            if attn_bias is None or self.window is not None:
                k_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
                attn_bias = _window_bias(positions, k_pos, self.window)
            new_cache = quantized_cache_append(layer_cache, k, v, cache_index)
            out = quantized_cache_attention(q, new_cache, attn_bias,
                                            cfg.kv_heads,
                                            softmax_scale=cfg.attn_scale)
            return self._combine(x, h1, self._proj_out(out, B, T)), new_cache
        ck = jax.lax.dynamic_update_slice(
            layer_cache["k"], k.astype(layer_cache["k"].dtype), (0, cache_index, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            layer_cache["v"], v.astype(layer_cache["v"].dtype), (0, cache_index, 0, 0))
        S = ck.shape[1]
        rep = cfg.num_attention_heads // cfg.kv_heads
        if attn_bias is None or self.window is not None:
            k_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
            attn_bias = _window_bias(positions, k_pos, self.window)
        out = reference_attention(q, repeat_kv(ck, rep), repeat_kv(cv, rep),
                                  bias=attn_bias, softmax_scale=cfg.attn_scale)
        return self._combine(x, h1, self._proj_out(out, B, T)), {"k": ck, "v": cv}


class DecoderLM(nn.Module):
    """See module docstring. Engine contract: ``__call__(batch) -> loss``."""

    config: DecoderConfig

    def setup(self):
        cfg = self.config
        self.embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                              name="embed")
        if cfg.learned_pos:
            self.pos_embed = nn.Embed(cfg.max_position_embeddings + cfg.pos_offset,
                                      cfg.hidden_size, dtype=cfg.dtype,
                                      name="pos_embed")
        if cfg.embed_norm:
            self.embed_ln = _Norm(cfg.norm, cfg.eps, cfg.dtype, name="embed_norm")
        kinds = cfg.attention_layers or ("global",) * cfg.num_hidden_layers
        self.layers = [DecoderBlock(cfg, name=f"layers_{i}",
                                    window=(cfg.local_window
                                            if kinds[i] == "local" else None))
                       for i in range(cfg.num_hidden_layers)]
        self.final_norm = _Norm(cfg.norm, cfg.eps, cfg.dtype, name="final_norm")
        if not cfg.tied_lm_head:
            self.lm_head = self.param("lm_head", nn.initializers.normal(0.02),
                                      (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        if cfg.head_bias:
            self.lm_head_bias = self.param("lm_head_bias", nn.initializers.zeros,
                                           (cfg.vocab_size,), jnp.float32)

    def _embed_in(self, input_ids, positions):
        cfg = self.config
        x = self.embed(input_ids)
        if cfg.learned_pos:
            x = x + self.pos_embed(positions + cfg.pos_offset)
        x = x.astype(cfg.dtype)
        if cfg.embed_norm:
            x = self.embed_ln(x)
        return x

    def _head(self, logits):
        if self.config.head_bias:
            return logits + self.lm_head_bias
        return logits

    def _logits(self, x):
        cfg = self.config
        x = self.final_norm(x)
        if cfg.tied_lm_head:
            return self._head(self.embed.attend(x.astype(jnp.float32)))
        return self._head((x @ self.lm_head.astype(cfg.dtype)).astype(jnp.float32))

    def _hidden(self, input_ids, positions):
        cfg = self.config
        x = self._embed_in(input_ids, positions)
        # shared across layers: built once here, threaded through the (possibly
        # rematerialised) blocks as an argument so remat saves it, not recomputes
        bias = (alibi_bias(positions, positions, cfg.num_attention_heads)
                if cfg.alibi else None)
        x = apply_checkpointed_layers(
            self, x, lambda mdl, h, i: mdl.layers[i](h, positions, bias),
            cfg.num_hidden_layers, cfg.remat, cfg.remat_policy,
            layers=self.layers, layer_args=(positions, bias))
        return self.final_norm(x)

    def forward_logits(self, input_ids, positions=None):
        cfg = self.config
        B, T = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = self._hidden(input_ids, positions)
        if cfg.tied_lm_head:
            return self._head(self.embed.attend(x.astype(jnp.float32)))
        return self._head((x @ self.lm_head.astype(cfg.dtype)).astype(jnp.float32))

    def __call__(self, batch, deterministic: bool = True):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels", input_ids)
        else:
            input_ids, labels = batch, batch
        B, T = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = self._hidden(input_ids, positions)
        # fused chunked projection+CE (chunked_causal_lm_loss): works for both
        # the tied embedding [V, C] and the untied lm_head param [C, V]
        from deepspeed_tpu.models.llama import chunked_causal_lm_loss
        hb = self.lm_head_bias if cfg.head_bias else None
        if cfg.tied_lm_head:
            return chunked_causal_lm_loss(x, self.embed.embedding, labels,
                                          head_bias=hb,
                                          batch_chunk=cfg.lm_loss_chunk)
        return chunked_causal_lm_loss(x, self.lm_head, labels, transpose=True,
                                      head_bias=hb,
                                      batch_chunk=cfg.lm_loss_chunk)

    def decode(self, input_ids, cache, cache_index, positions=None):
        cfg = self.config
        B, T = input_ids.shape
        if positions is None:
            positions = cache_index + jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = self._embed_in(input_ids, positions)
        S = cache["k"].shape[2]
        k_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        bias = _window_bias(positions, k_pos, None)
        if cfg.alibi:
            bias = bias + alibi_bias(positions, k_pos, cfg.num_attention_heads)
        new_cols = {key: [] for key in cache}
        for i, layer in enumerate(self.layers):
            x, nc = layer.decode(x, positions,
                                 {key: cache[key][i] for key in cache},
                                 cache_index, bias)
            for key in new_cols:
                new_cols[key].append(nc[key])
        return self._logits(x), {key: jnp.stack(v) for key, v in new_cols.items()}


def init_decoder_cache(config: DecoderConfig, batch_size: int, max_len: int,
                       dtype: Any = None,
                       kv_bits: Optional[int] = None) -> Dict[str, jax.Array]:
    """Dense KV cache for the v1 engine (analog of models/llama.py
    init_cache). ``kv_bits=8`` allocates the int8 tier: int8 values plus
    per-token-head f32 scales (persistent bytes ~halve; see the llama
    tier)."""
    dtype = dtype or config.dtype
    shape = (config.num_hidden_layers, batch_size, max_len, config.kv_heads,
             config.head_dim)
    if kv_bits is not None:
        if kv_bits != 8:
            raise ValueError(f"kv_bits must be 8, got {kv_bits!r}")
        sshape = shape[:-1]
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(sshape, jnp.float32),
                "v_scale": jnp.zeros(sshape, jnp.float32)}
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
