"""BERT encoder in flax.linen (BASELINE ladder config #2: BERT-large ZeRO-2).

Parity role: the reference accelerates BERT through the fused
``DeepSpeedTransformerLayer`` training kernels (``csrc/transformer``,
``ops/transformer/transformer.py:296``) and serves it via the bert inference
container (``module_inject/containers/bert.py``). On TPU the fused-kernel value is
captured by XLA fusion over this plain pre/post-LN encoder; param naming follows
HF conventions so ``BERT_TP_RULES`` (``parallel/tensor_parallel.py``) shard it.

Batch contract: ``{"input_ids", "attention_mask"?, "token_type_ids"?, "labels"?}``
— with labels (-100 = ignore) returns the masked-LM mean cross-entropy (the
pre-training objective), else the MLM logits.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.attention import reference_attention
from deepspeed_tpu.runtime.activation_checkpointing import apply_checkpointed_layers


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    exact_gelu: bool = False   # HF "gelu" is erf-exact; default keeps tanh approx
    mlm_bias: bool = False     # HF cls.predictions.decoder carries a bias
    dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        d = dict(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16,
                 intermediate_size=4096)
        d.update(kw); return cls(**d)

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=128,
                 max_position_embeddings=128)
        d.update(kw); return cls(**d)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.config
        B, T, _ = x.shape
        H, D = cfg.num_attention_heads, cfg.head_dim
        dense = lambda name: nn.Dense(H * D, dtype=cfg.dtype, name=name)
        q = dense("query")(x).reshape(B, T, H, D)
        k = dense("key")(x).reshape(B, T, H, D)
        v = dense("value")(x).reshape(B, T, H, D)
        return reference_attention(q, k, v, bias=bias).reshape(B, T, H * D)


class BertLayer(nn.Module):
    """Post-LN encoder block (original BERT ordering)."""

    config: BertConfig

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                       name=name)
        attn = BertSelfAttention(cfg, name="attention")(x, bias)
        attn = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                        name="attention_output")(attn)
        x = ln("attention_layernorm")(x + attn)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     name="intermediate")(x)
        h = nn.gelu(h, approximate=not cfg.exact_gelu)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="output")(h)
        return ln("output_layernorm")(x + h)


class BertForMaskedLM(nn.Module):
    """Returns MLM loss when batch has labels (-100 ignored), else logits."""

    config: BertConfig

    def setup(self):
        cfg = self.config
        self.wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                            name="word_embeddings")
        self.pos_emb = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                                dtype=cfg.dtype, name="position_embeddings")
        self.type_emb = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                                 dtype=cfg.dtype, name="token_type_embeddings")
        self.emb_ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                   name="embeddings_layernorm")
        self.layers = [BertLayer(cfg, name=f"layer_{i}")
                       for i in range(cfg.num_hidden_layers)]
        self.mlm_transform = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                                      name="mlm_transform")
        self.mlm_ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                   name="mlm_layernorm")
        if cfg.mlm_bias:
            self.mlm_decoder_bias = self.param("mlm_bias", nn.initializers.zeros,
                                               (cfg.vocab_size,), jnp.float32)

    def __call__(self, batch, deterministic: bool = True):
        cfg = self.config
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        B, T = input_ids.shape
        mask = batch.get("attention_mask") if isinstance(batch, dict) else None
        types = batch.get("token_type_ids") if isinstance(batch, dict) else None

        x = self.wte(input_ids)
        x = x + self.pos_emb(jnp.arange(T)[None, :])
        if types is None:
            types = jnp.zeros_like(input_ids)
        x = x + self.type_emb(types)
        x = self.emb_ln(x)

        # bidirectional: only padding is masked
        bias = None
        if mask is not None:
            from deepspeed_tpu.ops.attention import padding_mask_to_bias
            bias = padding_mask_to_bias(mask)
        x = apply_checkpointed_layers(
            self, x, lambda mdl, h, i: mdl.layers[i](h, bias),
            cfg.num_hidden_layers, cfg.remat, cfg.remat_policy)

        # MLM head: transform + tied decoder (HF cls.predictions shape)
        h = self.mlm_transform(x)
        h = nn.gelu(h, approximate=not cfg.exact_gelu)
        h = self.mlm_ln(h)
        logits = self.wte.attend(h.astype(jnp.float32))
        if cfg.mlm_bias:
            logits = logits + self.mlm_decoder_bias

        labels = batch.get("labels") if isinstance(batch, dict) else None
        if labels is None:
            return logits
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = labels >= 0
        safe = jnp.where(valid, labels, 0)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(jnp.sum(valid), 1)
        return jnp.sum(jnp.where(valid, nll, 0.0)) / denom
