"""Stable-Diffusion-class model surface (CLIP text encoder, UNet2D, VAE).

Parity role: the reference's diffusers serving surface —
``model_implementations/diffusers/unet.py`` (DSUNet: CUDA-graph capture of
the UNet forward), ``diffusers/vae.py`` (DSVAE), and the injection
containers ``module_inject/containers/{clip,unet,vae}.py`` (policies that
patch attention inside HF diffusers models). The reference WRAPS existing
torch modules; this framework is standalone, so the families live here as
flax modules (the same stance as the LLM zoo in ``models/``), and the
reference's CUDA-graph trick — capture the denoise step once, replay it per
step — is ``jax.jit`` + ``lax.fori_loop``: the WHOLE sampling loop is one
compiled program (``init_diffusion_inference``), which is strictly more
capture than per-forward graph replay.

TPU mapping notes:
  - Convolutions (``nn.Conv``) lower onto the MXU via XLA; NHWC layouts
    (flax default) are the TPU-native channel-last the reference moves its
    UNet to (``unet.to(memory_format=torch.channels_last)``).
  - Attention inside the UNet runs spatial self-attention + text
    cross-attention; sequence lengths are H*W (e.g. 64..4096) — the dense
    ``dot_product_attention`` path fuses fine at these sizes (flash pays off
    at LLM context lengths, not 32x32 latents).
  - The scheduler is DDIM (eta=0): deterministic, jit-friendly (no
    data-dependent control flow), the standard fast-sampling choice.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.attention import reference_attention


# --------------------------------------------------------------------------- #
# configs
# --------------------------------------------------------------------------- #

@dataclass
class DiffusionConfig:
    """One config tree for the three components (tiny defaults are
    fixture-sized; real SD dims in the classmethods)."""
    # CLIP text encoder
    vocab_size: int = 1000
    text_width: int = 64
    text_layers: int = 2
    text_heads: int = 4
    max_text_len: int = 16
    # UNet
    in_channels: int = 4
    base_channels: int = 32
    channel_mults: Tuple[int, ...] = (1, 2)
    unet_attn_heads: int = 4
    # VAE decoder
    latent_channels: int = 4
    vae_base_channels: int = 32
    image_channels: int = 3
    vae_upsamples: int = 2          # latent H -> H * 2**n
    dtype: Any = jnp.float32

    @classmethod
    def tiny(cls, **kw):
        d = dict()
        d.update(kw)
        return cls(**d)

    @classmethod
    def sd15_like(cls, **kw):
        d = dict(vocab_size=49408, text_width=768, text_layers=12,
                 text_heads=12, max_text_len=77, in_channels=4,
                 base_channels=320, channel_mults=(1, 2, 4, 4),
                 unet_attn_heads=8, latent_channels=4,
                 vae_base_channels=128, vae_upsamples=3)
        d.update(kw)
        return cls(**d)


# --------------------------------------------------------------------------- #
# CLIP text encoder (container parity: module_inject/containers/clip.py —
# the reference patches its self-attention; here the block IS ours)
# --------------------------------------------------------------------------- #

class CLIPTextEncoder(nn.Module):
    config: DiffusionConfig

    @nn.compact
    def __call__(self, token_ids):            # [B, T] int32
        cfg = self.config
        B, T = token_ids.shape
        W, H = cfg.text_width, cfg.text_heads
        x = nn.Embed(cfg.vocab_size, W, dtype=cfg.dtype,
                     name="token_embed")(token_ids)
        pos = nn.Embed(cfg.max_text_len, W, dtype=cfg.dtype,
                       name="pos_embed")(jnp.arange(T)[None, :])
        x = x + pos
        for i in range(cfg.text_layers):
            h = nn.LayerNorm(dtype=cfg.dtype, name=f"ln1_{i}")(x)
            qkv = nn.Dense(3 * W, dtype=cfg.dtype, name=f"qkv_{i}")(h)
            q, k, v = jnp.split(qkv.reshape(B, T, 3, H, W // H), 3, axis=2)
            # CLIP text towers are CAUSAL (OpenAI CLIP convention)
            att = reference_attention(q[:, :, 0], k[:, :, 0], v[:, :, 0],
                                      causal=True)
            x = x + nn.Dense(W, dtype=cfg.dtype, name=f"proj_{i}")(
                att.reshape(B, T, W))
            h2 = nn.LayerNorm(dtype=cfg.dtype, name=f"ln2_{i}")(x)
            m = nn.Dense(4 * W, dtype=cfg.dtype, name=f"fc1_{i}")(h2)
            m = nn.gelu(m)
            x = x + nn.Dense(W, dtype=cfg.dtype, name=f"fc2_{i}")(m)
        return nn.LayerNorm(dtype=cfg.dtype, name="final_ln")(x)  # [B, T, W]


# --------------------------------------------------------------------------- #
# UNet2D with timestep conditioning + text cross-attention
# (parity: diffusers UNet2DConditionModel served via DSUNet/unet container)
# --------------------------------------------------------------------------- #

def timestep_embedding(t, dim: int):
    """Sinusoidal timestep embedding (the standard DDPM form)."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10000.0) * jnp.arange(half) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


class ResBlock(nn.Module):
    out_ch: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, temb=None):         # x [B, H, W, C]
        h = nn.GroupNorm(num_groups=8, dtype=self.dtype)(x)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv1")(nn.silu(h))
        if temb is not None:                  # VAE blocks are unconditioned
            h = h + nn.Dense(self.out_ch, dtype=self.dtype,
                             name="temb_proj")(nn.silu(temb))[:, None, None, :]
        h = nn.GroupNorm(num_groups=8, dtype=self.dtype)(h)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv2")(nn.silu(h))
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=self.dtype,
                        name="skip")(x)
        return x + h


class SpatialTransformer(nn.Module):
    """Self-attention over H*W tokens + cross-attention to the text states
    (the block the reference's unet container swaps kernels into)."""
    heads: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, ctx):               # x [B, H, W, C]; ctx [B, T, Wt]
        B, H, W, C = x.shape
        hd = C // self.heads
        r = x.reshape(B, H * W, C)
        h1 = nn.LayerNorm(dtype=self.dtype)(r)
        q = nn.Dense(C, dtype=self.dtype, name="sa_q")(h1)
        k = nn.Dense(C, dtype=self.dtype, name="sa_k")(h1)
        v = nn.Dense(C, dtype=self.dtype, name="sa_v")(h1)
        sa = reference_attention(q.reshape(B, H * W, self.heads, hd),
                                 k.reshape(B, H * W, self.heads, hd),
                                 v.reshape(B, H * W, self.heads, hd))
        r = r + nn.Dense(C, dtype=self.dtype, name="sa_o")(
            sa.reshape(B, H * W, C))
        h2 = nn.LayerNorm(dtype=self.dtype)(r)
        q = nn.Dense(C, dtype=self.dtype, name="ca_q")(h2)
        k = nn.Dense(C, dtype=self.dtype, name="ca_k")(ctx)
        v = nn.Dense(C, dtype=self.dtype, name="ca_v")(ctx)
        T = ctx.shape[1]
        ca = reference_attention(q.reshape(B, H * W, self.heads, hd),
                                 k.reshape(B, T, self.heads, hd),
                                 v.reshape(B, T, self.heads, hd))
        r = r + nn.Dense(C, dtype=self.dtype, name="ca_o")(
            ca.reshape(B, H * W, C))
        h3 = nn.LayerNorm(dtype=self.dtype)(r)
        m = nn.Dense(4 * C, dtype=self.dtype, name="ff1")(h3)
        r = r + nn.Dense(C, dtype=self.dtype, name="ff2")(nn.gelu(m))
        return r.reshape(B, H, W, C)


class UNet2D(nn.Module):
    """Down/mid/up UNet with skip connections, timestep conditioning and
    text cross-attention at every resolution."""
    config: DiffusionConfig

    @nn.compact
    def __call__(self, latents, t, text_states):
        cfg = self.config
        dt = cfg.dtype
        temb = nn.Dense(cfg.base_channels * 4, dtype=dt, name="temb1")(
            timestep_embedding(t, cfg.base_channels).astype(dt))
        temb = nn.Dense(cfg.base_channels * 4, dtype=dt,
                        name="temb2")(nn.silu(temb))

        h = nn.Conv(cfg.base_channels, (3, 3), padding="SAME", dtype=dt,
                    name="conv_in")(latents)
        skips = [h]
        for i, mult in enumerate(cfg.channel_mults):
            ch = cfg.base_channels * mult
            h = ResBlock(ch, dt, name=f"down_res_{i}")(h, temb)
            h = SpatialTransformer(cfg.unet_attn_heads, dt,
                                   name=f"down_attn_{i}")(h, text_states)
            skips.append(h)
            if i != len(cfg.channel_mults) - 1:
                h = nn.Conv(ch, (3, 3), strides=(2, 2), padding="SAME",
                            dtype=dt, name=f"down_{i}")(h)

        h = ResBlock(h.shape[-1], dt, name="mid_res1")(h, temb)
        h = SpatialTransformer(cfg.unet_attn_heads, dt,
                               name="mid_attn")(h, text_states)
        h = ResBlock(h.shape[-1], dt, name="mid_res2")(h, temb)

        for i, mult in reversed(list(enumerate(cfg.channel_mults))):
            ch = cfg.base_channels * mult
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = ResBlock(ch, dt, name=f"up_res_{i}")(h, temb)
            h = SpatialTransformer(cfg.unet_attn_heads, dt,
                                   name=f"up_attn_{i}")(h, text_states)
            if i != 0:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
                h = nn.Conv(C, (3, 3), padding="SAME", dtype=dt,
                            name=f"up_{i}")(h)
        h = jnp.concatenate([h, skips.pop()], axis=-1)
        h = nn.GroupNorm(num_groups=8, dtype=dt, name="norm_out")(h)
        return nn.Conv(cfg.in_channels, (3, 3), padding="SAME", dtype=dt,
                       name="conv_out")(nn.silu(h))


# --------------------------------------------------------------------------- #
# VAE decoder (parity: diffusers AutoencoderKL.decode via DSVAE/vae container)
# --------------------------------------------------------------------------- #

class VAEDecoder(nn.Module):
    config: DiffusionConfig

    @nn.compact
    def __call__(self, z):                    # [B, h, w, latent_ch]
        cfg = self.config
        dt = cfg.dtype
        h = nn.Conv(cfg.vae_base_channels, (3, 3), padding="SAME", dtype=dt,
                    name="conv_in")(z)
        h = ResBlock(cfg.vae_base_channels, dt, name="mid")(h)
        for i in range(cfg.vae_upsamples):
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = nn.Conv(C, (3, 3), padding="SAME", dtype=dt,
                        name=f"up_{i}")(h)
            h = ResBlock(C, dt, name=f"up_res_{i}")(h)
        h = nn.GroupNorm(num_groups=8, dtype=dt, name="norm_out")(h)
        return nn.Conv(cfg.image_channels, (3, 3), padding="SAME", dtype=dt,
                       name="conv_out")(nn.silu(h))


# --------------------------------------------------------------------------- #
# pipeline wrapper: the DSUNet "capture once, replay per step" analog —
# jit + fori_loop compiles the WHOLE sampler into one program
# --------------------------------------------------------------------------- #

class DiffusionPipeline:
    """Text -> image sampling with classifier-free guidance and a DDIM
    (eta=0) schedule, fully jitted. Reference parity: the DeepSpeed
    inference path for stable diffusion (``init_inference`` on a diffusers
    pipeline: DSUNet + DSVAE + DSClipEncoder with cuda-graph capture).

    ``generate(token_ids, key, steps, guidance)`` returns images
    [B, H, W, 3] in [-1, 1]."""

    def __init__(self, config: DiffusionConfig, params, latent_hw: int = 8,
                 num_train_timesteps: int = 1000):
        self.config = config
        self.text = CLIPTextEncoder(config)
        self.unet = UNet2D(config)
        self.vae = VAEDecoder(config)
        self.params = params
        self.latent_hw = latent_hw
        self.T = num_train_timesteps
        # DDPM linear-beta schedule -> alpha_bar table (f32, device)
        betas = jnp.linspace(1e-4, 0.02, num_train_timesteps,
                             dtype=jnp.float32)
        self.alpha_bar = jnp.cumprod(1.0 - betas)
        # params are an explicit argument of the jitted function: a closure
        # capture would bake the weight pytree into the executable as
        # constants (doubling device memory at SD scale) and silently
        # ignore any later ``pipe.params = ...`` reassignment
        self._gen = jax.jit(self._generate, static_argnums=(4,))

    @staticmethod
    def init_params(config: DiffusionConfig, rng, latent_hw: int = 8):
        text = CLIPTextEncoder(config)
        unet = UNet2D(config)
        vae = VAEDecoder(config)
        r1, r2, r3 = jax.random.split(rng, 3)
        toks = jnp.zeros((1, config.max_text_len), jnp.int32)
        lat = jnp.zeros((1, latent_hw, latent_hw, config.in_channels),
                        config.dtype)
        return {
            "text": text.init(r1, toks)["params"],
            "unet": unet.init(r2, lat, jnp.zeros((1,), jnp.int32),
                              jnp.zeros((1, config.max_text_len,
                                         config.text_width),
                                        config.dtype))["params"],
            "vae": vae.init(r3, lat)["params"],
        }

    def _generate(self, params, token_ids, key, guidance, steps: int):
        cfg = self.config
        B = token_ids.shape[0]
        ctx = self.text.apply({"params": params["text"]}, token_ids)
        ctx_un = self.text.apply({"params": params["text"]},
                                 jnp.zeros_like(token_ids))
        lat = jax.random.normal(
            key, (B, self.latent_hw, self.latent_hw, cfg.in_channels),
            jnp.float32).astype(cfg.dtype)
        ts = jnp.linspace(self.T - 1, 0, steps).astype(jnp.int32)

        def step_fn(i, lat):
            t = jnp.full((B,), ts[i], jnp.int32)
            # classifier-free guidance: one batched UNet call for cond+uncond
            eps = self.unet.apply(
                {"params": params["unet"]},
                jnp.concatenate([lat, lat]),
                jnp.concatenate([t, t]),
                jnp.concatenate([ctx, ctx_un]))
            e_c, e_u = jnp.split(eps, 2)
            eps = e_u + guidance * (e_c - e_u)
            ab_t = self.alpha_bar[ts[i]]
            ab_prev = jnp.where(i + 1 < steps, self.alpha_bar[ts[
                jnp.minimum(i + 1, steps - 1)]], 1.0)
            x0 = (lat - jnp.sqrt(1.0 - ab_t) * eps) / jnp.sqrt(ab_t)
            lat = (jnp.sqrt(ab_prev) * x0
                   + jnp.sqrt(1.0 - ab_prev) * eps).astype(lat.dtype)
            return lat

        lat = jax.lax.fori_loop(0, steps, step_fn, lat)
        return self.vae.apply({"params": params["vae"]}, lat)

    def generate(self, token_ids, key, steps: int = 20,
                 guidance: float = 7.5):
        return self._gen(self.params, jnp.asarray(token_ids, jnp.int32),
                         key, jnp.float32(guidance), steps)


def init_diffusion_inference(config: DiffusionConfig, params,
                             latent_hw: int = 8) -> DiffusionPipeline:
    """Engine-style entry (parity: ``deepspeed.init_inference`` over a
    diffusers pipeline replacing UNet/VAE/CLIP with DS wrappers)."""
    return DiffusionPipeline(config, params, latent_hw=latent_hw)


# --------------------------------------------------------------------------- #
# injection policies (parity: module_inject/containers/{clip,unet,vae}.py —
# the reference's containers PATCH attention/linears inside existing
# diffusers modules rather than converting checkpoints; the analog here maps
# a pipeline component name onto its TPU-native module + the config fields
# it reads. Unlike the LLM zoo (HF-checkpoint-converting policies in
# module_inject/containers.py), the diffusion family is native-architecture:
# a faithful HF-weight mapping would require replicating diffusers' block
# graph exactly, which is out of scope for this surface.)
# --------------------------------------------------------------------------- #

class CLIPPolicy:
    component = "text_encoder"
    module_cls = CLIPTextEncoder
    config_fields = ("vocab_size", "text_width", "text_layers", "text_heads",
                     "max_text_len")


class UNetPolicy:
    component = "unet"
    module_cls = UNet2D
    config_fields = ("in_channels", "base_channels", "channel_mults",
                     "unet_attn_heads")


class VAEPolicy:
    component = "vae"
    module_cls = VAEDecoder
    config_fields = ("latent_channels", "vae_base_channels",
                     "image_channels", "vae_upsamples")


DIFFUSION_POLICIES = {p.component: p for p in
                      (CLIPPolicy, UNetPolicy, VAEPolicy)}
