"""Mixtral (sparse-MoE Llama lineage) in flax.linen.

Parity role: the reference serves Mixtral through
``inference/v2/model_implementations/mixtral`` (MoE over its CUTLASS grouped-GEMM
kernels) and trains MoE models through ``deepspeed.moe`` (``moe/sharded_moe.py``).
Here the family is a first-class model: the Llama backbone with each MLP replaced
by a top-k routed MoE of SwiGLU experts (BASELINE ladder config #4:
Mixtral-8x7B ZeRO-3 + EP).

TPU-native dispatch: capacity-limited one-hot combine/dispatch einsums (GShard
style, shared with ``parallel/moe.py``) — expert weights carry a leading [E, ...]
dim that the EP spec shards over the 'expert' mesh axis; XLA emits the all-to-all
the reference issues by hand (sharded_moe.py:95 _AllToAll).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import (LlamaAttention, LlamaConfig, RMSNorm,
                                        causal_lm_loss, decode_layers, init_cache)
from deepspeed_tpu.parallel.moe import _capacity, _constrain_expert, topk_gating
from deepspeed_tpu.runtime.activation_checkpointing import apply_checkpointed_layers


@dataclass
class MixtralConfig(LlamaConfig):
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    router_aux_loss_coef: float = 0.02
    capacity_factor: float = 2.0
    min_capacity: int = 4
    # "capacity" = one-hot dispatch with capacity dropping (EP all-to-all
    # capable); "dropless" = grouped-GEMM routing (lax.ragged_dot), exact HF
    # Mixtral semantics (no token dropping), faster on a single expert shard
    dispatch_mode: str = "capacity"

    @classmethod
    def mixtral_8x7b(cls, **kw):
        defaults = dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                        num_hidden_layers=32, num_attention_heads=32,
                        num_key_value_heads=8, max_position_embeddings=32768,
                        rope_theta=1e6, num_local_experts=8, num_experts_per_tok=2)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=128,
                        num_local_experts=4, num_experts_per_tok=2)
        defaults.update(kw)
        return cls(**defaults)


class MixtralSparseMoeBlock(nn.Module):
    """Top-k routed SwiGLU experts. Returns (out, l_aux)."""

    config: MixtralConfig

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        B, T, C = x.shape
        E = cfg.num_local_experts
        N = B * T
        tokens = x.reshape(N, C)

        router = nn.Dense(E, use_bias=False, dtype=jnp.float32, name="gate")
        logits = router(tokens.astype(jnp.float32))           # fp32 routing

        init = nn.initializers.normal(0.02)
        w_gate = self.param("w_gate", init, (E, C, cfg.intermediate_size), cfg.dtype)
        w_up = self.param("w_up", init, (E, C, cfg.intermediate_size), cfg.dtype)
        w_down = self.param("w_down", init, (E, cfg.intermediate_size, C), cfg.dtype)

        if cfg.dispatch_mode == "dropless":
            from deepspeed_tpu.parallel.moe import (_ep_size, dropless_moe,
                                                    dropless_moe_ep)
            ep, topo = _ep_size(True)
            if ep > 1:
                def swiglu_ws(ws, rows, group_sizes):
                    wg, wu, wd = ws
                    g = jax.lax.ragged_dot(rows, wg, group_sizes)
                    u = jax.lax.ragged_dot(rows, wu, group_sizes)
                    return jax.lax.ragged_dot(nn.silu(g) * u, wd, group_sizes)

                out, l_aux = dropless_moe_ep(
                    tokens, logits, cfg.num_experts_per_tok,
                    (w_gate, w_up, w_down), swiglu_ws, topo.mesh, ep)
                return out.reshape(B, T, C), l_aux.astype(jnp.float32)

            def swiglu_grouped(rows, group_sizes):
                g = jax.lax.ragged_dot(rows, w_gate, group_sizes)
                u = jax.lax.ragged_dot(rows, w_up, group_sizes)
                return jax.lax.ragged_dot(nn.silu(g) * u, w_down, group_sizes)

            out, l_aux = dropless_moe(tokens, logits, cfg.num_experts_per_tok,
                                      swiglu_grouped)
            return out.reshape(B, T, C), l_aux.astype(jnp.float32)

        cap = _capacity(N, E, cfg.capacity_factor * cfg.num_experts_per_tok,
                        cfg.min_capacity)
        combine, dispatch, l_aux = topk_gating(logits, cfg.num_experts_per_tok, cap)

        # dispatch: [N, E, C_cap] bool -> expert inputs [E, C_cap, d]; the
        # sharding constraint over 'expert' makes XLA emit the EP all-to-all
        xs = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), tokens)
        xs = _constrain_expert(xs)

        h = nn.silu(jnp.einsum("ecd,edf->ecf", xs, w_gate)) * \
            jnp.einsum("ecd,edf->ecf", xs, w_up)
        ys = _constrain_expert(jnp.einsum("ecf,efd->ecd", h, w_down))  # [E, C_cap, d]

        out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), ys)
        return out.reshape(B, T, C), l_aux.astype(jnp.float32)


class MixtralBlock(nn.Module):
    config: MixtralConfig

    def setup(self):
        cfg = self.config
        self.input_layernorm = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")
        self.post_attention_layernorm = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                                                name="post_attention_layernorm")
        self.self_attn = LlamaAttention(cfg, name="self_attn")
        self.block_sparse_moe = MixtralSparseMoeBlock(cfg, name="block_sparse_moe")

    def __call__(self, x, positions):
        x = x + self.self_attn(self.input_layernorm(x), positions)
        m, l_aux = self.block_sparse_moe(self.post_attention_layernorm(x))
        return x + m, l_aux

    def decode(self, x, positions, layer_cache, cache_index):
        a, new_cache = self.self_attn.decode(self.input_layernorm(x), positions,
                                             layer_cache, cache_index)
        x = x + a
        m, _ = self.block_sparse_moe(self.post_attention_layernorm(x))
        return x + m, new_cache


class MixtralForCausalLM(nn.Module):
    config: MixtralConfig

    def setup(self):
        cfg = self.config
        self.embed_tokens = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                                     dtype=cfg.dtype, name="embed_tokens")
        self.layers = [MixtralBlock(cfg, name=f"layers_{i}")
                       for i in range(cfg.num_hidden_layers)]
        self.norm = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")
        self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                                name="lm_head")

    def forward_logits(self, input_ids, positions=None):
        logits, _ = self._forward(input_ids, positions)
        return logits

    def _trunk_aux(self, input_ids, positions=None):
        B, T = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = self.embed_tokens(input_ids)

        def call_layer(mdl, carry, i):
            h, aux = carry
            h, l_aux = mdl.layers[i](h, positions)
            return h, aux + l_aux

        cfg = self.config
        x, aux_total = apply_checkpointed_layers(
            self, (x, jnp.float32(0.0)), call_layer,
            cfg.num_hidden_layers, cfg.remat, cfg.remat_policy)
        x = self.norm(x)
        return x, aux_total

    def _forward(self, input_ids, positions=None):
        x, aux_total = self._trunk_aux(input_ids, positions)
        return self.lm_head(x).astype(jnp.float32), aux_total

    def __call__(self, batch, deterministic: bool = True):
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels", input_ids)
        else:
            input_ids, labels = batch, batch
        x, aux_total = self._trunk_aux(input_ids)
        # fused chunked projection+CE (see models/llama.py)
        _ = self.lm_head(x[:, :1])
        kernel = self.lm_head.variables["params"]["kernel"]
        from deepspeed_tpu.models.llama import chunked_causal_lm_loss
        loss = chunked_causal_lm_loss(x, kernel, labels, transpose=True,
                                      batch_chunk=self.config.lm_loss_chunk)
        cfg = self.config
        return loss + cfg.router_aux_loss_coef * aux_total / cfg.num_hidden_layers

    def decode(self, input_ids, cache, cache_index, positions=None):
        return decode_layers(self, input_ids, cache, cache_index, positions)


__all__ = ["MixtralConfig", "MixtralForCausalLM", "init_cache"]
