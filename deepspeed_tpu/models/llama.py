"""Llama-family decoder models in flax.linen (Llama-2 / Mistral via config).

Parity role: the reference ships these families as *inference containers and
model implementations* (``module_inject/containers/llama.py``, ``llama2.py``,
``inference/v2/model_implementations/{llama_v2,mistral}``) over HF weights; this
framework is standalone, so the families live here as first-class flax models used
by both the training engine (BASELINE ladder config #3: Llama-2-7B ZeRO-3 bf16)
and the inference engines.

Architecture (Llama-2 / Mistral lineage): RMSNorm pre-norm, rotary position
embeddings, grouped-query attention (``num_key_value_heads < num_attention_heads``),
SwiGLU MLP, untied LM head, optional sliding-window attention (Mistral).

Two call paths:
  - ``__call__(batch)``: training convention — mean next-token cross-entropy
    (or logits when no labels can be formed), matching the engine contract.
  - ``decode(input_ids, cache, positions)``: incremental decoding with an explicit
    KV-cache pytree (see ``init_cache``) — the inference engines jit this. The
    cache is an explicit function argument, not flax mutable state, so it shards
    and donates cleanly under jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.ops.attention import dot_product_attention, reference_attention
from deepspeed_tpu.runtime.activation_checkpointing import apply_checkpointed_layers


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32        # < num_attention_heads => GQA (Mistral: 8)
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    sliding_window: Optional[int] = None  # Mistral: 4096
    qkv_bias: bool = False               # Qwen2 lineage: biased q/k/v projections
    # Gemma lineage structural flags:
    head_dim_override: Optional[int] = None  # head_dim decoupled from hidden/heads
    embed_scale_by_sqrt_dim: bool = False    # x *= sqrt(hidden) after embedding
    norm_plus_one: bool = False              # RMSNorm scales by (1 + weight)
    mlp_act: str = "silu"                    # "silu" | "gelu" (tanh) gate act
    # Ulysses sequence parallelism for training: attention runs through two
    # all-to-alls on the 'seq' mesh axis (parallel/ulysses.py); no-op when
    # the mesh has no seq axis. Requires heads and T divisible by seq size.
    sequence_parallel: bool = False
    # Ring-attention context parallelism (parallel/ring.py): KV rotates the
    # ICI ring while T stays sharded over 'seq'. The long-sequence choice
    # when head counts can't divide the seq axis. Mutually exclusive with
    # sequence_parallel.
    context_parallel: bool = False
    # rows per chunk in the fused projection+CE loss (chunked_causal_lm_loss):
    # larger chunks raise head-GEMM MXU efficiency, smaller bound the
    # [chunk, T, V] fp32 transient
    lm_loss_chunk: int = 4
    dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: Optional[str] = None

    def __post_init__(self):
        if ((self.sequence_parallel or self.context_parallel)
                and self.sliding_window is not None):
            raise ValueError(
                "sequence_parallel/context_parallel do not support "
                "sliding_window attention yet (both run full causal "
                "attention); unset one of the two")
        if self.sequence_parallel and self.context_parallel:
            raise ValueError("sequence_parallel and context_parallel are "
                             "mutually exclusive")

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def llama2_7b(cls, **kw):
        return cls(**kw)

    @classmethod
    def llama2_13b(cls, **kw):
        defaults = dict(hidden_size=5120, intermediate_size=13824,
                        num_hidden_layers=40, num_attention_heads=40,
                        num_key_value_heads=40)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def llama2_70b(cls, **kw):
        defaults = dict(hidden_size=8192, intermediate_size=28672,
                        num_hidden_layers=80, num_attention_heads=64,
                        num_key_value_heads=8)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def mistral_7b(cls, **kw):
        defaults = dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                        num_hidden_layers=32, num_attention_heads=32,
                        num_key_value_heads=8, max_position_embeddings=32768,
                        rope_theta=1e6, sliding_window=4096)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def tiny(cls, **kw):
        """Fixture-sized config (analog of tests/unit/simple_model.py fixtures)."""
        defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=128)
        defaults.update(kw)
        return cls(**defaults)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.float32
    plus_one: bool = False   # Gemma: y * (1 + weight), weight zero-centred

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.zeros if self.plus_one else nn.initializers.ones
        w = self.param("weight", init, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + self.eps)
        scale = (1.0 + w) if self.plus_one else w
        return (y * scale).astype(self.dtype)


def rope_frequencies(head_dim: int, theta: float) -> jax.Array:
    return 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))


def apply_rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding, interleaved-pair convention. x: [B, T, H, D],
    positions: [B, T] (int). Parity: the reference's apply_rotary_pos_emb kernel
    (csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu) — on TPU a pure
    jnp rotation that XLA fuses into the surrounding matmuls."""
    D = x.shape[-1]
    freqs = rope_frequencies(D, theta)                     # [D/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :]                   # [B, T, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, T, H_kv, D] -> [B, T, H_kv*n_rep, D] (GQA head expansion)."""
    if n_rep == 1:
        return x
    B, T, H, D = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (B, T, H, n_rep, D)).reshape(B, T, H * n_rep, D)


def _window_bias(q_positions: jax.Array, k_positions: jax.Array,
                 window: Optional[int]) -> jax.Array:
    """Additive bias [B, 1, Tq, Tk]: causal (key pos <= query pos), optionally
    restricted to the sliding window [q - window + 1, q]. Per-batch-row positions
    so left-padded / ragged batches mask correctly."""
    delta = q_positions[:, :, None] - k_positions[:, None, :]
    ok = delta >= 0
    if window is not None:
        ok = ok & (delta < window)
    return jnp.where(ok, 0.0, jnp.finfo(jnp.float32).min)[:, None]


def sliding_window_attention(q, k, v, positions, window: int) -> jax.Array:
    """O(T·w) local attention: queries in block i attend keys in blocks i-1 and i
    (block size = window, so [q-w+1, q] is always covered). Parity role: the
    reference's long-sequence lever is block-sparse Triton attention
    (ops/sparse_attention, 'bslongformer' pattern); this is the same banded
    structure expressed as a blocked einsum XLA tiles onto the MXU — no [T, T]
    score materialisation."""
    B, T, H, D = q.shape
    w = window
    nb = -(-T // w)
    pad = nb * w - T
    if pad:
        padw = ((0, 0), (0, pad), (0, 0), (0, 0))
        q, k, v = (jnp.pad(t, padw) for t in (q, k, v))
        # padded queries mask themselves out via positions = -inf sentinel
        positions = jnp.pad(positions, ((0, 0), (0, pad)), constant_values=-(10 ** 9))
    blk = lambda t: t.reshape(B, nb, w, H, D)
    qb, kb, vb = blk(q), blk(k), blk(v)
    def shift(t, fill=0):
        pad_cfg = ((0, 0), (1, 0)) + ((0, 0),) * (t.ndim - 2)
        return jnp.pad(t, pad_cfg, constant_values=fill)[:, :-1]

    k2 = jnp.concatenate([shift(kb), kb], axis=2)          # [B, nb, 2w, H, D]
    v2 = jnp.concatenate([shift(vb), vb], axis=2)
    pb = positions.reshape(B, nb, w)
    # phantom block before block 0 carries +inf-like positions => delta < 0 => masked
    pk2 = jnp.concatenate([shift(pb, fill=2 ** 30), pb], axis=2)  # [B, nb, 2w]
    delta = pb[..., :, None] - pk2[..., None, :]            # [B, nb, w, 2w]
    ok = (delta >= 0) & (delta < w)
    bias = jnp.where(ok, 0.0, jnp.finfo(jnp.float32).min)[:, :, None]  # [B,nb,1,w,2w]
    scale = 1.0 / (D ** 0.5)
    scores = jnp.einsum("bnqhd,bnkhd->bnhqk", qb, k2).astype(jnp.float32) * scale
    probs = jax.nn.softmax(scores + bias, axis=-1).astype(q.dtype)
    out = jnp.einsum("bnhqk,bnkhd->bnqhd", probs, v2).reshape(B, nb * w, H, D)
    return out[:, :T]


class LlamaAttention(nn.Module):
    config: LlamaConfig

    def setup(self):
        cfg = self.config
        dense = lambda feats, name, bias=False: nn.Dense(
            feats, use_bias=bias, dtype=cfg.dtype, name=name)
        qb = cfg.qkv_bias
        self.q_proj = dense(cfg.num_attention_heads * cfg.head_dim, "q_proj", qb)
        self.k_proj = dense(cfg.num_key_value_heads * cfg.head_dim, "k_proj", qb)
        self.v_proj = dense(cfg.num_key_value_heads * cfg.head_dim, "v_proj", qb)
        self.o_proj = dense(cfg.hidden_size, "o_proj")

    def _qkv(self, x, positions):
        cfg = self.config
        B, T, _ = x.shape
        q = self.q_proj(x).reshape(B, T, cfg.num_attention_heads, cfg.head_dim)
        k = self.k_proj(x).reshape(B, T, cfg.num_key_value_heads, cfg.head_dim)
        v = self.v_proj(x).reshape(B, T, cfg.num_key_value_heads, cfg.head_dim)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        return q, k, v

    def __call__(self, x, positions):
        cfg = self.config
        B, T, _ = x.shape
        q, k, v = self._qkv(x, positions)
        if cfg.sequence_parallel:
            # Ulysses (DeepSpeed sequence parallelism, sequence/layer.py:60):
            # T shards over the 'seq' mesh axis; two all-to-alls around local
            # attention. K/V stay at Hkv heads across the wire — the GQA
            # repeat happens post-scatter inside the local attention, so the
            # all-to-all moves 1/n_rep of the repeated volume. No-op when the
            # mesh's seq axis is 1. (sliding_window rejected in __post_init__)
            from deepspeed_tpu.parallel.ulysses import sequence_parallel_attention
            out = sequence_parallel_attention(q, k, v, causal=True)
        elif cfg.context_parallel:
            from deepspeed_tpu.parallel.ulysses import context_parallel_attention
            out = context_parallel_attention(q, k, v, causal=True)
        else:
            n_rep = cfg.num_attention_heads // cfg.num_key_value_heads
            k, v = repeat_kv(k, n_rep), repeat_kv(v, n_rep)
            if cfg.sliding_window is not None and T > cfg.sliding_window:
                out = sliding_window_attention(q, k, v, positions,
                                               cfg.sliding_window)
            else:
                out = dot_product_attention(q, k, v, causal=True)
        out = checkpoint_name(
            out.reshape(B, T, cfg.num_attention_heads * cfg.head_dim), "attn_out")
        return self.o_proj(out)

    def decode(self, x, positions, layer_cache, cache_index):
        """Incremental step: append this step's K/V at ``cache_index`` and attend
        over the filled prefix. layer_cache: {"k","v"}: [B, S_max, H_kv, D] —
        or the int8 tier with "k_scale"/"v_scale" [B, S_max, H_kv] f32
        (quantize on append, dequant fused into the attention read)."""
        cfg = self.config
        B, T, _ = x.shape
        q, k, v = self._qkv(x, positions)
        new_cache = {}
        if "k_scale" in layer_cache:
            # ADVICE r4: dequant FOLDED into the attention dots (see
            # quantized_cache_attention) — no dequantized [B, S_max, Hkv, D]
            # cache nor its repeat_kv is ever materialised, so the transient
            # peak that offset the tier's 1.94x capacity gain is gone.
            new_cache = quantized_cache_append(layer_cache, k, v, cache_index)
            S = new_cache["k"].shape[1]
            k_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
            bias = _window_bias(positions, k_pos, cfg.sliding_window)
            out = quantized_cache_attention(q, new_cache, bias,
                                            cfg.num_key_value_heads)
            out = self.o_proj(out.reshape(
                B, T, cfg.num_attention_heads * cfg.head_dim))
            return out, new_cache
        else:
            ck = jax.lax.dynamic_update_slice(
                layer_cache["k"], k.astype(layer_cache["k"].dtype),
                (0, cache_index, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                layer_cache["v"], v.astype(layer_cache["v"].dtype),
                (0, cache_index, 0, 0))
            new_cache = {"k": ck, "v": cv}
        S = ck.shape[1]
        n_rep = cfg.num_attention_heads // cfg.num_key_value_heads
        kk, vv = repeat_kv(ck, n_rep), repeat_kv(cv, n_rep)
        # mask: key slot j visible iff its position <= this row's query position
        # (covers prefill + decode), within the sliding window when configured
        k_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        bias = _window_bias(positions, k_pos, cfg.sliding_window)
        out = reference_attention(q, kk, vv, bias=bias)
        out = self.o_proj(out.reshape(B, T, cfg.num_attention_heads * cfg.head_dim))
        return out, new_cache


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
                        name="gate_proj")(x)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
                      name="up_proj")(x)
        act = nn.gelu if cfg.mlp_act == "gelu" else nn.silu
        h = act(gate) * up
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                        name="down_proj")(h)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    def setup(self):
        cfg = self.config
        self.input_layernorm = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                                       cfg.norm_plus_one, name="input_layernorm")
        self.post_attention_layernorm = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                                                cfg.norm_plus_one,
                                                name="post_attention_layernorm")
        self.self_attn = LlamaAttention(cfg, name="self_attn")
        self.mlp = LlamaMLP(cfg, name="mlp")

    def __call__(self, x, positions):
        x = x + self.self_attn(self.input_layernorm(x), positions)
        return x + self.mlp(self.post_attention_layernorm(x))

    def decode(self, x, positions, layer_cache, cache_index):
        a, new_cache = self.self_attn.decode(self.input_layernorm(x), positions,
                                             layer_cache, cache_index)
        x = x + a
        return x + self.mlp(self.post_attention_layernorm(x)), new_cache


def causal_lm_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean next-token NLL with shift-by-one (shared by the CausalLM heads).

    logsumexp form: NLL = logsumexp(logits) - logits[label]. Unlike
    log_softmax + gather, this never materialises a second [B, T, V] fp32
    array — on TPU the vocab dim dominates activation memory/bandwidth
    (V=50k fp32 is ~1.6 GB at B=8, T=1024)."""
    logits_s = logits[:, :-1, :]
    labels_s = labels[:, 1:]
    lse = jax.scipy.special.logsumexp(logits_s, axis=-1)
    picked = jnp.take_along_axis(logits_s, labels_s[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - picked)


def chunked_causal_lm_loss(x: jax.Array, vocab_weight: jax.Array,
                           labels: jax.Array, batch_chunk: int = 4,
                           transpose: bool = False,
                           head_bias: Optional[jax.Array] = None) -> jax.Array:
    """Fused projection + cross entropy over batch chunks.

    ``x`` [B, T, C] final hidden states; ``vocab_weight`` [V, C] (embedding
    layout; pass ``transpose=True`` for a [C, V] lm_head kernel). The [B, T, V]
    logits tensor never materialises: each chunk's logits live only inside a
    rematerialised scan body (~chunk*T*V fp32 transient), which is what lets
    large-vocab models run at memory-bound batch sizes — the role of the
    reference's fused logits kernels (inference/v2 logits_gather + vocab-
    parallel loss in Megatron-style training).
    """
    B, T, C = x.shape
    chunk = max(1, min(batch_chunk, B))
    while B % chunk:
        chunk -= 1
    xs = x[:, :-1, :].reshape(B // chunk, chunk, T - 1, C)
    ys = labels[:, 1:].reshape(B // chunk, chunk, T - 1)
    w = vocab_weight if transpose else vocab_weight.T  # [C, V]

    # bf16 models project in bf16 with fp32 MXU accumulation (the v5e runs
    # fp32 matmuls at a fraction of bf16 rate; accumulation stays exact).
    # fp32 models keep the fp32 path bit-for-bit.
    mm_dtype = jnp.bfloat16 if x.dtype == jnp.bfloat16 else jnp.float32

    def body(acc, inp):
        h, y = inp
        logits = jax.lax.dot_general(
            h.astype(mm_dtype), w.astype(mm_dtype),
            (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        if head_bias is not None:
            logits = logits + head_bias.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, y[..., None], axis=-1)[..., 0]
        return acc + jnp.sum(lse - picked), None

    total, _ = jax.lax.scan(jax.checkpoint(body), jnp.float32(0.0), (xs, ys))
    return total / (B * (T - 1))


def decode_layers(model, input_ids, cache, cache_index, positions):
    """Shared incremental-decode trunk for the CausalLM heads (duck-typed over
    ``embed_tokens``/``layers``/``norm``/``lm_head``). Returns (logits, cache)."""
    B, T = input_ids.shape
    if positions is None:
        positions = cache_index + jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    x = model.embed_tokens(input_ids)
    if getattr(model.config, "embed_scale_by_sqrt_dim", False):
        x = (x.astype(jnp.float32)
             * (model.config.hidden_size ** 0.5)).astype(x.dtype)
    new_cols = {key: [] for key in cache}
    for i, layer in enumerate(model.layers):
        layer_cache = {key: cache[key][i] for key in cache}
        x, nc = layer.decode(x, positions, layer_cache, cache_index)
        for key in new_cols:
            new_cols[key].append(nc[key])
    x = model.norm(x)
    logits = model.lm_head(x).astype(jnp.float32)
    return logits, {key: jnp.stack(cols) for key, cols in new_cols.items()}


class LlamaForCausalLM(nn.Module):
    """Training: ``__call__(batch)`` -> loss (engine contract). Inference:
    ``apply(..., method='forward_logits'/'decode')``."""

    config: LlamaConfig

    def setup(self):
        cfg = self.config
        self.embed_tokens = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                                     dtype=cfg.dtype, name="embed_tokens")
        self.layers = [LlamaBlock(cfg, name=f"layers_{i}")
                       for i in range(cfg.num_hidden_layers)]
        self.norm = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_plus_one,
                            name="norm")
        self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                                name="lm_head")

    def _trunk(self, input_ids, positions):
        cfg = self.config
        x = self.embed_tokens(input_ids)
        if cfg.embed_scale_by_sqrt_dim:
            # Gemma normaliser; fp32 round-trip matches HF's bf16 cast order
            x = (x.astype(jnp.float32) * (cfg.hidden_size ** 0.5)).astype(x.dtype)
        x = apply_checkpointed_layers(
            self, x, lambda mdl, h, i: mdl.layers[i](h, positions),
            cfg.num_hidden_layers, cfg.remat, cfg.remat_policy,
            layers=self.layers, layer_args=(positions,))
        return self.norm(x)

    def forward_logits(self, input_ids, positions=None):
        B, T = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = self._trunk(input_ids, positions)
        return self.lm_head(x).astype(jnp.float32)

    def __call__(self, batch, deterministic: bool = True):
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels", input_ids)
        else:
            input_ids, labels = batch, batch
        B, T = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = self._trunk(input_ids, positions)
        # instantiate the head params (negligible [B,1,V] call, DCE'd after
        # init), then fused chunked projection+CE — the [B,T,V] logits never
        # materialise (chunked_causal_lm_loss)
        _ = self.lm_head(x[:, :1])
        kernel = self.lm_head.variables["params"]["kernel"]
        return chunked_causal_lm_loss(x, kernel, labels, transpose=True,
                                      batch_chunk=self.config.lm_loss_chunk)

    def decode(self, input_ids, cache, cache_index, positions=None):
        """One incremental step (prefill or single-token decode).

        input_ids: [B, T]; cache: pytree from ``init_cache`` — {"k","v"}:
        [L, B, S_max, H_kv, D]; cache_index: int32 write offset.
        Returns (logits [B, T, V] fp32, new_cache)."""
        return decode_layers(self, input_ids, cache, cache_index, positions)


def quantized_cache_append(layer_cache, k, v, cache_index):
    """Quantize this step's K/V rows (per token-head symmetric int8) and
    append them to an int8 dense cache (v1 KV tier; ZeRO-Inference analog,
    reference README.md:23). Returns the updated cache dict."""
    new_cache = {}
    for name, rows in (("k", k), ("v", v)):
        scale = jnp.max(jnp.abs(rows.astype(jnp.float32)),
                        axis=-1) / 127.0                        # [B,T,Hkv]
        scale = jnp.maximum(scale, 1e-8)
        q8 = jnp.clip(jnp.round(rows.astype(jnp.float32) / scale[..., None]),
                      -127, 127).astype(jnp.int8)
        new_cache[name] = jax.lax.dynamic_update_slice(
            layer_cache[name], q8, (0, cache_index, 0, 0))
        new_cache[f"{name}_scale"] = jax.lax.dynamic_update_slice(
            layer_cache[f"{name}_scale"], scale, (0, cache_index, 0))
    return new_cache


def quantized_cache_attention(q, cache, bias, num_kv_heads,
                              softmax_scale=None):
    """Attention over an int8 dense cache with the dequant FOLDED into the
    dots (ADVICE r4): per-token-head scales multiply score columns (K) and
    p (V) — no dequantized [B, S, Hkv, D] cache and no repeat_kv to H heads
    is ever materialised.

    q [B, T, H, D]; cache {"k","v" int8 [B,S,Hkv,D], "k_scale","v_scale"
    [B,S,Hkv] f32}; bias additive f32 [B, 1|H, T, S] (window mask and/or
    ALiBi). Returns [B, T, H, D] in q's dtype."""
    B, T, H, D = q.shape
    S = cache["k"].shape[1]
    Hkv = num_kv_heads
    G = H // Hkv
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D ** 0.5)
    qg = q.reshape(B, T, Hkv, G, D).astype(jnp.float32)
    sc = jnp.einsum("btkgd,bskd->btkgs", qg,
                    cache["k"].astype(jnp.float32)) * scale
    sc = sc * cache["k_scale"].astype(jnp.float32) \
        .transpose(0, 2, 1)[:, None, :, None, :]
    bias_b = jnp.broadcast_to(bias, (B, H, T, S)) \
        .reshape(B, Hkv, G, T, S).transpose(0, 3, 1, 2, 4)
    p = jax.nn.softmax(sc + bias_b, axis=-1)
    pv = p * cache["v_scale"].astype(jnp.float32) \
        .transpose(0, 2, 1)[:, None, :, None, :]
    out = jnp.einsum("btkgs,bskd->btkgd", pv,
                     cache["v"].astype(jnp.float32))
    return out.reshape(B, T, H, D).astype(q.dtype)


def init_cache(config: LlamaConfig, batch_size: int, max_len: int,
               dtype: Any = None, kv_bits: Any = None) -> Dict[str, jax.Array]:
    """Dense per-sequence KV cache (inference v1 path; the v2 engine uses the
    blocked/paged cache in deepspeed_tpu.inference.ragged instead).

    ``kv_bits=8``: int8 storage with per-token-per-head f32 scales
    (ZeRO-Inference KV tier — the persistent cache halves, so servable
    context x batch at fixed HBM ~doubles; reference README.md:23)."""
    dtype = dtype or config.dtype
    shape = (config.num_hidden_layers, batch_size, max_len,
             config.num_key_value_heads, config.head_dim)
    if kv_bits == 8:
        sshape = shape[:-1]
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(sshape, jnp.float32),
                "v_scale": jnp.zeros(sshape, jnp.float32)}
    if kv_bits is not None:
        raise ValueError(f"kv_bits must be None or 8, got {kv_bits!r}")
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
