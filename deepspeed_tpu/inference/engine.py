"""Inference engine v1: TP-sharded jitted forward + KV-cache generation.

Parity: ``InferenceEngine`` (reference ``deepspeed/inference/engine.py:39``) —
``init_inference(model, config)`` wraps a model for serving: model-parallel group
creation (``:254``), AutoTP / kernel-injection sharding (``:408``), checkpoint
loading (``:331``), CUDA-graph capture (``:524``), and a patched ``generate``.

TPU-native re-design:
  - "MP group creation" = a mesh with a 'tensor' axis sized ``tp_size``.
  - "AutoTP weight slicing" = PartitionSpec rules (``parallel/tensor_parallel``);
    XLA's SPMD partitioner derives the column/row-parallel compute and the
    per-layer all-reduce the reference's ``LinearAllreduce`` modules issue by hand.
  - "CUDA graph capture" = jit compilation (always on; ``enable_cuda_graph`` is
    accepted and ignored).
  - "kernel injection" = the ops layer's Pallas routing (``ops/attention.py``),
    always active on TPU.
  - generation: jitted prefill + jitted single-token decode step with a donated
    dense KV cache (the paged/ragged cache belongs to the v2 engine).

The model must follow the zoo decode protocol (``models/llama.py``):
``apply(..., method='forward_logits')`` and ``apply(ids, cache, index,
method='decode')``; cache built by ``models.llama.init_cache``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS,
                                     MeshTopology, build_topology, get_topology,
                                     set_topology)
from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.tree import tree_cast


class InferenceEngine:
    """See module docstring."""

    def __init__(self,
                 model: Any,
                 config: InferenceConfig,
                 model_parameters: Optional[Any] = None,
                 mesh_topology: Optional[MeshTopology] = None,
                 init_cache_fn: Optional[Callable] = None):
        self.config = config
        self.module = model
        self.model_config = getattr(model, "config", None)

        tp = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        ep = config.moe.ep_size if config.moe.enabled else 1
        if mesh_topology is not None:
            # register so global-topology readers (e.g. MoE sharding constraints)
            # see the same mesh this engine shards over
            self.topology = set_topology(mesh_topology)
        else:
            n = len(jax.devices())
            if tp * ep > n:
                raise ValueError(f"tp_size*ep_size={tp * ep} > {n} devices")
            self.topology = set_topology(build_topology(
                MeshConfig(tensor=tp, expert=ep, data=n // (tp * ep), fsdp=1)))
        self._dtype = config.compute_dtype

        # -- params: load -> cast -> quantize -> shard --------------------- #
        params = model_parameters
        if params is None and config.checkpoint.checkpoint_dir:
            params = self._load_checkpoint_params(config.checkpoint.checkpoint_dir,
                                                  config.checkpoint.tag)
        if params is None:
            raise ValueError("init_inference needs model_parameters or "
                             "config.checkpoint.checkpoint_dir")
        params = tree_cast(params, self._dtype)
        self._tp_specs = self._derive_specs(params)
        self._weights_quantized = bool(config.quant.enabled)
        if self._weights_quantized:
            # true int8 storage (HBM footprint /2 vs bf16): dequant happens at
            # jit entry in forward/prefill/decode via _live_params
            self.params = self._shard_params_quantized(params)
        else:
            self.params = self._shard_params(params)

        self._init_cache_fn = init_cache_fn
        self._prefill = None
        self._decode_step = None
        self._forward = None
        self._rng = jax.random.PRNGKey(config.seed)
        log_dist(f"init_inference: tp={tp} ep={ep} dtype={config.dtype} "
                 f"quant={'on' if config.quant.enabled else 'off'}", ranks=[0])

    # ------------------------------------------------------------------ #
    # setup helpers
    # ------------------------------------------------------------------ #

    def _load_checkpoint_params(self, ckpt_dir: str, tag: Optional[str]):
        """Parity: engine.py:331 _load_checkpoint — reads the training layout's
        model_states file into a param pytree (keys are '/'-joined paths)."""
        import os
        from deepspeed_tpu.checkpoint.state import (MODEL_FILE, read_latest_tag)
        tag = tag or read_latest_tag(ckpt_dir) or ""
        path = os.path.join(ckpt_dir, tag, MODEL_FILE)
        data = np.load(path)
        tree: Dict[str, Any] = {}
        for key in data.files:
            parts = key.split("/")
            node = tree
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = data[key]
        return tree

    @staticmethod
    def _quantizable(path, leaf) -> bool:
        """Matmul weights only: the reference's post-init quant skips
        embeddings and norms (inference/quantization/utils.py)."""
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        return (getattr(leaf, "ndim", 0) >= 2 and "embed" not in name
                and "norm" not in name.lower())

    def _shard_params_quantized(self, params):
        """ZeRO-inference weight-only quantization with REAL int8 storage
        (parity: inference/quantization/quantization.py + layers.py dequant-
        on-the-fly): each matmul weight becomes {q: int8, s: fp32 row scales}
        placed with the weight's TP sharding (scales replicate the sharded-out
        last dim)."""
        from deepspeed_tpu.runtime.zero.zeropp import quantize_leaf
        topo = self.topology

        def base_sharding(leaf, spec):
            return NamedSharding(topo.mesh, spec if spec is not None else P())

        spec_tree = self._tp_specs
        if spec_tree is None:
            spec_tree = jax.tree_util.tree_map(lambda _: P(), params)

        bits = int(self.config.quant.bits)
        group = int(self.config.quant.group_size)

        def one(path, leaf, spec):
            sh = base_sharding(leaf, spec)
            if not self._quantizable(path, leaf):
                return jax.device_put(leaf, sh)
            d = jax.jit(lambda x: quantize_leaf(x, num_bits=bits,
                                                group_size=group))(jnp.asarray(leaf))
            s_spec = list(spec) if spec else []
            while len(s_spec) < leaf.ndim:
                s_spec.append(None)
            # scale shape is leaf.shape[:-1] + (n_groups, 1)
            s_sh = NamedSharding(topo.mesh, P(*(s_spec[:-1] + [None, None])))
            return {"q": jax.device_put(d["q"], sh),
                    "s": jax.device_put(d["s"], s_sh)}

        # leaves follow `params`; the spec subtree (a P or None) passes whole
        return jax.tree_util.tree_map_with_path(one, params, spec_tree)

    def _live_params(self, params):
        """Dequantize inside jit (XLA fuses the int8*scale expansion into the
        consuming matmuls; weights stay int8 in HBM)."""
        if not self._weights_quantized:
            return params
        from deepspeed_tpu.runtime.zero.zeropp import dequantize_param_tree
        return dequantize_param_tree(params, self._dtype)

    def _derive_specs(self, params):
        topo = self.topology
        specs = None
        if topo.tp_world_size > 1:
            from deepspeed_tpu.parallel.tensor_parallel import (derive_tp_specs,
                                                                tp_rules_for)
            family = self.config.model_family or _guess_family(self.module)
            specs = derive_tp_specs(params, tp_rules_for(family), topo.tp_world_size)
        if topo.ep_world_size > 1:
            from deepspeed_tpu.parallel.moe import derive_ep_specs
            ep = derive_ep_specs(params, topo.ep_world_size)
            if specs is None:
                specs = ep
            else:
                specs = jax.tree_util.tree_map(
                    lambda t, e: e if tuple(e) != () else t, specs, ep,
                    is_leaf=lambda s: isinstance(s, P))
        return specs

    def _shard_params(self, params):
        topo = self.topology
        if self._tp_specs is None:
            sh = jax.tree_util.tree_map(lambda _: topo.replicated(), params)
        else:
            sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(topo.mesh, s), self._tp_specs,
                is_leaf=lambda s: isinstance(s, P))
        return jax.device_put(params, sh)

    def _cache_sharding(self, cache):
        """KV cache [L, B, S, H_kv, D]: batch over 'data', heads over 'tensor'
        when divisible (the reference slices the KV heads across TP ranks in its
        injected attention modules)."""
        topo = self.topology
        tp = topo.tp_world_size

        def sh(x):
            # rank >= 5: KV arrays [L, B, S, H_kv, D]; rank 4: the int8
            # tier's scale arrays [L, B, S, H_kv] — same batch/head layout
            spec = [None] * x.ndim
            if x.ndim >= 4:
                if x.shape[1] % max(topo.sizes[DATA_AXIS], 1) == 0:
                    spec[1] = DATA_AXIS
                if tp > 1 and x.shape[3] % tp == 0:
                    spec[3] = TENSOR_AXIS
            return NamedSharding(topo.mesh, P(*spec))

        return jax.tree_util.tree_map(sh, cache)

    def _make_cache(self, batch_size: int, max_len: int):
        fn = self._init_cache_fn
        from deepspeed_tpu.models.decoder import DecoderLM, init_decoder_cache
        from deepspeed_tpu.models.llama import init_cache
        if fn is None:
            fn = (init_decoder_cache if isinstance(self.module, DecoderLM)
                  else init_cache)
        if self.config.kv_quant.enabled:
            # int8 KV tier (ZeRO-Inference analog) — llama lineage AND the
            # decoder zoo (VERDICT r4 #9); custom cache factories must
            # accept kv_bits to opt in
            cache = fn(self.model_config, batch_size, max_len,
                       dtype=self._dtype, kv_bits=self.config.kv_quant.bits)
        else:
            cache = fn(self.model_config, batch_size, max_len,
                       dtype=self._dtype)
        return jax.device_put(cache, self._cache_sharding(cache))

    # ------------------------------------------------------------------ #
    # forward / generate
    # ------------------------------------------------------------------ #

    def forward(self, input_ids) -> jax.Array:
        """Full-sequence logits (parity: InferenceEngine.forward engine.py:584)."""
        if self._forward is None:
            mod = self.module

            def fwd(params, ids):
                params = self._live_params(params)
                return mod.apply({"params": params}, ids,
                                 method=type(mod).forward_logits)

            self._forward = jax.jit(fwd)
        return self._forward(self.params, jnp.asarray(input_ids))

    __call__ = forward

    def _build_gen_steps(self):
        mod = self.module
        method = type(mod).decode

        def prefill(params, ids, cache):
            params = self._live_params(params)
            logits, cache = mod.apply({"params": params}, ids, cache,
                                      jnp.int32(0), method=method)
            return logits[:, -1, :], cache

        def step(params, tok, cache, index):
            params = self._live_params(params)
            logits, cache = mod.apply({"params": params}, tok, cache, index,
                                      method=method)
            return logits[:, -1, :], cache

        self._prefill = jax.jit(prefill, donate_argnums=(2,))
        self._decode_step = jax.jit(step, donate_argnums=(2,))

    def _sample(self, logits: jax.Array, do_sample: bool, temperature: float,
                top_k: int) -> jax.Array:
        if not do_sample:
            return jnp.argmax(logits, axis=-1)
        self._rng, key = jax.random.split(self._rng)
        logits = logits / jnp.maximum(temperature, 1e-6)
        if top_k > 0:
            kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
            logits = jnp.where(logits < kth, jnp.finfo(logits.dtype).min, logits)
        return jax.random.categorical(key, logits, axis=-1)

    def generate(self,
                 input_ids,
                 max_new_tokens: int = 32,
                 do_sample: bool = False,
                 temperature: float = 1.0,
                 top_k: int = 0,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        """Autoregressive generation (parity: the reference patches
        ``model.generate`` through its injected modules; here an explicit jitted
        prefill + decode loop). Returns [B, T + max_new_tokens] token ids."""
        ids = jnp.asarray(input_ids)
        B, T = ids.shape
        if max_new_tokens > self.config.max_out_tokens:
            raise ValueError(f"max_new_tokens {max_new_tokens} exceeds "
                             f"config.max_out_tokens {self.config.max_out_tokens}")
        max_len = T + max_new_tokens
        if max_len > self.config.max_tokens:
            raise ValueError(f"prompt+generation {max_len} exceeds "
                             f"config.max_tokens {self.config.max_tokens}")
        if self._prefill is None:
            self._build_gen_steps()
        cache = self._make_cache(B, max_len)
        logits, cache = self._prefill(self.params, ids, cache)

        out = [np.asarray(ids)]
        tok = self._sample(logits, do_sample, temperature, top_k)
        finished = np.zeros((B,), bool)
        for i in range(max_new_tokens):
            tok_np = np.asarray(tok)
            if eos_token_id is not None and i + 1 >= self.config.min_out_tokens:
                tok_np = np.where(finished, eos_token_id, tok_np)
                finished |= tok_np == eos_token_id
            out.append(tok_np[:, None])
            if eos_token_id is not None and finished.all():
                break
            if i + 1 == max_new_tokens:
                break
            logits, cache = self._decode_step(self.params, jnp.asarray(tok_np)[:, None],
                                              cache, jnp.int32(T + i))
            tok = self._sample(logits, do_sample, temperature, top_k)
        return np.concatenate(out, axis=1)

    # ------------------------------------------------------------------ #

    @property
    def mp_world_size(self) -> int:
        return self.topology.tp_world_size

    def module_state_dict(self):
        """Plain weight tree (quantized storage is dequantized for export, so
        the return shape is stable regardless of ``quant.enabled``)."""
        if self._weights_quantized:
            return jax.device_get(jax.jit(self._live_params)(self.params))
        return jax.device_get(self.params)


def _guess_family(model) -> Optional[str]:
    fam = getattr(getattr(model, "config", None), "family", None)
    if fam:
        return fam
    name = type(model).__name__.lower()
    for fam in ("mixtral", "llama", "gpt2", "bert", "neox", "mistral"):
        if fam in name:
            return "llama" if fam == "mistral" else fam
    return None
