"""Inference subsystem.

v1 (``engine.py``): TP-sharded jitted forward/generate with a dense KV cache —
parity with the reference's kernel-injection/AutoTP ``InferenceEngine``
(``deepspeed/inference/engine.py:39``).

v2 (``ragged/``, ``engine_v2.py``): FastGen-class continuous batching over a
blocked/paged KV cache with Dynamic-SplitFuse scheduling — parity with
``deepspeed/inference/v2``.
"""

from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine
