"""Inference engine configuration.

Parity: ``DeepSpeedInferenceConfig`` (reference ``deepspeed/inference/config.py``) —
the same knob surface (tensor_parallel.tp_size, dtype, max_out_tokens, quant,
checkpoint, replace_with_kernel_inject) re-based on this repo's dataclass config
tree. CUDA-graph options are accepted-and-ignored (XLA jit compilation subsumes
graph capture); kernel injection maps to the Pallas kernel routing that is always
on for TPU.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax.numpy as jnp

from deepspeed_tpu.config import ConfigError, ConfigModel

_DTYPES = {"float32": jnp.float32, "fp32": jnp.float32,
           "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
           "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
           "int8": jnp.int8}


@dataclass
class TPConfig(ConfigModel):
    """Parity: ``DeepSpeedTPConfig`` (inference/config.py:47)."""
    enabled: bool = True
    tp_size: int = 1


@dataclass
class InferenceMoEConfig(ConfigModel):
    """Parity: ``DeepSpeedMoEConfig`` (inference/config.py:65)."""
    enabled: bool = True
    ep_size: int = 1
    moe_experts: Any = field(default_factory=lambda: [1])


@dataclass
class WeightQuantConfig(ConfigModel):
    """Parity: ``WeightQuantConfig`` (inference/config.py:100) + ZeRO-inference
    weight-only quantization (inference/quantization)."""
    enabled: bool = False
    bits: int = 8
    group_size: int = 64


@dataclass
class KVQuantConfig(ConfigModel):
    """int8 KV cache (ZeRO-Inference long-context tier — the reference pairs
    weight quantization with KV-cache offload/quantization for its 20x claim,
    README.md:23). Per-token-per-head symmetric int8 with f32 scales: the
    persistent cache halves, so max servable context x batch at fixed HBM
    ~doubles. Supported by the llama-lineage v1 path."""
    enabled: bool = False
    bits: int = 8

    def __post_init__(self):
        if self.enabled and self.bits != 8:
            raise ConfigError(f"kv_quant.bits must be 8, got {self.bits!r}")


@dataclass
class InferenceCheckpointConfig(ConfigModel):
    """Parity: checkpoint loading args of ``DeepSpeedInferenceConfig``."""
    checkpoint_dir: Optional[str] = None
    tag: Optional[str] = None


@dataclass
class InferenceConfig(ConfigModel):
    """Parity: ``DeepSpeedInferenceConfig`` (inference/config.py:125+)."""
    dtype: str = "bfloat16"
    tensor_parallel: TPConfig = field(default_factory=TPConfig)
    moe: InferenceMoEConfig = field(default_factory=InferenceMoEConfig)
    quant: WeightQuantConfig = field(default_factory=WeightQuantConfig)
    kv_quant: KVQuantConfig = field(default_factory=KVQuantConfig)
    checkpoint: InferenceCheckpointConfig = field(default_factory=InferenceCheckpointConfig)
    max_out_tokens: int = 1024
    min_out_tokens: int = 1
    max_tokens: int = 4096          # prompt + generation KV budget per sequence
    replace_with_kernel_inject: bool = False   # accepted; Pallas routing is implicit
    enable_cuda_graph: bool = False            # accepted-and-ignored (XLA jit)
    model_family: Optional[str] = None         # TP rule table selector
    seed: int = 0

    @property
    def compute_dtype(self):
        if self.dtype not in _DTYPES:
            raise ConfigError(f"inference dtype {self.dtype!r} not in {sorted(_DTYPES)}")
        return _DTYPES[self.dtype]

    @classmethod
    def load(cls, config: Optional[Dict[str, Any]] = None, **kwargs) -> "InferenceConfig":
        import copy
        data = copy.deepcopy(dict(config or {}))  # never mutate the caller's dict
        data.update(kwargs)
        # legacy flat aliases (reference accepts mp_size at top level)
        if "mp_size" in data:
            tp = data.setdefault("tensor_parallel", {})
            if isinstance(tp, dict):
                tp.setdefault("tp_size", data.pop("mp_size"))
            else:
                data.pop("mp_size")
        data.pop("replace_method", None)  # deprecated in reference, ignored here
        return cls.from_dict(data)
