"""One attention-kernel interface for the v2 serving stack.

``AttentionKernelSpec`` is the single dispatch surface every v2 device
program routes its attention through — the ragged paged pass, the packed
prefill fast path, the fused decode-step/multistep programs, and the
speculative verify step (``ragged_model.py`` builders). Before it existed,
each builder picked kernels per call site (window/alibi partials, TP
shard_map wrapping, int8-scale keyword plumbing) and the engine carried one
build-time refusal per (feature x feature) pair that had never been wired;
composing a new pool layout meant touching every site. Now:

- **trace-time dispatch** keys on the pool's dtype at the call: every method
  takes ``kv_scales=None`` — ``None`` is a bf16/f32 pool, a scale-tile array
  is an int8 pool and the method routes to the kernel's dequantizing
  variant. Sliding window and ALiBi are bound once at construction.
- **build-time capability** lives in ONE table
  (:meth:`validate_engine_build`): the engine asks it instead of scattering
  refusals, so what composes (int8 x prefix cache, int8 x spec decode,
  int8 x page fabric) and what does not (int8 x tensor parallel,
  spec x sliding window) is decided — and tested — in one place
  (tests/unit/test_kv_quant_stack.py pins the surviving refusal messages).

int8 write semantics (the invariant the byte gates rest on): quantize-on-
write is the semantic boundary — every program attends a token through the
value its int8 page stores. Paths that write-then-attend (ragged pass
decode rows, spec verify) get this for free; fused paths that attend the
current token from registers or the side slab pass new K/V through
``kv_write_dequant`` first (``ops/pallas/paged_attention.py``), so all
paths agree on the attended VALUES and differ only at cross-kernel
float-association noise (~1e-7 — the same level the fp16 byte-stream gates
already tolerate between the chunk/decode/sidebuf kernels).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

from deepspeed_tpu.ops.pallas.flash_attention import flash_attention_packed
from deepspeed_tpu.ops.pallas.paged_attention import (
    paged_chunk_attention_batched, paged_decode_attention,
    paged_decode_attention_sidebuf, paged_decode_attention_step)
from deepspeed_tpu.ops.pallas.paged_splitk import (
    paged_chunk_attention_splitk, paged_decode_attention_splitk,
    paged_decode_attention_splitk_step, paged_sidebuf_attention_splitk)

_QUANT_TP_MSG = "int8 KV pages + TP not wired"
_SPLIT_TP_MSG = ("attention.decode_splits > 1 with tensor_parallel > 1 is "
                 "not wired (the split-K LSE merge would land outside the "
                 "shard_map body)")


class AttentionKernelSpec:
    """Kernel dispatch for one model spec on one mesh.

    Construction binds the per-model statics (window, alibi, tp, mesh);
    each method is called inside a traced program with the per-layer pool
    view and routes to the right kernel variant. TP wrapping (shard_map on
    the 'tensor' axis) is applied here — one helper, identical in_specs per
    kernel shape — so no builder carries its own wrapping."""

    def __init__(self, spec: Any, mesh=None, tp: int = 1, n_splits: int = 1):
        self.spec = spec
        self.mesh = mesh
        self.tp = int(tp)
        self.n_splits = int(n_splits)
        if self.n_splits > 1:
            # flash-decoding rung: every paged caller routes through the
            # split-K dispatchers so decode, fused step, sidebuf and spec
            # verify all ride the same ladder rung (ONE compiled program
            # per rung). tp > 1 keeps the chunk-serial path — refused at
            # build time by validate_engine_build.
            assert self.tp == 1, _SPLIT_TP_MSG
            ns = self.n_splits
            self._decode = functools.partial(
                paged_decode_attention_splitk, window=spec.window,
                alibi=spec.alibi, n_splits=ns)
            self._chunk = functools.partial(
                paged_chunk_attention_splitk, window=spec.window,
                alibi=spec.alibi, n_splits=ns)
            self._step = functools.partial(
                paged_decode_attention_splitk_step, window=spec.window,
                alibi=spec.alibi, n_splits=ns)
            self._sidebuf = functools.partial(
                paged_sidebuf_attention_splitk, window=spec.window,
                alibi=spec.alibi, n_splits=ns)
        else:
            self._decode = functools.partial(
                paged_decode_attention, window=spec.window, alibi=spec.alibi)
            self._chunk = functools.partial(
                paged_chunk_attention_batched, window=spec.window,
                alibi=spec.alibi)
            self._step = functools.partial(
                paged_decode_attention_step, window=spec.window,
                alibi=spec.alibi)
            self._sidebuf = functools.partial(
                paged_decode_attention_sidebuf, window=spec.window,
                alibi=spec.alibi)
        self._packed = functools.partial(flash_attention_packed,
                                         window=spec.window)

    # ------------------------------------------------------------------ #
    # build-time capability surface
    # ------------------------------------------------------------------ #

    @staticmethod
    def validate_engine_build(spec: Any, cfg: Any) -> None:
        """THE build-time capability table for the v2 engine: raises the
        canonical refusal for every (feature x feature) pair the kernel
        surface cannot carry, in one place. ``spec`` is the adapted
        :class:`~deepspeed_tpu.inference.v2.ragged_model.RaggedModelSpec``,
        ``cfg`` the :class:`RaggedInferenceEngineConfig`. What is absent
        here COMPOSES: int8 KV pages run under the prefix cache, spec
        decode, preempt-offload and the cross-engine page fabric (the PR
        that collapsed those three former refusals into this table)."""
        if cfg.kv_quant.enabled:
            if cfg.tensor_parallel > 1:
                raise NotImplementedError(
                    "kv_quant with tensor_parallel > 1 is not wired")
            if (spec.head_dim % 128 != 0
                    or (spec.num_kv_heads * cfg.kv_cache.block_size)
                    % 128 != 0):
                raise ValueError(
                    "kv_quant needs head_dim % 128 == 0 and "
                    "num_kv_heads * block_size % 128 == 0 (the kernels' "
                    "scale-tile lane alignment; got head_dim="
                    f"{spec.head_dim}, num_kv_heads={spec.num_kv_heads}, "
                    f"block_size={cfg.kv_cache.block_size})")
        attn = getattr(cfg, "attention", None)
        if attn is not None and attn.decode_splits > 1:
            if cfg.tensor_parallel > 1:
                raise NotImplementedError(_SPLIT_TP_MSG)
            # everything else composes: sliding window / ALiBi mask inside
            # each split, int8 dequant per gathered page, spec verify rides
            # the chunk dispatcher, small head dims take the XLA scan
        if cfg.prefix_cache.enabled and spec.window is not None:
            raise NotImplementedError(
                "prefix_cache with a sliding-window model is not wired: "
                "the page ring overwrites pages in place, which would rot "
                "cached content under a live sharer")
        if cfg.spec_decode.enabled and spec.window is not None:
            raise NotImplementedError(
                "spec_decode with a sliding-window model is not wired "
                "(the page ring aliases the verify step's k+1-ahead "
                "write span)")

    # ------------------------------------------------------------------ #
    # trace-time dispatch (called inside jitted programs)
    # ------------------------------------------------------------------ #

    def _tp_wrap(self, fn, in_specs, out_specs):
        from deepspeed_tpu.utils.jax_compat import shard_map
        return shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)

    def decode(self, q, kv_l, block_tables, ctx_lens,
               kv_scales: Optional[Any] = None):
        """Single-token-per-sequence decode attention (one ctx-bounded
        query row per sequence) over the per-layer pool view ``kv_l``
        ([L*NB, 2, Hkv, bs, D]; block tables pre-offset by l*NB)."""
        if self.tp > 1:
            assert kv_scales is None, _QUANT_TP_MSG
            from jax.sharding import PartitionSpec as P
            from deepspeed_tpu.comm.mesh import TENSOR_AXIS
            fn = self._tp_wrap(
                self._decode,
                in_specs=(P(None, TENSOR_AXIS, None),
                          P(None, None, TENSOR_AXIS, None, None),
                          P(None, None), P(None)),
                out_specs=P(None, TENSOR_AXIS, None))
            return fn(q, kv_l, block_tables, ctx_lens)
        if kv_scales is not None:
            return self._decode(q, kv_l, block_tables, ctx_lens,
                                kv_scales=kv_scales)
        return self._decode(q, kv_l, block_tables, ctx_lens)

    def chunk(self, q, kv_l, block_tables, q_starts, ctx_lens,
              kv_scales: Optional[Any] = None):
        """Batched prompt-chunk (and spec-verify) flash attention: one slot
        per chunk, causal by absolute position."""
        if self.tp > 1:
            assert kv_scales is None, _QUANT_TP_MSG
            from jax.sharding import PartitionSpec as P
            from deepspeed_tpu.comm.mesh import TENSOR_AXIS
            fn = self._tp_wrap(
                self._chunk,
                in_specs=(P(None, None, TENSOR_AXIS, None),
                          P(None, None, TENSOR_AXIS, None, None),
                          P(None, None), P(None), P(None)),
                out_specs=P(None, None, TENSOR_AXIS, None))
            return fn(q, kv_l, block_tables, q_starts, ctx_lens)
        if kv_scales is not None:
            return self._chunk(q, kv_l, block_tables, q_starts, ctx_lens,
                               kv_scales=kv_scales)
        return self._chunk(q, kv_l, block_tables, q_starts, ctx_lens)

    def decode_step(self, q, k_new, v_new, kv_l, block_tables, ctx_lens,
                    kv_scales: Optional[Any] = None):
        """Fused write+attend decode step (pool aliased through the kernel;
        new rows scattered after). Returns ``(out, kv_l)`` — with scales,
        ``(out, kv_l, kv_scales)``. For int8 pools pass ``k_new/v_new``
        through ``kv_write_dequant`` first (module docstring)."""
        if self.tp > 1:
            assert kv_scales is None, _QUANT_TP_MSG
            from jax.sharding import PartitionSpec as P
            from deepspeed_tpu.comm.mesh import TENSOR_AXIS
            fn = self._tp_wrap(
                self._step,
                in_specs=(P(None, TENSOR_AXIS, None),
                          P(None, TENSOR_AXIS, None),
                          P(None, TENSOR_AXIS, None),
                          P(None, None, TENSOR_AXIS, None, None),
                          P(None, None), P(None)),
                out_specs=(P(None, TENSOR_AXIS, None),
                           P(None, None, TENSOR_AXIS, None, None)))
            return fn(q, k_new, v_new, kv_l, block_tables, ctx_lens)
        if kv_scales is not None:
            return self._step(q, k_new, v_new, kv_l, block_tables, ctx_lens,
                              kv_scales=kv_scales)
        return self._step(q, k_new, v_new, kv_l, block_tables, ctx_lens)

    def sidebuf(self, q, kv_l, block_tables, prefix_lens, side_k, side_v, j,
                layer_idx, kv_scales: Optional[Any] = None):
        """Frozen-prefix + side-slab decode attention (the scatter-free
        multistep schedule). Only reachable at tp == 1 (the multistep
        builder's side-buffer gate), so no TP wrap. For int8 pools the
        slab must hold ``kv_write_dequant``'d rows (module docstring)."""
        assert self.tp == 1, "side-buffer schedule is tp == 1 only"
        kw = {} if kv_scales is None else dict(kv_scales=kv_scales)
        return self._sidebuf(q, kv_l, block_tables, prefix_lens,
                             side_k, side_v, j, layer_idx=layer_idx, **kw)

    def packed(self, q, k, v, seg):
        """Packed segment-masked prefill flash (no paged reads — the
        prefill-from-zero fast path)."""
        if self.tp > 1:
            from jax.sharding import PartitionSpec as P
            from deepspeed_tpu.comm.mesh import TENSOR_AXIS
            fn = self._tp_wrap(
                self._packed,
                in_specs=(P(None, TENSOR_AXIS, None),
                          P(None, TENSOR_AXIS, None),
                          P(None, TENSOR_AXIS, None), P(None)),
                out_specs=P(None, TENSOR_AXIS, None))
            return fn(q, k, v, seg)
        return self._packed(q, k, v, seg)
