"""Adapter registry: the lifecycle/refcount half of multi-tenant LoRA.

State machine per adapter (docs/SERVING.md "Multi-tenant LoRA"):

    REGISTERED --fault-in--> RESIDENT --evict--> EVICTED
         \\______________________________________/
                   (restore = fault-in from pinned buffers)

- **REGISTERED**: the validated checkpoint payload lives as a host master
  copy (``[rank, elements]``, pool dtype) — no device pages yet.
- **RESIDENT**: the adapter owns ``rank`` pool pages; its weights are
  gatherable by the decode programs. Residency persists after the last
  in-flight request releases it (an LRU cache, like KV prefix blocks).
- **EVICTED**: pages were fetched device->host into pinned
  ``SwapBufferPool`` buffers and freed — restore scatters the SAME bytes
  back (byte-exact round trip, the KV offload contract), returning the
  buffers to the pool.

Refcounts gate eviction exactly like KV pages: an adapter bound to any
in-flight request can never be evicted, so a decode batch's gather is
always backed. Fault-in under pool pressure evicts idle adapters LRU;
``maybe_fail("serve.lora_fault")`` sits inside the fault-in so the chaos
bench can cancel mid-fault (rollback: allocated pages freed, binding
undone, refcounts at baseline).

Each fault-in/evict takes ONE pair of ``perf_counter`` stamps feeding both
the ``serve/lora/{fault,swap}`` tracer spans and the :class:`LoraStats`
counters (the stats-equals-spans discipline, docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from deepspeed_tpu.inference.v2.lora.pool import LoraPagePool
from deepspeed_tpu.monitor.serving import LoraStats
from deepspeed_tpu.monitor.trace import tracer as _tracer
from deepspeed_tpu.runtime.swap_tensor.buffer_pool import SwapBufferPool
from deepspeed_tpu.utils.caching import next_pow2
from deepspeed_tpu.utils.fault_injection import maybe_fail as _maybe_fail
from deepspeed_tpu.utils.threads import make_lock

REGISTERED = "registered"
RESIDENT = "resident"
EVICTED = "evicted"


@dataclass
class _Adapter:
    name: str
    rank: int
    master: Optional[np.ndarray]          # [rank, elements] host master
    state: str = REGISTERED
    page_ids: List[int] = field(default_factory=list)
    bufs: List[np.ndarray] = field(default_factory=list)   # pinned (EVICTED)
    refcount: int = 0
    last_used: int = 0                    # LRU clock stamp


class LoraAdapterRegistry:
    """Adapter lifecycle over one :class:`LoraPagePool`.

    ONE mutator thread by design (the frontend's engine thread / the bench
    driver — the same discipline as the scheduler), but the cheap metadata
    readers (``names``/``rank``/``is_resident``/``can_admit``/``binding``)
    are called from CLIENT threads (``frontend.submit`` validation) and the
    router's adapter-state probe, so the maps they iterate are guarded by
    ``serving.lora.registry``. Device work — fault-in scatter, eviction
    fetch, the residency sync — always runs OUTSIDE that lock (threadlint
    TL002): a client thread listing adapters must never wait out a swap.
    The engine exposes this as ``engine.lora``."""

    def __init__(self, pool: LoraPagePool, swap_buffers: int = 16,
                 max_rank: Optional[int] = None,
                 stats: Optional[LoraStats] = None):
        self.pool = pool
        self.max_rank = max_rank
        self.swap = SwapBufferPool(max_buffers=swap_buffers)
        self.stats = stats if stats is not None else LoraStats()
        # guards _adapters/_bindings map SHAPE + adapter metadata fields
        # (state/refcount/rank) for cross-thread readers; device work and
        # payload copies stay outside it
        self._meta = make_lock("serving.lora.registry")
        self._adapters: Dict[str, _Adapter] = {}
        self._bindings: Dict[int, str] = {}   # uid -> adapter name
        self._clock = 0

    # -- registration ----------------------------------------------------- #

    def register(self, name: str, pages: Optional[np.ndarray]) -> None:
        """Register a validated adapter payload (``module_inject.lora``
        packs checkpoints into this page layout).

        ``pages``: ``[rank, elements]`` rank-slice rows in the pool dtype,
        or ``None``/empty for a rank-0 (no-op) adapter — rank-0 adapters
        own no pages, are trivially resident, and never join the rank
        bucket. Duplicate names: an IDENTICAL payload re-registers
        idempotently; a different payload replaces an IDLE adapter
        (device/host state dropped first) and refuses while any request
        holds the old one in flight."""
        rows = None
        rank = 0
        if pages is not None:
            rows = np.asarray(pages, self.pool.dtype)
            if rows.size == 0:
                rows = None
            elif rows.ndim != 2 or rows.shape[1] != self.pool.elements:
                raise ValueError(
                    f"adapter {name!r} payload shape {rows.shape} does not "
                    f"match this pool's page layout (rank, "
                    f"{self.pool.elements}) — pack it with "
                    "module_inject.load_lora_adapter against THIS engine")
            else:
                rank = rows.shape[0]
        if rank > self.pool.num_pages:
            raise ValueError(
                f"adapter {name!r} rank {rank} exceeds the pool "
                f"({self.pool.num_pages} pages) — raise lora.pool_pages or "
                "reduce the adapter rank")
        if self.max_rank is not None and rank > self.max_rank:
            raise ValueError(
                f"adapter {name!r} rank {rank} exceeds lora.max_rank "
                f"({self.max_rank}) — the warmed (bucket, rank-bucket) "
                "program grid stops there, so admitting it would compile "
                "mid-steady-state; raise lora.max_rank (and re-warm)")
        with self._meta:
            old = self._adapters.get(name)
        if old is not None:
            same = (old.rank == rank
                    and (rows is None if old.master is None
                         else (old.master is not None
                               and np.array_equal(old.master, rows))))
            if same:
                return                      # idempotent re-register
            if old.refcount > 0:
                raise ValueError(
                    f"adapter {name!r} is bound to {old.refcount} in-flight "
                    "request(s) — a re-register with a DIFFERENT payload "
                    "must wait until they finish (or use a new name)")
            self.unregister(name)
        with self._meta:
            self._adapters[name] = _Adapter(name=name, rank=rank,
                                            master=rows)
        self.stats.set_resident(name, rank == 0)

    def unregister(self, name: str) -> None:
        """Drop an IDLE adapter entirely (device pages freed, pinned
        buffers returned, master forgotten)."""
        ad = self._get(name)
        if ad.refcount > 0:
            raise ValueError(
                f"adapter {name!r} is bound to {ad.refcount} in-flight "
                "request(s) — cannot unregister")
        if ad.state == RESIDENT and ad.page_ids:
            self.pool.free(ad.page_ids)
        for buf in ad.bufs:
            self.swap.put(buf)
        with self._meta:
            del self._adapters[name]
        self.stats.drop(name)

    def drain_swap(self) -> int:
        """Return every EVICTED adapter's pinned buffers to the swap pool;
        returns the number of buffers drained.

        Byte-safe: the host master rows are retained for the adapter's
        whole lifetime, so a drained adapter just drops back to
        REGISTERED and its next fault-in re-uploads from the master
        instead of the pinned snapshot. Settles the pool to its quiescent
        baseline (``swap.outstanding == 0``) for leak accounting —
        benchmarks snapshot their pool baselines after this, otherwise
        whichever adapters HAPPEN to sit evicted at snapshot time read as
        leaked buffers (the serving_bench --lora baseline flake)."""
        with self._meta:
            evicted = [ad for ad in self._adapters.values()
                       if ad.state == EVICTED]
        drained = 0
        for ad in evicted:
            for buf in ad.bufs:
                self.swap.put(buf)
            drained += len(ad.bufs)
            with self._meta:
                ad.bufs = []
                ad.state = REGISTERED
        return drained

    def _get(self, name: str) -> _Adapter:
        try:
            return self._adapters[name]
        except KeyError:
            raise KeyError(
                f"unknown LoRA adapter {name!r} (registered: "
                f"{sorted(self._adapters)}) — register it via "
                "module_inject.load_lora_adapter first") from None

    # -- introspection (admission / router / engine dispatch) ------------- #

    @property
    def names(self) -> List[str]:
        with self._meta:
            return sorted(self._adapters)

    @property
    def rank_bucket(self) -> int:
        """The pow2 rank bucket EVERY LoRA decode program dispatches at:
        ``next_pow2(max registered rank)``, 0 when only rank-0/no adapters
        exist. Engine-stable after registration (NOT per-batch), so adapter
        churn inside the registered set never changes program signatures —
        the zero-steady-state-compile invariant."""
        with self._meta:
            ranks = [a.rank for a in self._adapters.values() if a.rank > 0]
        return next_pow2(max(ranks)) if ranks else 0

    def rank(self, name: str) -> int:
        with self._meta:
            return self._get(name).rank

    def is_resident(self, name: str) -> bool:
        with self._meta:
            ad = self._get(name)
            return ad.rank == 0 or ad.state == RESIDENT

    def refcount(self, name: str) -> int:
        with self._meta:
            return self._get(name).refcount

    def binding(self, uid: int) -> Optional[str]:
        with self._meta:
            return self._bindings.get(int(uid))

    def can_admit(self, name: str, releasing=()) -> bool:
        """Could ``acquire`` succeed right now without shedding anyone?
        True when resident, rank-0, or free + idle-evictable pages cover
        the rank (the admission loop's pool-pressure signal). ``releasing``
        simulates a set of uids whose bindings are about to drop (the
        planner's already-chosen preempt victims): an adapter becomes
        evictable when those releases would take its refcount to zero."""
        with self._meta:
            ad = self._get(name)
            if ad.rank == 0 or ad.state == RESIDENT:
                return True
            rel = {int(u) for u in releasing}
            held = {}
            for u, n in self._bindings.items():
                if u not in rel:
                    held[n] = held.get(n, 0) + 1
            evictable = sum(a.rank for a in self._adapters.values()
                            if a.state == RESIDENT
                            and held.get(a.name, 0) == 0)
        return self.pool.free_pages + evictable >= ad.rank

    # -- request lifecycle ------------------------------------------------ #

    def acquire(self, uid: int, name: str) -> None:
        """Bind request ``uid`` to adapter ``name`` and make it resident
        (faulting in — evicting idle adapters LRU — as needed). Exception-
        safe: a failure mid-fault (pool pressure, injected
        ``serve.lora_fault``) rolls the binding and refcount back and frees
        any pages allocated, so cancel-while-faulting leaves the registry
        at baseline."""
        uid = int(uid)
        with self._meta:
            assert uid not in self._bindings, \
                f"uid {uid} already bound to {self._bindings[uid]!r}"
            ad = self._get(name)
            hit = ad.rank == 0 or ad.state == RESIDENT
            ad.refcount += 1
            self._bindings[uid] = name
        try:
            self._ensure_resident(ad)     # device work: NOT under _meta
        except BaseException:
            with self._meta:
                ad.refcount -= 1
                del self._bindings[uid]
            raise
        with self._meta:
            self._clock += 1
            ad.last_used = self._clock
        self.stats.record_acquire(name, hit)

    def release(self, uid: int) -> None:
        """Unbind a finished/cancelled/shed request. The adapter STAYS
        resident (LRU-cached) until pool pressure evicts it."""
        uid = int(uid)
        with self._meta:
            name = self._bindings.pop(uid, None)
            if name is None:
                return
            ad = self._adapters[name]
            ad.refcount -= 1
            assert ad.refcount >= 0
        self.stats.record_release(name)

    # -- residency (fault-in / evict) ------------------------------------- #

    def _ensure_resident(self, ad: _Adapter) -> None:
        if ad.rank == 0 or ad.state == RESIDENT:
            return
        t0 = time.perf_counter()
        while self.pool.free_pages < ad.rank:
            victim = self._lru_victim(exclude=ad.name)
            if victim is None:
                raise RuntimeError(
                    f"LoRA pool pressure: adapter {ad.name!r} needs "
                    f"{ad.rank} pages, {self.pool.free_pages} free and "
                    "every resident adapter is bound to in-flight requests "
                    "— admission should defer this request (can_admit)")
            self.evict(victim.name)
        ids = self.pool.alloc(ad.rank)
        try:
            # chaos site: cancel-while-faulting (serving_bench --lora and
            # tests pin that the rollback restores refcounts + free pages)
            _maybe_fail("serve.lora_fault")
            if ad.state == EVICTED:
                rows = np.stack([self.swap.view(buf, (self.pool.elements,),
                                                self.pool.dtype)
                                 for buf in ad.bufs])
            else:
                rows = ad.master
            self.pool.put_pages(rows, ids)
        except BaseException:
            self.pool.free(ids)
            raise
        with self._meta:
            ad.page_ids = ids
            if ad.state == EVICTED:
                for buf in ad.bufs:
                    self.swap.put(buf)
                ad.bufs = []
            ad.state = RESIDENT
        # sync before the stamp: the fault-in span/counters time the swap-in
        # through device completion, not just the scatter dispatch (this
        # runs in the admission round, never inside a decode slice)
        jax.block_until_ready(self.pool.pool)
        t1 = time.perf_counter()
        nbytes = ad.rank * self.pool.page_nbytes
        # one stamp pair feeds the span AND the counters (stats == spans)
        self.stats.record_fault(ad.name, nbytes, t1 - t0)
        if _tracer.enabled:
            _tracer.add("serve/lora/fault", t0, t1, lane="serve/lora",
                        adapter=ad.name, pages=ad.rank, nbytes=nbytes)

    def _lru_victim(self, exclude: str) -> Optional[_Adapter]:
        best = None
        for a in self._adapters.values():
            if (a.name == exclude or a.state != RESIDENT or a.refcount > 0
                    or a.rank == 0):
                continue
            if best is None or a.last_used < best.last_used:
                best = a
        return best

    def evict(self, name: str) -> None:
        """Device -> pinned host buffers, pages freed (refcount must be 0).
        The restore half is ``acquire``'s fault-in; the round trip is
        byte-exact (the ``fetch_pages``/``put_pages`` contract)."""
        ad = self._get(name)
        if ad.state != RESIDENT or ad.rank == 0:
            return
        if ad.refcount > 0:
            raise RuntimeError(
                f"adapter {name!r} is bound to {ad.refcount} in-flight "
                "request(s) — cannot evict (the refcount gate that keeps "
                "decode gathers backed)")
        t0 = time.perf_counter()
        rows = self.pool.fetch_pages(ad.page_ids)
        bufs = []
        for i in range(ad.rank):
            buf = self.swap.get(self.pool.page_nbytes)
            np.copyto(self.swap.view(buf, (self.pool.elements,),
                                     self.pool.dtype), rows[i])
            bufs.append(buf)
        with self._meta:
            self.pool.free(ad.page_ids)
            ad.page_ids = []
            ad.bufs = bufs
            ad.state = EVICTED
        t1 = time.perf_counter()
        nbytes = ad.rank * self.pool.page_nbytes
        # timed work already drained: fetch_pages ends in fetch_to_host and
        # the buffer fills are host copies
        self.stats.record_evict(name, nbytes, t1 - t0)  # jaxlint: disable=JL001
        if _tracer.enabled:
            _tracer.add("serve/lora/swap", t0, t1, lane="serve/lora",
                        adapter=name, pages=ad.rank, nbytes=nbytes)

    # -- decode dispatch --------------------------------------------------- #

    def page_table(self, uids: Sequence[int], bucket: int,
                   rb: int) -> np.ndarray:
        """The per-batch ``adapter_pt [bucket, rb]`` int32 operand: each
        row's bound adapter's page ids (rank-padded with the zero page);
        unbound rows, rank-0 rows, and bucket-pad rows are all-zero-page
        (exact-zero delta — inert, like scratch-page KV rows)."""
        pt = np.full((bucket, rb), self.pool.zero_page, np.int32)
        for i, uid in enumerate(uids):
            name = self._bindings.get(int(uid))
            if name is None:
                continue
            ad = self._adapters[name]
            if ad.rank == 0:
                continue
            assert ad.state == RESIDENT, \
                f"bound adapter {name!r} not resident (refcount gate broken)"
            pt[i, :ad.rank] = ad.page_ids
        return pt

    def close(self) -> None:
        """Drop everything (engine teardown): frees device pages and
        returns pinned buffers; refuses while requests are in flight."""
        for name in list(self._adapters):
            if self._adapters[name].refcount > 0:
                raise RuntimeError(
                    f"adapter {name!r} still bound at close()")
            self.unregister(name)
