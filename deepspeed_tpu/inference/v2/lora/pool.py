"""Paged adapter-weight pool for multi-tenant LoRA serving.

The device half of ``inference/v2/lora/``: ONE dense array
``[num_pages + 2, elements]`` in the model dtype, managed exactly like the
KV pool (docs/SERVING.md "Multi-tenant LoRA"):

- a **page** is one rank slice of a whole adapter (column j of every
  targeted projection's A matrix + row j of its B, all layers —
  ``ragged_model.lora_page_layout``), so every page has the same size and
  a rank-r adapter owns r pages anywhere in the pool;
- index ``num_pages`` is the **zero page**: read-only zeros backing the
  null adapter, rank padding below the dispatch bucket, and gather pad
  slots — rows bound to it contribute exact-zero deltas, which is what
  keeps pad rows inert and adapter-free streams byte-identical;
- index ``num_pages + 1`` is the **junk page**: the write-only scatter
  padding target (the scratch-page discipline of the KV movers — pad
  writes land on the one page no adapter can own);
- host round-trips run through bucketed jitted gather/scatter movers
  (pow2-padded id vectors, one dispatch + one transfer per batch, the
  ``fetch_pages``/``put_pages`` pattern), drained via the policed
  ``fetch_to_host``; first use of each (op, bucket) signature counts as a
  compile through ``compile_hook`` so the engine's zero-steady-state-
  compile gate covers adapter churn, and ``warm()`` pre-compiles the grid.

The decode programs read the pool array directly (``lora_pool[adapter_pt]``
inside the jit) — it is an OPERAND of the step programs, never donated
there; only the scatter mover donates it (rebinding ``self.pool``, the
``put_pages`` discipline).
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.engine_v2 import fetch_to_host
from deepspeed_tpu.inference.v2.ragged_model import lora_page_layout
from deepspeed_tpu.utils.caching import next_pow2


class LoraPagePool:
    """Fixed-size adapter-weight pages on device + a free-list allocator.

    Allocation/refcount policy lives in :class:`~deepspeed_tpu.inference.v2.
    lora.registry.LoraAdapterRegistry`; this class owns only the device
    array, the free list, and the bucketed host movers."""

    def __init__(self, spec, targets: Tuple[str, ...], num_pages: int,
                 compile_hook: Optional[Callable[[], None]] = None):
        self.spec = spec
        self.targets = tuple(targets)
        self.elements, self.in_max, self.out_max = \
            lora_page_layout(spec, self.targets)
        self.num_pages = int(num_pages)
        self.zero_page = self.num_pages
        self.junk_page = self.num_pages + 1
        self.dtype = jnp.dtype(spec.dtype)
        self.pool = jnp.zeros((self.num_pages + 2, self.elements),
                              self.dtype)
        self._free: List[int] = list(range(self.num_pages))
        self._progs = None
        self._buckets: set = set()
        self._compile_hook = compile_hook

    # -- allocator ------------------------------------------------------- #

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def page_nbytes(self) -> int:
        return self.elements * self.dtype.itemsize

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"LoRA pool exhausted: need {n} pages, {len(self._free)} "
                f"free of {self.num_pages} — evict an idle adapter first "
                "(registry handles this; a direct caller raced it)")
        return [self._free.pop() for _ in range(n)]

    def free(self, ids: Sequence[int]) -> None:
        for b in ids:
            b = int(b)
            assert 0 <= b < self.num_pages, f"freeing non-pool page {b}"
            assert b not in self._free, f"double free of LoRA page {b}"
            self._free.append(b)

    # -- bucketed host movers (the KV page-fabric pattern) --------------- #

    def _programs(self):
        if self._progs is None:

            @jax.jit
            def _gather(pool, idx):
                return pool[idx]

            @functools.partial(jax.jit, donate_argnums=(0,))
            def _scatter(pool, rows, idx):
                return pool.at[idx].set(rows)

            self._progs = (_gather, _scatter)
        return self._progs

    def _bucket(self, kind: str, n: int) -> int:
        """Pad count for a mover batch; first use of each (op, bucket)
        signature counts as a compile (engine.compiles via the hook)."""
        b = next_pow2(n)
        key = (kind, b)
        if key not in self._buckets:
            self._buckets.add(key)
            if self._compile_hook is not None:
                self._compile_hook()
        return b

    def fetch_pages(self, ids: Sequence[int]) -> np.ndarray:
        """Adapter pages to host, one bucketed gather: ``[n, elements]`` in
        the pool dtype — the evict half of the swap round trip. Byte-exact
        with :meth:`put_pages` (same dtype both ways; pinned by
        tests/unit/test_lora_serving.py). Pad slots read the zero page."""
        ids = [int(b) for b in ids]
        gather, _ = self._programs()
        bucket = self._bucket("gather", len(ids))
        idx = np.full((bucket,), self.zero_page, np.int32)
        idx[:len(ids)] = ids
        return fetch_to_host(gather(self.pool, jnp.asarray(idx)))[:len(ids)]

    def put_pages(self, rows: np.ndarray, ids: Sequence[int]) -> None:
        """Scatter host rows ``[n, elements]`` into pool pages ``ids`` (one
        bucketed dispatch) — the restore/fault-in half. Pad slots write
        zeros into the write-only junk page."""
        ids = [int(b) for b in ids]
        if not ids:
            return
        _, scatter = self._programs()
        bucket = self._bucket("scatter", len(ids))
        idx = np.full((bucket,), self.junk_page, np.int32)
        idx[:len(ids)] = ids
        rows = np.asarray(rows, self.dtype)
        if rows.shape != (len(ids), self.elements):
            raise ValueError(
                f"LoRA page payload shape {rows.shape} does not match "
                f"({len(ids)}, {self.elements}) — pages are fixed-size "
                "rank slices (lora_page_layout)")
        if bucket != len(ids):
            rows = np.concatenate(
                [rows, np.zeros((bucket - len(ids), self.elements),
                                rows.dtype)])
        # direct rebind (the put_pages discipline): the donated pool's
        # reference is replaced before the next decode step reads it
        self.pool = scatter(self.pool, jnp.asarray(rows), jnp.asarray(idx))

    def warm(self, max_rank: int) -> None:
        """Pre-compile both movers over the pow2 bucket grid up to
        ``next_pow2(max_rank)`` (the largest batch one adapter's fault/evict
        can move), round-tripping zero-page content into the junk page —
        a mid-steady-state adapter fault must never observe a compile."""
        top = next_pow2(max(1, int(max_rank)))
        for b in [1 << i for i in range(top.bit_length())]:
            rows = self.fetch_pages([self.zero_page] * b)
            self.put_pages(rows, [self.junk_page] * b)
