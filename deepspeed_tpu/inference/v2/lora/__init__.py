"""Multi-tenant LoRA serving: paged adapter weights + grouped decode.

- :class:`LoraPagePool` — the device page pool + bucketed host movers;
- :class:`LoraAdapterRegistry` — adapter lifecycle (register / acquire /
  release / LRU evict / byte-exact restore) and the per-batch page table.

The matmul half lives in ``ragged_model`` (``lora_target_dims``,
``lora_page_layout``, ``lora_layer_operands`` and the ``lora_targets``
builder knob); checkpoint loading/validation in ``module_inject.lora``.
"""

from deepspeed_tpu.inference.v2.lora.pool import LoraPagePool
from deepspeed_tpu.inference.v2.lora.registry import (
    EVICTED,
    REGISTERED,
    RESIDENT,
    LoraAdapterRegistry,
)

__all__ = [
    "LoraPagePool",
    "LoraAdapterRegistry",
    "REGISTERED",
    "RESIDENT",
    "EVICTED",
]
