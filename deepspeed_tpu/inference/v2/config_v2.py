"""Inference v2 engine configuration.

Parity: ``RaggedInferenceEngineConfig`` (reference ``inference/v2/config_v2.py``)
with its ``DSStateManagerConfig`` (``ragged/manager_configs.py``): tracked-sequence
capacity, ragged-batch token budget, and KV memory sizing — plus the TPU additions
(mesh/tp size, page block size).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax.numpy as jnp


@dataclass
class DSStateManagerConfig:
    """Parity: ``DSStateManagerConfig`` (manager_configs.py)."""
    max_tracked_sequences: int = 64          # sequences with live KV state
    max_ragged_sequence_count: int = 32      # decode rows per pass
    max_ragged_batch_size: int = 768         # token budget per pass (chunks + decode)
    max_context: int = 8192                  # per-sequence KV capacity
    prefill_chunk_size: int = 128            # tokens per prompt-chunk slot

    @property
    def chunk_budget(self) -> int:
        return self.max_ragged_batch_size - self.max_ragged_sequence_count

    @property
    def chunk_slot_size(self) -> int:
        """Static tokens per slot. Stays exactly ``prefill_chunk_size`` (a
        user-aligned size, 128 by default): dividing the budget evenly
        instead gives sizes like 147 whose q-block collapses to 1-row MXU
        tiles in the batched prefill kernel."""
        return min(self.prefill_chunk_size, max(1, self.chunk_budget))

    @property
    def num_chunk_slots(self) -> int:
        """Prompt-chunk slots per pass. Multi-slot is the prefill throughput
        lever: one chunk per pass serialises N prompts on N pass dispatches
        (host descriptor build + tunnel RTT each). The count rounds the
        budget to the NEAREST slot multiple, so realized chunk capacity is
        within half a slot of ``chunk_budget`` — flooring stranded up to a
        slot's worth (96 of 736 tokens at the defaults)."""
        cs = self.chunk_slot_size
        return max(1, (self.chunk_budget + cs // 2) // cs)


@dataclass
class KVCacheSizingConfig:
    block_size: int = 128
    num_blocks: Optional[int] = None         # explicit pool size
    memory_fraction: float = 0.8             # else: fraction of free HBM


@dataclass
class QuantizationConfig:
    """Weight-only quantization for the serving path (parity: the reference's
    v2 quantization config, ``inference/v2/config_v2.py`` QuantizationConfig,
    backing the CUTLASS fp16 x int8 mixed GEMM). ``weight_bits=8`` stores the
    streamed weight matrices int8 in HBM with per-output-column scales and
    dequantizes inside the dot (see ``ragged_model._mm``). None = off."""
    weight_bits: Optional[int] = None

    def __post_init__(self):
        # 4 = PACKED int4 (two per byte along K, 4x under bf16 at rest —
        # reference csrc/quantization/quantize_intX.cu); 8 = int8
        if self.weight_bits not in (None, 4, 8):
            raise ValueError("quantization.weight_bits must be None, 4 or 8, "
                             f"got {self.weight_bits!r}")


@dataclass
class KVQuantConfig:
    """int8 KV pages (parity role: the blocked-flash KV stream +
    ZeRO-Inference's KV quantization strategy, reference README.md:23).
    Pages store int8 values with per-token-head f32 scales (1.6% overhead at
    head_dim 128); the paged kernels dequantize in-flight, halving the
    page-read stream that bounds large-batch GQA decode. A first-class pool
    layout for the WHOLE v2 serving stack: composes with the prefix cache
    (COW copies the scale tile with the page), spec decode (the verify step
    quantizes-on-write), preempt-offload and the cross-engine page fabric
    (packed value+scale-tile payloads, byte-exact round trips) — see
    docs/SERVING.md "Quantized KV" for the layout, the write semantics and
    the byte-vs-rtol gate taxonomy. Requires tp == 1 (the one surviving
    refusal, raised at engine build), head_dim % 128 == 0 and
    num_kv_heads * block_size % 128 == 0."""
    enabled: bool = False
    bits: int = 8

    def __post_init__(self):
        if self.bits != 8:
            raise ValueError(f"kv_quant.bits must be 8, got {self.bits!r}")


@dataclass
class PrefixCacheConfig:
    """Automatic prefix caching (parity role: SGLang RadixAttention / vLLM
    automatic-prefix-caching; see ``inference/v2/prefix_cache.py``). When
    enabled, completed sequences' KV pages are retained in a radix tree keyed
    on token blocks and new prompts reuse every cached whole-block prefix —
    zero prefill is scheduled for the matched span. Off by default: sharing is
    a semantic no-op (outputs stay logit-exact) but the tree holds pool blocks
    that eviction must reclaim under pressure.

    ``max_cached_blocks`` caps how many pool blocks the tree may retain
    (None = bounded only by the pool itself; idle cached blocks are evicted
    LRU whenever an allocation would otherwise fail). ``eviction`` names the
    policy; only ``"lru"`` is implemented."""
    enabled: bool = False
    max_cached_blocks: Optional[int] = None
    eviction: str = "lru"

    def __post_init__(self):
        if self.eviction != "lru":
            raise ValueError(
                f"prefix_cache.eviction must be 'lru', got {self.eviction!r}")
        if self.max_cached_blocks is not None and self.max_cached_blocks < 1:
            raise ValueError("prefix_cache.max_cached_blocks must be >= 1 "
                             f"(or None), got {self.max_cached_blocks}")


@dataclass
class CompileConfig:
    """Persistent compile cache + AOT warmup for the serving hot path.

    Steady-state decode cost on TPU is bounded below by recompiles: every new
    (bucketed) batch shape pays a multi-second XLA compile, and through a
    remote-compile tunnel a cold engine pays it for every program on its
    first wave of traffic. This config wires ``utils/compile_cache.py``
    (the ``jax_compilation_cache_dir`` integration) into engine construction
    and optionally AOT-warms the whole decode bucket grid at startup so
    serving traffic never observes a compile.

    ``cache_dir``: root directory for the persistent XLA compile cache.
    ``None`` (default) defers to the ``DSTPU_COMPILE_CACHE`` environment
    variable; unset/empty means the engine does not touch the process-level
    cache config (bench/test entrypoints may still have configured one).
    CPU backends get a host-fingerprint subdir (see utils/compile_cache.py —
    AOT CPU executables SIGILL on hosts missing the build host's ISA).

    ``warmup``: pre-compile the serving program set at engine construction —
    the ragged paged pass, the prefill fast path, and the fused decode-step
    program for every bucket in ``warmup_buckets`` (plus fused multistep
    programs for each burst length in ``warmup_decode_steps``). Warmup runs
    each program once over the engine's scratch KV page, so with a persistent
    cache a *second* engine start skips compilation entirely.

    ``warmup_buckets``: decode-row buckets to pre-compile. ``None`` = the
    full power-of-two grid ``1, 2, 4, ..., next_pow2(max_ragged_sequence_
    count)`` — the whole reachable bucket set, since admission/retirement
    rounds every live count to this grid.
    """
    cache_dir: Optional[str] = None
    min_compile_time_secs: float = 2.0
    warmup: bool = False
    warmup_buckets: Optional[Any] = None     # list of ints
    warmup_decode_steps: Any = ()            # list of fused-burst lengths

    def resolve_cache_dir(self) -> str:
        """Effective cache root: explicit config wins, else the
        ``DSTPU_COMPILE_CACHE`` env knob ("" = leave process config alone)."""
        if self.cache_dir is not None:
            return self.cache_dir
        import os
        return os.environ.get("DSTPU_COMPILE_CACHE", "")

    def __post_init__(self):
        if self.warmup_buckets is not None:
            if any(not isinstance(b, int) or b < 1
                   for b in self.warmup_buckets):
                raise ValueError("compile.warmup_buckets must be ints >= 1, "
                                 f"got {self.warmup_buckets!r}")
            # normalize to the pow2 grid the live path actually uses — the
            # same rounding engine.warmup() applies to explicit buckets, so
            # both entry points accept the same inputs
            from deepspeed_tpu.utils.caching import next_pow2
            self.warmup_buckets = sorted({next_pow2(b)
                                          for b in self.warmup_buckets})
        if any(not isinstance(n, int) or n < 1
               for n in self.warmup_decode_steps):
            raise ValueError("compile.warmup_decode_steps must be ints >= 1, "
                             f"got {self.warmup_decode_steps!r}")


@dataclass
class SpecDecodeConfig:
    """Speculative decoding for the steady-state decode path
    (``inference/v2/spec/``; docs/SERVING.md "Speculative decoding").

    When enabled, ``engine.decode_pipeline`` returns a
    ``SpecDecodePipeline``: each pipeline step proposes up to ``k`` draft
    tokens per sequence from its own token history (prompt-lookup / n-gram
    matching — no second model), verifies them in ONE ragged forward
    (``ragged_model.build_verify_step``), and emits the accepted prefix plus
    one greedy bonus token. Greedy speculation is exactness-preserving:
    token streams are byte-identical to the spec-off pipeline, gated by
    ``serving_bench.py --spec``.

    ``k``: max draft tokens verified per step — the top rung of the
    (bucket, k) warmup grid. Prefer ``k + 1`` a POWER OF TWO (3, 7, 15):
    the chunk kernel's q-block must divide k+1, and an odd k+1 collapses
    it to 1-row blocks with (k+1)x the grid steps (measured ~2x slower on
    the bench box — a misaligned k warns below). ``min_match`` /
    ``max_ngram``: the proposer matches the longest history suffix of
    length in [min_match, max_ngram] and proposes its continuation; no
    match proposes nothing and the step degenerates to plain decode for
    that row. ``adaptive``: per-sequence MIMD k backoff — any reject drops
    a row's draft budget to accepted + 1 (down to a probe of 1, so
    re-entering a repetitive span is detected), full accepts double it
    back toward k; a traced per-row operand, never a recompile.

    Greedy-only: sampled pipelines bypass speculation with a one-time
    warning. Not wired for sliding-window models (the page ring aliases the
    K+1-ahead write span); int8 KV pages compose — the verify step
    quantizes-on-write like the decode step (docs/SERVING.md
    "Quantized KV")."""
    enabled: bool = False
    k: int = 3
    min_match: int = 2
    max_ngram: int = 4
    adaptive: bool = True

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec_decode.k must be >= 1, got {self.k}")
        if self.enabled and (self.k + 1) & self.k != 0:
            import warnings
            warnings.warn(
                f"spec_decode.k={self.k}: k + 1 is not a power of two, so "
                "the verify kernel's q-block collapses to 1-row blocks "
                "(measured ~2x slower) — prefer k in 3, 7, 15, ...",
                stacklevel=3)
        if self.min_match < 1:
            raise ValueError("spec_decode.min_match must be >= 1, got "
                             f"{self.min_match}")
        if self.max_ngram < self.min_match:
            raise ValueError(
                f"spec_decode.max_ngram ({self.max_ngram}) must be >= "
                f"min_match ({self.min_match})")


@dataclass
class LoraConfig:
    """Multi-tenant LoRA serving (``inference/v2/lora/``; docs/SERVING.md
    "Multi-tenant LoRA"). One base model plus per-tenant low-rank adapters —
    the S-LoRA/Punica pattern — served from a paged adapter-weight pool
    managed exactly like the KV pool: fixed-size weight pages (one page per
    rank slice), refcounted per in-flight request, LRU-evicted to pinned
    host buffers under pool pressure and restored byte-exactly.

    ``pool_pages``: device pages in the adapter pool. One adapter of rank r
    occupies r pages, so the pool holds ``pool_pages / mean_rank`` adapters
    resident; registering more than fit is the POINT — cold adapters park on
    host and fault back in on demand. Must hold at least one ``max_rank``
    adapter.

    ``max_rank``: the largest adapter rank this engine accepts. Ranks are
    bucketed to powers of two for dispatch: the decode/verify program grid
    is keyed by (bucket, rank-bucket) and ``warmup`` pre-compiles every
    rung, so adapter churn never compiles. The grouped-matmul rank operand
    runs at ``next_pow2(max registered rank)``; smaller adapters pad their
    page tables with the pool's zero page (an exact zero contribution).

    ``targets``: which projections carry deltas — a subset of
    ``("q", "k", "v", "o")``. Deltas apply inside the DECODE and VERIFY
    programs (the serving hot path this subsystem exists for); prefill
    passes run the base model (docs/SERVING.md "Multi-tenant LoRA" states
    the resulting decode-scope semantics).

    ``swap_buffers`` caps the pinned host bounce-buffer pool
    (``runtime/swap_tensor/buffer_pool.py``) evicted adapters park in."""
    enabled: bool = False
    pool_pages: int = 64
    max_rank: int = 16
    targets: Any = ("q", "v")
    swap_buffers: int = 16

    def __post_init__(self):
        self.targets = tuple(self.targets)
        bad = [t for t in self.targets if t not in ("q", "k", "v", "o")]
        if bad:
            raise ValueError(f"lora.targets must be a subset of "
                             f"('q', 'k', 'v', 'o'), got {self.targets!r}")
        if not self.targets:
            raise ValueError("lora.targets must name at least one projection")
        if self.max_rank < 1:
            raise ValueError(f"lora.max_rank must be >= 1, got {self.max_rank}")
        if self.pool_pages < self.max_rank:
            raise ValueError(
                f"lora.pool_pages ({self.pool_pages}) must hold at least one "
                f"max_rank ({self.max_rank}) adapter")
        if self.swap_buffers < 1:
            raise ValueError("lora.swap_buffers must be >= 1, got "
                             f"{self.swap_buffers}")


@dataclass
class PriorityClassConfig:
    """One tenant priority class for the serving frontend
    (``inference/v2/serving/``): a strict-priority level plus the latency
    SLOs admission plans against. ``priority`` is higher-wins; ``ttft_slo_ms``
    bounds time-to-first-token (arrival -> first streamed token) and
    ``tbt_slo_ms`` bounds time-between-tokens — the two numbers
    goodput-under-SLO is gated on (docs/SERVING.md "Frontend")."""
    name: str
    priority: int
    ttft_slo_ms: float = 2000.0
    tbt_slo_ms: float = 250.0

    def __post_init__(self):
        if not self.name:
            raise ValueError("priority class needs a non-empty name")
        if self.ttft_slo_ms <= 0 or self.tbt_slo_ms <= 0:
            raise ValueError(f"class {self.name!r}: SLO targets must be > 0")


def _default_classes():
    return [PriorityClassConfig("interactive", 2, 500.0, 100.0),
            PriorityClassConfig("standard", 1, 2000.0, 250.0),
            PriorityClassConfig("batch", 0, 30000.0, 2000.0)]


@dataclass
class ServingConfig:
    """The SLO-aware serving frontend (``inference/v2/serving/frontend.py``).

    ``classes``: the tenant priority classes (dicts or
    :class:`PriorityClassConfig`), strict priority between classes, FIFO
    within one.

    ``decode_slice``: pipeline steps per ``DecodePipeline.run`` burst — the
    iteration-level continuous-batching grain. Admission, retirement,
    preemption and restore all happen at slice boundaries; a smaller slice
    lowers admission latency, a larger one amortises per-run host work.

    ``preemption`` picks what happens to low-priority victims under KV-pool
    pressure:

    - ``"offload"`` (default): the victim's *private* KV pages (allocator
      refcount 1 — prefix-cache-shared pages are never touched) round-trip
      through pinned host buffers (``runtime/swap_tensor/buffer_pool.py``)
      and are restored byte-identically on readmit; falls back to recompute
      per victim when ``max_offload_bytes`` is exhausted.
    - ``"recompute"``: the victim is flushed and re-prefilled from its
      prompt + generated-so-far tokens on readmit (vLLM's drop-and-recompute
      baseline).
    - ``"none"``: reject-only — no preemption; admission turns conservative
      (a request is admitted only when its full prompt + ``max_new_tokens``
      KV lifetime is fundable up front) and excess load is held, then shed.

    ``shed_factor``: a queued request is shed once
    ``elapsed_queue_delay + predicted_prefill + one_slice >
    ttft_slo_ms * shed_factor`` — it can no longer meet its SLO, so
    admitting it would burn prefill compute on a guaranteed miss.

    ``max_offload_bytes``: host-buffer capacity for offloaded pages (None =
    unbounded); ``offload_buffers`` caps the pinned-buffer pool's free list.
    ``max_queue`` bounds the pending queue (beyond = immediate shed);
    ``idle_wait_s`` is the engine thread's block interval when idle.

    ``attribution``: record the per-request phase ledger
    (``RequestHandle.timeline()`` — queued/admission/prefill/handoff_wait/
    decode/preempted/restore/migration stints from the same perf stamps the
    trace spans carry) and bucket SLO misses by dominant phase
    (``serve/slo/*``; docs/OBSERVABILITY.md "SLO-miss attribution"). A few
    list appends per phase TRANSITION — nothing per token; ``False``
    disables both (the A/B lever ``serving_bench.py --trace-overhead``
    gates).

    ``spec``: serve greedy requests through the engine's speculative
    pipeline when ``spec_decode.enabled`` (default). ``False`` pins this
    frontend to the plain ``DecodePipeline`` — a per-frontend A/B lever
    (draft-miss overhead vs k-token amortization), and the discipline the
    byte-equality bench gates use: spec-on and spec-off greedy streams
    agree only up to cross-kernel float noise (~1e-4/token argmax flips on
    a random-init model — docs/SERVING.md "Quantized KV" gate taxonomy),
    so a replay gated bit-exactly against a plain reference serves plain.

    ``tenant_classes``: explicit tenant -> priority-class mapping (tenant
    here = LoRA adapter name, the multi-tenant identity of docs/SERVING.md
    "Multi-tenant LoRA"). Per-request ``priority=`` stays the override, but
    a submit that names an adapter WITHOUT naming a class defaults to the
    tenant's mapped class instead of ``"standard"`` — mixed benches stop
    misclassifying traffic whose class lives in workload config rather
    than on each request. Every value must name a configured class.
    """
    classes: Any = field(default_factory=_default_classes)
    tenant_classes: Any = field(default_factory=dict)
    decode_slice: int = 8
    spec: bool = True
    preemption: str = "offload"
    max_offload_bytes: Optional[int] = None
    offload_buffers: int = 16
    shed_factor: float = 1.0
    max_queue: int = 1024
    idle_wait_s: float = 0.02
    attribution: bool = True

    def __post_init__(self):
        self.classes = [PriorityClassConfig(**c) if isinstance(c, dict) else c
                        for c in self.classes]
        names = [c.name for c in self.classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate priority class names: {names}")
        if not self.classes:
            raise ValueError("serving.classes must name at least one class")
        if self.preemption not in ("offload", "recompute", "none"):
            raise ValueError("serving.preemption must be 'offload', "
                             f"'recompute' or 'none', got {self.preemption!r}")
        if self.decode_slice < 1:
            raise ValueError("serving.decode_slice must be >= 1")
        self.tenant_classes = dict(self.tenant_classes)
        for tenant, cls_name in self.tenant_classes.items():
            if cls_name not in names:
                raise ValueError(
                    f"serving.tenant_classes[{tenant!r}] = {cls_name!r} names "
                    f"no configured priority class (configured: {names})")

    def get_class(self, name: str) -> PriorityClassConfig:
        for c in self.classes:
            if c.name == name:
                return c
        raise KeyError(f"unknown priority class {name!r}; configured: "
                       f"{[c.name for c in self.classes]}")

    def class_for(self, priority: Optional[str],
                  tenant: Optional[str] = None) -> PriorityClassConfig:
        """Resolve a request's class: explicit ``priority`` wins, else the
        tenant's ``tenant_classes`` mapping, else ``"standard"``."""
        if priority is not None:
            return self.get_class(priority)
        if tenant is not None and tenant in self.tenant_classes:
            return self.get_class(self.tenant_classes[tenant])
        return self.get_class("standard")


@dataclass
class HealthConfig:
    """Replica failure detection + self-healing for the multi-replica router
    (``inference/v2/serving/health.py``; docs/SERVING.md "Failure
    semantics"). Off by default: a router without health monitoring keeps
    the PR 10 behavior — a dead replica surfaces NAMED at
    ``drain()``/``close()`` instead of being failed over.

    When ``enabled``, a ``dstpu-health`` thread polls every ``interval_s``:
    engine-thread/prefill-worker LIVENESS (a died loop is ``down``
    immediately) plus a PROGRESS heartbeat — the decode-step counter the
    pipeline stats already track (and prefill tokens completed) — so a
    *wedged* replica is detected, not just a dead one. A replica with work
    in flight whose counters stop moving turns ``suspect`` after
    ``suspect_after_s`` and ``down`` after ``down_after_s``; detection
    fences the replica (its loop emits nothing further), migrates every
    in-flight request to a survivor, and — with ``auto_rejoin`` — rebuilds
    a frontend on the engine once its old thread has exited, re-warming the
    pow2 program grids off the hot path (``rejoin_warmup``) before the
    replica re-enters routing.

    ``fence_join_s`` bounds how long failover waits for the failed engine
    thread to exit before migrating anyway (streams stay exact either way:
    migration seals each handle under its emit lock, and a fenced loop
    drops every later emission)."""
    enabled: bool = False
    interval_s: float = 0.05
    suspect_after_s: float = 1.0
    down_after_s: float = 3.0
    fence_join_s: float = 1.0
    auto_rejoin: bool = True
    rejoin_warmup: bool = True

    def __post_init__(self):
        for f in ("interval_s", "suspect_after_s", "down_after_s",
                  "fence_join_s"):
            if getattr(self, f) <= 0:
                raise ValueError(f"health.{f} must be > 0, got "
                                 f"{getattr(self, f)}")
        if self.down_after_s < self.suspect_after_s:
            raise ValueError(
                f"health.down_after_s ({self.down_after_s}) must be >= "
                f"suspect_after_s ({self.suspect_after_s})")


@dataclass
class RouterConfig:
    """The multi-replica serving router (``inference/v2/serving/router.py``;
    docs/SERVING.md "Multi-replica & disaggregation"). Cluster-level — it
    configures a ``ServingRouter`` over N engines, not any single engine.

    ``policy`` picks request placement:

    - ``"cache_aware"`` (default): route to the replica whose radix prefix
      cache holds the longest cached match for the prompt (the
      SGLang-RadixAttention trick at cluster scope, read from a shared
      chain-hash index fed by per-replica insert/evict deltas), scored
      against load: ``score = cached_tokens - balance * outstanding``.
    - ``"round_robin"``: placement ignores caches — the bench baseline.

    ``balance`` is the stickiness/balance tradeoff knob: how many cached
    prompt tokens one outstanding request on a replica outweighs. ``0`` is
    pure stickiness (hotspot risk); large values degrade to least-loaded.

    ``topology``:

    - ``"colocated"`` (default): every replica runs prefill AND decode.
    - ``"disaggregated"``: dedicated prefill replicas run SplitFuse passes
      and hand finished KV to decode replicas over the page fabric
      (``engine.export_kv``/``import_kv`` — the same bucketed page gather
      preempt-offload rides), eliminating prefill interference on decode
      TBT.

    ``federation``: aggregate per-replica admission state (per-class
    queue-delay EMAs + SLO cost models) into placement — a replica whose
    predicted TTFT already busts the class SLO is skipped while a cold one
    absorbs, and the router sheds up front when EVERY candidate is hot
    (``shed_factor`` scales the SLO bound exactly like
    ``ServingConfig.shed_factor``).

    ``health``: replica failure detection + self-healing
    (:class:`HealthConfig`; docs/SERVING.md "Failure semantics").

    ``handoff_retries`` / ``handoff_timeout_s`` / ``handoff_backoff_s``:
    bounded-retry budget for the disaggregated prefill->decode handoff
    (``utils/resilience.retry_call`` semantics). Each attempt is
    deadline-wrapped (``IOTimeout`` past ``handoff_timeout_s`` — a wedged
    decode replica must not stall the prefill worker unboundedly) and
    re-planned against a DIFFERENT decode replica; a request that exhausts
    the budget is shed with the error NAMED on its handle
    (``RequestHandle.error``), never swallowed."""
    policy: str = "cache_aware"
    balance: float = 32.0
    topology: str = "colocated"
    federation: bool = True
    shed_factor: float = 1.0
    health: Any = field(default_factory=HealthConfig)
    handoff_retries: int = 3
    handoff_timeout_s: Optional[float] = 30.0
    handoff_backoff_s: float = 0.05

    def __post_init__(self):
        if isinstance(self.health, dict):
            self.health = HealthConfig(**self.health)
        if self.handoff_retries < 1:
            raise ValueError("router.handoff_retries must be >= 1, got "
                             f"{self.handoff_retries}")
        if self.handoff_timeout_s is not None and self.handoff_timeout_s <= 0:
            raise ValueError("router.handoff_timeout_s must be > 0 (or "
                             f"None), got {self.handoff_timeout_s}")
        if self.handoff_backoff_s < 0:
            raise ValueError("router.handoff_backoff_s must be >= 0, got "
                             f"{self.handoff_backoff_s}")
        if self.policy not in ("cache_aware", "round_robin"):
            raise ValueError("router.policy must be 'cache_aware' or "
                             f"'round_robin', got {self.policy!r}")
        if self.topology not in ("colocated", "disaggregated"):
            raise ValueError("router.topology must be 'colocated' or "
                             f"'disaggregated', got {self.topology!r}")
        if self.balance < 0:
            raise ValueError(f"router.balance must be >= 0, got {self.balance}")
        if self.shed_factor <= 0:
            raise ValueError("router.shed_factor must be > 0, got "
                             f"{self.shed_factor}")


@dataclass
class AttentionConfig:
    """Flash-decoding split-K knobs (docs/SERVING.md "Attention kernels").

    ``decode_splits``: top rung of the pow2 split ladder. 1 (default) keeps
    the chunk-serial kernels exactly — split-K never dispatches. S > 1 makes
    every paged attention caller (ragged decode pass, fused decode
    step/multistep, sidebuf, spec verify) route through the split-K
    dispatchers (``ops/pallas/paged_splitk.py``): each sequence's page range
    is cut into up to S grid-parallel splits emitting ``(acc, lse)``
    partials, merged by one logsumexp-weighted pass. The engine warms ONE
    program per ladder rung ``[1, 2, 4, ..., decode_splits]`` so the
    admission-driven rung choice never compiles on the hot path.

    ``min_ctx_per_split``: rung selection — the engine picks
    ``min(decode_splits, pow2_floor(max_live_ctx / min_ctx_per_split))``
    each step, so short-context batches stay on the split=1 (chunk-serial)
    program where the merge pass is pure overhead, and long tails climb the
    ladder as context grows."""
    decode_splits: int = 1
    min_ctx_per_split: int = 512

    def __post_init__(self):
        if self.decode_splits < 1 or (
                self.decode_splits & (self.decode_splits - 1)) != 0:
            raise ValueError(
                "attention.decode_splits must be a power of two >= 1 (the "
                f"warmed pow2 split ladder), got {self.decode_splits}")
        if self.min_ctx_per_split < 1:
            raise ValueError("attention.min_ctx_per_split must be >= 1, "
                             f"got {self.min_ctx_per_split}")


@dataclass
class RaggedInferenceEngineConfig:
    state_manager: DSStateManagerConfig = field(default_factory=DSStateManagerConfig)
    kv_cache: KVCacheSizingConfig = field(default_factory=KVCacheSizingConfig)
    quantization: QuantizationConfig = field(default_factory=QuantizationConfig)
    kv_quant: KVQuantConfig = field(default_factory=KVQuantConfig)
    prefix_cache: PrefixCacheConfig = field(default_factory=PrefixCacheConfig)
    compile: CompileConfig = field(default_factory=CompileConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    spec_decode: SpecDecodeConfig = field(default_factory=SpecDecodeConfig)
    lora: LoraConfig = field(default_factory=LoraConfig)
    attention: AttentionConfig = field(default_factory=AttentionConfig)
    tensor_parallel: int = 1
    dtype: Any = jnp.bfloat16
    seed: int = 0

    @classmethod
    def load(cls, config=None, **overrides) -> "RaggedInferenceEngineConfig":
        if isinstance(config, cls):
            if overrides:
                raise ValueError("pass overrides via a dict config, not on top of "
                                 "an already-built RaggedInferenceEngineConfig")
            cfg = config
        else:
            d = dict(config or {})
            d.update(overrides)
            sm = DSStateManagerConfig(**d.pop("state_manager", {})) \
                if not isinstance(d.get("state_manager"), DSStateManagerConfig) \
                else d.pop("state_manager")
            kv = d.pop("kv_cache", {})
            kv = KVCacheSizingConfig(**kv) if isinstance(kv, dict) else kv
            qz = d.pop("quantization", {})
            qz = QuantizationConfig(**qz) if isinstance(qz, dict) else qz
            kq = d.pop("kv_quant", {})
            kq = KVQuantConfig(**kq) if isinstance(kq, dict) else kq
            pc = d.pop("prefix_cache", {})
            pc = PrefixCacheConfig(**pc) if isinstance(pc, dict) else pc
            co = d.pop("compile", {})
            co = CompileConfig(**co) if isinstance(co, dict) else co
            sv = d.pop("serving", {})
            sv = ServingConfig(**sv) if isinstance(sv, dict) else sv
            sd = d.pop("spec_decode", {})
            sd = SpecDecodeConfig(**sd) if isinstance(sd, dict) else sd
            lr = d.pop("lora", {})
            lr = LoraConfig(**lr) if isinstance(lr, dict) else lr
            at = d.pop("attention", {})
            at = AttentionConfig(**at) if isinstance(at, dict) else at
            cfg = cls(state_manager=sm, kv_cache=kv, quantization=qz,
                      kv_quant=kq, prefix_cache=pc, compile=co, serving=sv,
                      spec_decode=sd, lora=lr, attention=at, **d)
        if cfg.state_manager.chunk_budget <= 0:
            raise ValueError("max_ragged_batch_size must exceed max_ragged_sequence_count")
        return cfg
