"""Dynamic SplitFuse pass scheduler.

Parity: the FastGen scheduling policy (reference ``blogs/deepspeed-fastgen`` §
"Dynamic SplitFuse", and the ``can_schedule``/``query`` accounting in
``inference/v2/engine_v2.py:153-227``): long prompts are decomposed into chunks
processed across passes; short work is composed so every pass runs near the token
budget. Each pass here = all ready decode tokens (one per active sequence, up to
``max_ragged_sequence_count``) + up to ``num_chunk_slots`` prompt chunks of
``chunk_slot_size`` tokens each — the chunks' matmuls amortise the decode tokens'
bandwidth (the SplitFuse win), and multiple slots per pass keep prefill from
serialising on per-pass dispatch costs; attention splits per section (batched
chunked flash for the slots, paged flash-decode for the rest) in
``ragged_model.py``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, TYPE_CHECKING, Tuple

import numpy as np

from deepspeed_tpu.inference.v2.config_v2 import DSStateManagerConfig
from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache
from deepspeed_tpu.inference.v2.ragged.ragged_batch import RaggedBatch
from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import DSSequenceDescriptor

if TYPE_CHECKING:  # avoid an import cycle at runtime
    from deepspeed_tpu.inference.v2.prefix_cache import RadixPrefixCache


class DynamicSplitFuseScheduler:

    def __init__(self, config: DSStateManagerConfig, cache: BlockedKVCache,
                 allocator: BlockedAllocator,
                 prefix_cache: "Optional[RadixPrefixCache]" = None):
        self.config = config
        self.cache = cache
        self.allocator = allocator
        # radix-tree KV reuse (prefix_cache.py): new prompts adopt cached
        # pages at admission, completed sequences release pages back to the
        # tree instead of the free list. None = cache off (reference
        # recompute-everything behaviour). Mutually exclusive with the
        # sliding-window page ring (ring reuse overwrites pages in place, so
        # a cached page's content would rot under a live sharer).
        self.prefix_cache = prefix_cache
        # prompt tokens actually prefilled (post-cache); the shared-prefix
        # bench leg reads this to report computed-prefill savings
        self.prefill_tokens_completed = 0
        self.seqs: Dict[int, DSSequenceDescriptor] = {}
        bs = cache.config.block_size
        self.max_blocks = -(-config.max_context // bs)
        # sliding-window span (set by the engine from the model spec). With a
        # window, per-sequence physical KV is a PAGE RING of ring_pages
        # blocks: logical page i beyond the ring reuses blocks[i - ring];
        # dead tokens are overwritten in place, so a sequence's KV footprint
        # is bounded by the window however long it runs (the ZeRO-Inference
        # long-context analog of the reference's sliding cache).
        self.window: Optional[int] = None
        # record token history even without a prefix cache (set by the
        # engine when speculative decoding is on: the n-gram proposer drafts
        # from each sequence's prompt history, spec/proposer.py)
        self.record_history_always = False

    @property
    def _pass_take_cap(self) -> int:
        """Max prompt tokens one sequence may take in one pass under a
        window (also bounds the live span the ring must cover)."""
        cfg = self.config
        return min(self.window + self.cache.config.block_size,
                   cfg.num_chunk_slots * cfg.chunk_slot_size)

    @property
    def ring_pages(self) -> Optional[int]:
        """Physical pages per sequence under a window. The live span during
        a pass is [earliest_query - window + 1, write_head]: a chunked
        continuation pass of T tokens still needs ``window`` tokens behind
        its FIRST query row while writing T ahead, so the ring covers
        window + T (+1 page of slack) — not just the window. Aliased logical
        pages are then >= ring*bs > window + T tokens apart: no pass can
        read or scatter-collide with a page it is overwriting."""
        if self.window is None:
            return None
        bs = self.cache.config.block_size
        return -(-(self.window + self._pass_take_cap) // bs) + 1

    def ring_covers(self, n_tokens: int) -> bool:
        """True iff a consumer may freeze page reads while writing
        ``n_tokens`` ahead (the side-buffer multistep schedule's flush
        pattern): the ring spans window + _pass_take_cap live tokens, so a
        frozen chunk is safe only when its whole write fits in the take the
        ring was sized for. Without a window there is no ring — always
        True."""
        if self.window is None:
            return True
        return n_tokens <= self._pass_take_cap

    # ------------------------------------------------------------------ #
    # sequence admission (parity: engine_v2.put token intake)
    # ------------------------------------------------------------------ #

    def add_tokens(self, uid: int, tokens: np.ndarray) -> None:
        tokens = np.asarray(tokens, np.int32)
        seq = self.seqs.get(uid)
        known = 0 if seq is None else seq.seen_tokens + len(seq.pending)
        total = known + len(tokens)
        if total > self.config.max_context:
            raise ValueError(f"sequence {uid}: {total} tokens > max_context "
                             f"{self.config.max_context}")
        new_seq = seq is None
        if new_seq:
            if len(self.seqs) >= self.config.max_tracked_sequences:
                raise RuntimeError(
                    f"max_tracked_sequences={self.config.max_tracked_sequences} exceeded")
            seq = self.seqs[uid] = DSSequenceDescriptor(uid=uid)
            if self.prefix_cache is not None:
                seq.weight_version = self.prefix_cache.weight_version
        if self._cache_active or self.record_history_always:
            seq.record_history(tokens)
        if self._cache_active:
            if new_seq and len(tokens) > 1:
                # adopt every cached whole-block prefix: matched pages join
                # the block table with ZERO prefill scheduled; only the
                # uncached tail (always >= 1 token, so the last token's
                # logits are computed fresh) goes through SplitFuse
                m = self.prefix_cache.match(tokens)
                if m.n_cached:
                    seq.blocks.extend(m.blocks)
                    seq.seen_tokens = m.n_cached
                    seq.cached_tokens = m.n_cached
                    tokens = tokens[m.n_cached:]
        seq.extend_pending(tokens)

    @property
    def _cache_active(self) -> bool:
        return self.prefix_cache is not None and self.window is None

    def flush(self, uid: int) -> None:
        """Release a sequence's KV blocks (parity: ``engine_v2.flush``). With
        the prefix cache on, pages return to the radix tree — warm for the
        next matching prompt — instead of the free list; eviction reclaims
        them under pool pressure."""
        seq = self.seqs.pop(uid, None)
        if seq is None or not seq.blocks:
            return
        # ring reuse repeats physical ids in the logical list — settle each once
        uniq = list(dict.fromkeys(seq.blocks))
        if self._cache_active \
                and seq.weight_version == self.prefix_cache.weight_version:
            known = self._cacheable_tokens(seq)
            self.prefix_cache.release(seq.history(known), uniq)
        else:
            # no cache — or this sequence's KV predates a weight swap
            # (weight_version stamp trails the tree): old-weight pages must
            # never be filed into the post-swap tree, so they free instead
            self.allocator.free(uniq)

    @staticmethod
    def _cacheable_tokens(seq: DSSequenceDescriptor) -> int:
        """Tokens whose (position -> token id) mapping is certain: the
        contiguous recorded-history prefix, capped by what the KV actually
        holds. Pages beyond this are released, never cached."""
        valid = seq.history_len if seq.history_valid is None \
            else seq.history_valid
        return min(valid, seq.seen_tokens)

    # ------------------------------------------------------------------ #
    # capacity queries (parity: engine_v2.query/can_schedule :153-227)
    # ------------------------------------------------------------------ #

    def _new_blocks_needed(self, seq: DSSequenceDescriptor,
                           new_tokens: int) -> int:
        """Fresh allocator blocks required for ``new_tokens`` more tokens —
        under a window, capped by the ring (pages beyond it are reuses)."""
        bs = self.cache.config.block_size
        need = seq.kv_blocks_needed(new_tokens, bs)
        ring = self.ring_pages
        if ring is not None:
            need = min(need, max(0, ring - len(seq.blocks)))
        return need

    def _available_blocks(self) -> int:
        """Blocks obtainable right now: the free list plus cached pages held
        only by the radix tree (evicted on demand by ``_alloc``)."""
        free = self.allocator.free_blocks
        if self._cache_active:
            free += self.prefix_cache.evictable_blocks
        return free

    def _alloc(self, num_blocks: int) -> np.ndarray:
        """Allocate, LRU-evicting idle cached pages to cover a shortfall."""
        short = num_blocks - self.allocator.free_blocks
        if short > 0 and self._cache_active:
            self.prefix_cache.evict(short)
        return self.allocator.allocate(num_blocks)

    def query(self, uid: int, max_request_tokens: int) -> Tuple[int, int]:
        """(max new tokens fundable by free blocks, available blocks).
        Accounts for queued-but-unprocessed pending tokens, which will consume
        the same pool; cached-but-idle prefix pages count as available (they
        evict on demand)."""
        seq = self.seqs.get(uid, DSSequenceDescriptor(uid=uid))
        bs = self.cache.config.block_size
        avail = self._available_blocks()
        if self.ring_pages is not None and len(seq.blocks) >= self.ring_pages:
            # ring complete: any request fits in place (up to max_context)
            return max_request_tokens, avail
        slack = len(seq.blocks) * bs - seq.seen_tokens - len(seq.pending)
        fundable = max(0, slack + avail * bs)
        return min(max_request_tokens, fundable), avail

    def can_schedule(self, uids: List[int], lengths: List[int]) -> bool:
        needed = 0
        for uid, n in zip(uids, lengths):
            seq = self.seqs.get(uid, DSSequenceDescriptor(uid=uid))
            needed += self._new_blocks_needed(seq, len(seq.pending) + n)
        # free list first: the evictable count walks the whole radix tree,
        # only worth it on an actual shortfall
        if needed > self.allocator.free_blocks \
                and needed > self._available_blocks():
            return False
        new = sum(1 for u in uids if u not in self.seqs)
        return len(self.seqs) + new <= self.config.max_tracked_sequences

    def has_pending(self) -> bool:
        return any(len(s.pending) > 0 for s in self.seqs.values())

    @property
    def available_blocks(self) -> int:
        """Blocks obtainable right now (free list + evictable cached pages) —
        the capacity number the serving frontend's admission model plans
        with."""
        return self._available_blocks()

    def blocks_needed(self, uids: List[int], n_tokens: int) -> int:
        """Fresh allocator blocks a fused-decode reservation of ``n_tokens``
        more tokens for every uid would take (``decode_batch``'s per-run
        ``reserve``) — the serving frontend's per-slice funding check."""
        return sum(self._new_blocks_needed(self.seqs[u], n_tokens)
                   for u in uids)

    # ------------------------------------------------------------------ #
    # preempt-offload support (serving frontend; docs/SERVING.md)
    # ------------------------------------------------------------------ #

    def private_tail(self, uid: int) -> Tuple[int, List[int]]:
        """``(kept, tail)``: the maximal *suffix* of ``uid``'s block table
        held by nobody else (allocator refcount 1) — the pages preemption may
        offload. Shared pages (radix-tree references, co-holding sequences)
        are always a prefix here: the tree files/matches whole-block
        prefixes only, and eviction never touches a page a live sequence
        holds — so a shared page's content is stable and the sequence simply
        keeps its references across the preemption."""
        if self.window is not None:
            raise NotImplementedError(
                "preemption with a sliding-window page ring is not wired "
                "(the logical block list aliases physical pages)")
        blocks = self.seqs[uid].blocks
        k = len(blocks)
        while k > 0 and self.allocator.ref_count(blocks[k - 1]) == 1:
            k -= 1
        return k, list(blocks[k:])

    def drop_tail(self, uid: int, kept: int) -> None:
        """Free the blocks beyond ``kept`` and truncate the block table —
        the releasing half of a preempt-offload (page CONTENT must already
        be copied out; ``free`` recycles the ids immediately)."""
        seq = self.seqs[uid]
        self.allocator.free(seq.blocks[kept:])
        del seq.blocks[kept:]

    def adopt_sequence(self, uid: int, tokens: np.ndarray,
                       n_blocks: int) -> List[int]:
        """Create a sequence whose KV was computed ELSEWHERE — the import
        half of a cross-engine prefill->decode handoff (``engine.import_kv``;
        serving/cluster.py). Allocates ``n_blocks`` fresh pages (LRU-evicting
        idle cached pages on a shortfall), records the token history, and
        marks all ``tokens`` as seen — the caller scatters the page CONTENT
        in (``engine.put_pages``) before the sequence decodes. Returns the
        allocated ids in logical order, exactly like ``grow_tail``."""
        if self.window is not None:
            raise NotImplementedError(
                "cross-engine KV adoption with a sliding-window page ring "
                "is not wired (the logical block list aliases physical "
                "pages)")
        tokens = np.asarray(tokens, np.int32)
        if uid in self.seqs:
            raise ValueError(f"sequence {uid} is already tracked")
        if len(tokens) < 1:
            raise ValueError("adopt_sequence needs at least one token")
        if len(tokens) > self.config.max_context:
            raise ValueError(f"sequence {uid}: {len(tokens)} tokens > "
                             f"max_context {self.config.max_context}")
        bs = self.cache.config.block_size
        if n_blocks * bs < len(tokens):
            raise ValueError(
                f"{n_blocks} pages cannot hold {len(tokens)} tokens at "
                f"block_size {bs}")
        if len(self.seqs) >= self.config.max_tracked_sequences:
            raise RuntimeError(
                f"max_tracked_sequences={self.config.max_tracked_sequences} "
                "exceeded")
        if n_blocks > self.allocator.free_blocks \
                and n_blocks > self._available_blocks():
            raise RuntimeError(
                f"cannot adopt sequence {uid}: needs {n_blocks} KV blocks, "
                f"{self._available_blocks()} obtainable")
        seq = self.seqs[uid] = DSSequenceDescriptor(uid=uid)
        if self._cache_active or self.record_history_always:
            seq.record_history(tokens)
        ids = [int(b) for b in self._alloc(n_blocks)] if n_blocks else []
        seq.blocks.extend(ids)
        seq.seen_tokens = len(tokens)
        return ids

    def grow_tail(self, uid: int, n: int) -> List[int]:
        """Append ``n`` fresh pages to ``uid``'s block table (LRU-evicting
        idle cached pages on a shortfall) and return their ids, in order —
        the restore half: the caller scatters the offloaded page contents
        into these before the sequence decodes again."""
        seq = self.seqs[uid]
        ids = [int(b) for b in self._alloc(n)] if n else []
        seq.blocks.extend(ids)
        return ids

    # ------------------------------------------------------------------ #
    # multi-step decode support (device-fused token loop)
    # ------------------------------------------------------------------ #

    def reserve(self, uid: int, n_tokens: int) -> None:
        """Pre-allocate KV blocks so ``uid`` can append ``n_tokens`` without
        host intervention (the fused N-step decode writes pages directly).
        Enforces the same max_context bound as ``add_tokens``."""
        seq = self.seqs[uid]
        total = seq.seen_tokens + len(seq.pending) + n_tokens
        if total > self.config.max_context:
            raise ValueError(f"sequence {uid}: {total} tokens > max_context "
                             f"{self.config.max_context}")
        self._ensure_blocks(seq, n_tokens)

    def decode_batch(self, uids: List[int], n_reserve: int,
                     scratch_block: int) -> "DecodeBatch":
        """Bucketed decode-only descriptors for the fused decode programs.

        Reserves ``n_reserve`` tokens of KV per sequence UP FRONT (so the
        per-step host work during a fused burst / pipelined run is just the
        ``DecodeBatch.advance`` increments — the block tables already cover
        the whole run), then packs positions/block-tables/context-lengths
        into arrays padded to ``next_pow2(len(uids))`` rows. Pad rows point
        wholly at ``scratch_block`` (see DecodeBatch for why that is inert).
        """
        from deepspeed_tpu.utils.caching import next_pow2
        for u in uids:
            self.reserve(u, n_reserve)
        bucket = next_pow2(len(uids))
        mb = self.max_blocks
        bt = np.full((bucket, mb), scratch_block, np.int32)
        pos = np.zeros((bucket,), np.int32)
        for i, u in enumerate(uids):
            seq = self.seqs[u]
            bt[i] = seq.block_table(mb)
            pos[i] = seq.seen_tokens
        # pad rows: pos 0 -> ctx 1, attending exactly one (scratch) token
        ctx = pos + 1
        from deepspeed_tpu.inference.v2.ragged.ragged_batch import DecodeBatch
        return DecodeBatch(uids=[int(u) for u in uids], bucket=bucket,
                           positions=pos, block_tables=bt, ctx_lens=ctx)

    def advance(self, uid: int, n_tokens: int) -> None:
        """Record ``n_tokens`` device-generated tokens (their KV was written
        by the fused loop; no pending compute remains)."""
        seq = self.seqs[uid]
        assert len(seq.pending) == 0, "advance() with pending host tokens"
        if self._cache_active and seq.history_valid is None:
            # the host never saw these tokens: history recorded after this
            # point is position-shifted, unusable as radix keys — seal the
            # contiguous prefix here (see DSSequenceDescriptor.history_valid)
            seq.history_valid = seq.history_len
        seq.seen_tokens += n_tokens

    def rollback_reserved(self, uid: int) -> List[int]:
        """Block-granular KV rollback: free every reserved-but-unused
        trailing block — pages wholly past ``seen_tokens`` — and truncate
        the block table. Returns the freed ids.

        This is the speculative-decode reject path's reclamation
        (``spec/pipeline.py``): a verify run reserves KV for full acceptance
        up front, and a reject-heavy run leaves whole pages the advanced
        history never reached. Only the FRESH suffix is ever touched:
        prefix-cache-shared pages and COW-adopted tails all hold tokens
        within ``seen_tokens`` (the tree files whole-block history prefixes;
        COW adoption copies a partial page the sequence then fills), so the
        rollback boundary can never cross a shared or content-bearing page
        — enforced by the refcount guard below, not just assumed."""
        if self.window is not None:
            # ring reuse repeats physical ids in the logical list; there is
            # no fresh suffix to roll back (and spec decode refuses windowed
            # models before ever reserving ahead)
            return []
        seq = self.seqs[uid]
        bs = self.cache.config.block_size
        need = -(-seq.seen_tokens // bs)
        tail = [int(b) for b in seq.blocks[need:]]
        if not tail:
            return []
        shared = [b for b in tail if self.allocator.ref_count(b) != 1]
        if shared:
            raise RuntimeError(
                f"rollback of sequence {uid} would free shared block(s) "
                f"{shared} (refcount != 1) — reserved tails must be fresh")
        self.allocator.free(tail)
        del seq.blocks[need:]
        return tail

    # ------------------------------------------------------------------ #
    # pass construction
    # ------------------------------------------------------------------ #

    def _ensure_blocks(self, seq: DSSequenceDescriptor, new_tokens: int) -> None:
        bs = self.cache.config.block_size
        ring = self.ring_pages
        if ring is None:
            need = seq.kv_blocks_needed(new_tokens, bs)
            if need:
                seq.blocks.extend(int(b) for b in self._alloc(need))
            return
        target = -(-(seq.seen_tokens + new_tokens) // bs)   # logical pages
        fresh = min(max(0, target - len(seq.blocks)),
                    max(0, ring - len(seq.blocks)))
        if fresh:
            seq.blocks.extend(int(b) for b in self._alloc(fresh))
        while len(seq.blocks) < target:                      # ring reuse
            seq.blocks.append(seq.blocks[len(seq.blocks) - ring])

    def schedule_pass(self) -> Optional[RaggedBatch]:
        """Build the next pass, or None when no pending work exists."""
        cfg = self.config
        NC, Cs = cfg.num_chunk_slots, cfg.chunk_slot_size
        S, MB = cfg.max_ragged_sequence_count, self.max_blocks
        bs = self.cache.config.block_size
        batch = RaggedBatch(num_slots=NC, slot_size=Cs, max_sequences=S,
                            max_blocks=MB)
        kv_dest = np.full((NC * Cs + S,), self.cache.oob_sentinel, np.int32)

        # decode rows: sequences holding exactly one pending token
        decode = [s for s in self.seqs.values()
                  if len(s.pending) == 1 and s.seen_tokens > 0]
        decode = decode[:S]
        for row, seq in enumerate(decode):
            self._ensure_blocks(seq, 1)
            pos = seq.seen_tokens
            batch.decode_uids.append(seq.uid)
            batch.decode_tokens[row] = seq.pending[0]
            batch.decode_positions[row] = pos
            batch.decode_block_tables[row] = seq.block_table(MB)
            batch.decode_ctx_lens[row] = pos + 1
            kv_dest[NC * Cs + row] = self.cache.flat_write_index(
                seq.blocks[pos // bs], pos % bs)
            seq.in_flight_tokens = 1

        # prompt chunks, up to NC slots: longest pending first (prefer
        # finishing prefills). A sequence may claim SEVERAL consecutive slots
        # in one pass (its chunk KV is scattered before attention runs, so a
        # later slot sees the earlier slots' tokens) — a lone long prompt
        # then prefills at the full slot capacity per pass, not one slot.
        prompts = sorted((s for s in self.seqs.values()
                          if len(s.pending) > 1 or
                          (len(s.pending) == 1 and s.seen_tokens == 0
                           and s.uid not in batch.decode_uids)),
                         key=lambda s: -len(s.pending))
        sl = 0
        from_zero = True   # every chunk sequence starts at position 0?
        # page-granular write plan (pure-prefill fast path; see RaggedBatch)
        PW = NC * Cs // bs + NC
        batch.page_ids = np.full((PW,), self.cache.config.num_blocks, np.int32)
        batch.page_rows = np.zeros((PW,), np.int32)
        batch.page_fill = np.zeros((PW,), np.int32)
        pw = 0
        for seq in prompts:
            if sl >= NC:
                break
            take = min(len(seq.pending), (NC - sl) * Cs)
            if self.window is not None:
                # the ring covers window + _pass_take_cap tokens of live
                # span; taking more in one pass would overwrite pages the
                # pass's own queries still need (the remainder prefills on
                # the next pass)
                take = min(take, self._pass_take_cap)
            self._ensure_blocks(seq, take)
            blocks = np.asarray(seq.blocks, np.int32)
            batch.chunk_uids.append(seq.uid)
            batch.chunk_is_final.append(take == len(seq.pending))
            if seq.seen_tokens > 0:
                from_zero = False
            else:
                # from position 0, tokens fill pages in order: one plan entry
                # per touched page, rows contiguous from this seq's first row.
                # Under a window, pages wholly dead by the end of the take are
                # skipped — their tokens are never attended again, and writing
                # them could collide with a ring-reused live page in the same
                # scatter.
                r0_seq = sl * Cs
                for p in range(-(-take // bs)):
                    if (self.window is not None
                            and (p + 1) * bs <= take - self.window):
                        continue
                    batch.page_ids[pw] = blocks[p]
                    batch.page_rows[pw] = r0_seq + p * bs
                    batch.page_fill[pw] = min(bs, take - p * bs)
                    pw += 1
            taken = 0
            while taken < take:
                n = min(Cs, take - taken)
                q0 = seq.seen_tokens + taken
                positions = q0 + np.arange(n, dtype=np.int32)
                r0 = sl * Cs
                batch.chunk_tokens[r0:r0 + n] = seq.pending[taken:taken + n]
                batch.chunk_positions[r0:r0 + n] = positions
                batch.chunk_ntok[sl] = n
                batch.chunk_block_tables[sl] = seq.block_table(MB)
                batch.chunk_q0[sl] = q0
                batch.chunk_ctx_lens[sl] = q0 + n
                batch.row_seg[r0:r0 + n] = len(batch.chunk_uids) - 1
                kv_dest[r0:r0 + n] = self.cache.flat_write_index(
                    blocks[positions // bs], positions % bs)
                batch.slot_uid.append(seq.uid)
                taken += n
                sl += 1
            seq.in_flight_tokens = take

        batch.kv_dest = kv_dest
        batch.pure_prefill = (not batch.decode_uids and bool(batch.chunk_uids)
                              and from_zero)
        if batch.current_sequences == 0:
            return None
        # flash_attention_packed's correctness contract (see its docstring:
        # per-sequence rows contiguous-in-order, padding rows seg -1) is
        # PRODUCED here, so it is asserted here: non-padding row_seg values
        # must be non-decreasing and positions within a segment must advance
        # by exactly 1. O(rows) numpy — negligible next to the pass itself.
        live = batch.row_seg >= 0
        segs = batch.row_seg[live]
        if segs.size > 1:
            dseg = np.diff(segs)
            dpos = np.diff(batch.chunk_positions[live])
            if not (np.all(dseg >= 0) and np.all(dpos[dseg == 0] == 1)):
                raise AssertionError(
                    "scheduler produced an interleaved/unordered packed "
                    "batch; flash_attention_packed requires per-sequence "
                    "rows contiguous and position-ordered")
        return batch

    def complete_pass(self, batch: RaggedBatch) -> List[int]:
        """Advance descriptors after the pass ran; returns uids whose *next-token
        logits* this pass produced (final prompt chunks + all decode rows)."""
        finished: List[int] = []
        for uid, is_final in zip(batch.chunk_uids, batch.chunk_is_final):
            seq = self.seqs[uid]
            n = seq.in_flight_tokens
            seq.seen_tokens += n
            seq.pending = seq.pending[n:]
            seq.in_flight_tokens = 0
            self.prefill_tokens_completed += n
            if is_final:
                finished.append(uid)
                if self._cache_active:
                    # eager insert: file the finished prompt's FULL pages into
                    # the radix tree now (tree takes its own references; the
                    # live sequence keeps its own), so later arrivals reuse
                    # them without waiting for this sequence to flush. Partial
                    # tails are only filed at flush — one tree node per page,
                    # so eviction accounting stays exact. The filed_tokens
                    # watermark skips the re-walk when no NEW full page
                    # completed since the last insert (multi-turn put()s).
                    bs = self.cache.config.block_size
                    known = self._cacheable_tokens(seq)
                    full = (known // bs) * bs
                    if full > seq.filed_tokens and seq.weight_version \
                            == self.prefix_cache.weight_version:
                        self.prefix_cache.insert(seq.history(full),
                                                 seq.blocks[:full // bs],
                                                 transfer_refs=False)
                        seq.filed_tokens = full
        for uid in batch.decode_uids:
            seq = self.seqs[uid]
            seq.seen_tokens += 1
            seq.pending = seq.pending[1:]
            seq.in_flight_tokens = 0
            finished.append(uid)
        return finished
