"""Blocked (paged) KV cache.

Parity: ``KVCacheManager`` / blocked KV configs (reference
``inference/v2/ragged/kv_cache.py`` + ``inference/v2/ragged/manager_configs.py``).
Pages are device arrays ``[L, num_blocks, H_kv, block_size, D]`` — HEAD-MAJOR
pages, chosen so

  - every pool view in the serving program has (block_size, head_dim) trailing
    dims: no padded sublane tiles for any kv-head count, so the flat-rows <->
    paged reshapes in the layer scan are bitcasts (a head-minor layout makes
    XLA materialise pool-sized copies at e.g. H_kv=12 — see
    ops/pallas/paged_attention.py module docstring);
  - the paged kernels pull whole contiguous pages via scalar-prefetched block
    tables, one DMA per page;
  - the per-token cache write is a flat scatter of H_kv rows at
    ``(block * H_kv + h) * block_size + slot``.

Sharding: KV heads ride the 'tensor' mesh axis when divisible (the reference slices
KV heads across TP ranks in its sharded model implementations); layers/pages are
never sharded — a page must live whole on the chip that attends with it.

The cache arrays are *functional*: each engine pass takes them as donated jit
arguments and returns the updated pages, so XLA aliases them in place in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import TENSOR_AXIS, MeshTopology


@dataclass
class KVCacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    block_size: int = 128
    num_blocks: int = 256
    dtype: Any = jnp.bfloat16
    # int8 pages with per-token-head f32 scales (see config_v2.KVQuantConfig):
    # the pools become (int8 values, f32 scales) pytrees; every consumer
    # dequantizes in-kernel
    quantized: bool = False

    @property
    def max_tokens(self) -> int:
        return self.num_blocks * self.block_size

    def bytes_per_block(self) -> int:
        """Exact at-rest bytes of one pool block across all layers — for
        quantized pools this is ALSO the host page-fabric payload size
        (``engine.page_payload_spec``): int8 values plus the f32 scale tile
        in its padded DMA layout, one source of size truth for offload
        capacity accounting and handoff validation."""
        if self.quantized:
            from deepspeed_tpu.ops.pallas.paged_attention import (
                kv_scale_tiles_shape)
            _, r8, lanes = kv_scale_tiles_shape(1, self.num_kv_heads,
                                                self.block_size)
            values = 2 * self.num_kv_heads * self.block_size * self.head_dim
            return self.num_layers * (values + r8 * lanes * 4)
        itemsize = jnp.dtype(self.dtype).itemsize
        return (2 * self.num_layers * self.block_size * self.num_kv_heads
                * self.head_dim * itemsize)

    @classmethod
    def from_memory_budget(cls, num_layers: int, num_kv_heads: int, head_dim: int,
                           budget_bytes: int, block_size: int = 128,
                           dtype: Any = jnp.bfloat16) -> "KVCacheConfig":
        """Size the pool from an HBM budget (parity: the reference sizes its pool
        from free GPU memory after model load, ``engine_v2.py`` memory config)."""
        probe = cls(num_layers, num_kv_heads, head_dim, block_size, 1, dtype)
        nb = max(1, budget_bytes // probe.bytes_per_block())
        return cls(num_layers, num_kv_heads, head_dim, block_size, int(nb), dtype)


class BlockedKVCache:
    """Owns the combined page array [L, NB, 2, Hkv, bs, D] (K = index 0,
    V = index 1 — one page per sequence-chunk holds BOTH, because the
    decode kernel is per-DMA-copy bound; see ops/pallas/paged_attention.py)
    and its sharding. With ``config.quantized`` the pool is an (int8
    values, f32 per-token-head scales [L, NB, 2, Hkv, bs]) tuple."""

    def __init__(self, config: KVCacheConfig, topology: Optional[MeshTopology] = None):
        self.config = config
        self.topology = topology
        self._copy_prog = None      # COW page-copy program (copy_page)
        shape = (config.num_layers, config.num_blocks, 2,
                 config.num_kv_heads, config.block_size, config.head_dim)
        sharding = None
        if topology is not None:
            tp = topology.tp_world_size
            spec = [None] * 6
            if tp > 1 and config.num_kv_heads % tp == 0:
                spec[3] = TENSOR_AXIS
            sharding = NamedSharding(topology.mesh, P(*spec))
        if config.quantized:
            if sharding is not None and topology.tp_world_size > 1:
                raise NotImplementedError(
                    "int8 KV pages with tensor_parallel > 1 are not wired")
            # scales live in the kernels' DMA tile layout AT REST
            # ([L, NB, R8, 128] f32; paged_attention.kv_scale_tiles_shape) so
            # no pass ever pays a pool-sized pad+reshape to convert them
            from deepspeed_tpu.ops.pallas.paged_attention import (
                kv_scale_tiles_shape)
            sshape = (config.num_layers,) + kv_scale_tiles_shape(
                config.num_blocks, config.num_kv_heads, config.block_size)
            self.kv = (_zeros(shape, jnp.int8, None),
                       _zeros(sshape, jnp.float32, None))
        else:
            self.kv = _zeros(shape, config.dtype, sharding)
        self.sharding = sharding

    def update(self, kv) -> None:
        """Adopt the pages returned by a jitted pass (donated in, aliased out)."""
        self.kv = kv

    def copy_page(self, src_block: int, dst_block: int) -> None:
        """Device-side copy of one whole page (all layers, K and V) — the
        prefix cache's copy-on-write step when a sequence adopts a
        partially-filled cached page it must keep writing into. One jitted
        program reused for every (src, dst) pair via traced scalar indices.
        The tree_map'd body carries a quantized pool's (values, scale
        tiles) tuple leaf-for-leaf — both leaves have the page dim at axis
        1, so COW adoption copies a page's int8 bytes AND its scale tile
        together, byte-exactly (tests/unit/test_kv_quant_stack.py)."""
        if self._copy_prog is None:
            import functools

            @functools.partial(jax.jit, donate_argnums=(0,))
            def _copy(kv, src, dst):
                return jax.tree_util.tree_map(
                    lambda a: a.at[:, dst].set(a[:, src]), kv)

            self._copy_prog = _copy
        self.kv = self._copy_prog(self.kv, jnp.int32(src_block),
                                  jnp.int32(dst_block))

    def flat_write_index(self, block_id: np.ndarray, slot: np.ndarray) -> np.ndarray:
        """Host-side: flat scatter destination over the fused page dim; padding
        rows use an out-of-bounds sentinel so the scatter drops them."""
        return (np.asarray(block_id, np.int64) * self.config.block_size
                + np.asarray(slot, np.int64)).astype(np.int32)

    @property
    def oob_sentinel(self) -> int:
        return self.config.num_blocks * self.config.block_size


def _zeros(shape: Tuple[int, ...], dtype, sharding):
    if sharding is None:
        return jnp.zeros(shape, dtype)
    return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)()
