"""Ragged batching primitives (parity: reference ``inference/v2/ragged/``)."""

from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache, KVCacheConfig
from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import DSSequenceDescriptor
from deepspeed_tpu.inference.v2.ragged.ragged_batch import RaggedBatch
