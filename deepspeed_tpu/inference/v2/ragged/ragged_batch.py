"""Host-side pass descriptor arrays.

Parity: ``RaggedBatchWrapper`` (reference ``inference/v2/ragged/ragged_wrapper.py``)
— the per-forward metadata buffers (token ids, inflight descriptors, KV block
tables) assembled on host and shipped to device once per pass. The reference uses
pinned host buffers (``ragged/csrc/fast_host_buffer.cu``); here plain numpy arrays
feed ``jax.device_put`` / jit donation.

Pass layout (static shapes; see ``ragged_model.py`` for how each section is used):

  - **chunk section** (``chunk_budget`` rows): one sequence's prompt chunk —
    Dynamic SplitFuse processes at most one prompt chunk per pass alongside all
    ready decode tokens, so prefill never stalls token generation.
  - **decode section** (``max_sequences`` rows): one query token per sequence,
    served by the paged flash-decode kernel.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class RaggedBatch:
    # static capacities
    chunk_budget: int
    max_sequences: int
    max_blocks: int

    # chunk section (one prompt chunk)
    chunk_uid: Optional[int] = None
    chunk_tokens: np.ndarray = None           # [C] int32
    chunk_positions: np.ndarray = None        # [C] int32
    chunk_num_tokens: int = 0
    chunk_block_table: np.ndarray = None      # [MB] int32
    chunk_ctx_len: int = 0                    # kv visible after this chunk
    chunk_is_final: bool = False              # last chunk of prompt -> logits used

    # decode section
    decode_uids: List[int] = field(default_factory=list)
    decode_tokens: np.ndarray = None          # [S] int32
    decode_positions: np.ndarray = None       # [S] int32
    decode_block_tables: np.ndarray = None    # [S, MB] int32
    decode_ctx_lens: np.ndarray = None        # [S] int32 (0 => inactive row)

    # flat KV scatter destinations for every new token, chunk rows then decode
    # rows; padding rows hold the cache's OOB sentinel so the write drops them
    kv_dest: np.ndarray = None                # [C + S] int32

    def __post_init__(self):
        C, S, MB = self.chunk_budget, self.max_sequences, self.max_blocks
        if self.chunk_tokens is None:
            self.chunk_tokens = np.zeros((C,), np.int32)
        if self.chunk_positions is None:
            self.chunk_positions = np.zeros((C,), np.int32)
        if self.chunk_block_table is None:
            self.chunk_block_table = np.zeros((MB,), np.int32)
        if self.decode_tokens is None:
            self.decode_tokens = np.zeros((S,), np.int32)
        if self.decode_positions is None:
            self.decode_positions = np.zeros((S,), np.int32)
        if self.decode_block_tables is None:
            self.decode_block_tables = np.zeros((S, MB), np.int32)
        if self.decode_ctx_lens is None:
            self.decode_ctx_lens = np.zeros((S,), np.int32)
        if self.kv_dest is None:
            self.kv_dest = np.zeros((C + S,), np.int32)

    @property
    def current_tokens(self) -> int:
        return self.chunk_num_tokens + len(self.decode_uids)

    @property
    def current_sequences(self) -> int:
        return (1 if self.chunk_uid is not None else 0) + len(self.decode_uids)

    def device_arrays(self) -> Dict[str, Any]:
        """The dict handed to the jitted pass (shapes static across passes)."""
        return {
            "chunk_tokens": self.chunk_tokens,
            "chunk_positions": self.chunk_positions,
            "chunk_num_tokens": np.int32(self.chunk_num_tokens),
            "chunk_block_table": self.chunk_block_table,
            "chunk_ctx_len": np.int32(self.chunk_ctx_len),
            "decode_tokens": self.decode_tokens,
            "decode_positions": self.decode_positions,
            "decode_block_tables": self.decode_block_tables,
            "decode_ctx_lens": self.decode_ctx_lens,
            "kv_dest": self.kv_dest,
        }
