"""Host-side pass descriptor arrays.

Parity: ``RaggedBatchWrapper`` (reference ``inference/v2/ragged/ragged_wrapper.py``)
— the per-forward metadata buffers (token ids, inflight descriptors, KV block
tables) assembled on host and shipped to device once per pass. The reference uses
pinned host buffers (``ragged/csrc/fast_host_buffer.cu``); here plain numpy arrays
feed ``jax.device_put`` / jit donation.

Pass layout (static shapes; see ``ragged_model.py`` for how each section is used):

  - **chunk section** (``num_slots`` slots of ``slot_size`` rows): several
    sequences' prompt chunks prefill together in one pass — one chunk per pass
    would serialise N prompts on N pass dispatches (host descriptor build +
    transfer RTT each); Dynamic SplitFuse composes them with the ready decode
    tokens so prefill never stalls token generation.
  - **decode section** (``max_sequences`` rows): one query token per sequence,
    served by the paged flash-decode kernel.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np


@dataclass
class RaggedBatch:
    # static capacities
    num_slots: int                            # chunk slots per pass
    slot_size: int                            # tokens per slot
    max_sequences: int
    max_blocks: int

    # chunk section (num_slots prompt chunks, slot-major rows). A sequence
    # may span several consecutive slots in one pass: chunk_uids and
    # chunk_is_final are per SEQUENCE (scheduling order); slot_uid is per
    # filled SLOT (the logits row for a finished prompt is its last slot).
    chunk_uids: List[int] = field(default_factory=list)   # per sequence
    slot_uid: List[int] = field(default_factory=list)     # per filled slot
    chunk_tokens: np.ndarray = None           # [NC * Cs] int32
    chunk_positions: np.ndarray = None        # [NC * Cs] int32
    chunk_ntok: np.ndarray = None             # [NC] int32 (0 = empty slot)
    chunk_block_tables: np.ndarray = None     # [NC, MB] int32
    chunk_q0: np.ndarray = None               # [NC] int32
    chunk_ctx_lens: np.ndarray = None         # [NC] int32 (0 = empty slot)
    chunk_is_final: List[bool] = field(default_factory=list)  # per sequence

    # decode section
    decode_uids: List[int] = field(default_factory=list)
    decode_tokens: np.ndarray = None          # [S] int32
    decode_positions: np.ndarray = None       # [S] int32
    decode_block_tables: np.ndarray = None    # [S, MB] int32
    decode_ctx_lens: np.ndarray = None        # [S] int32 (0 => inactive row)

    # flat KV scatter destinations for every new token, chunk rows then decode
    # rows; padding rows hold the cache's OOB sentinel so the write drops them
    kv_dest: np.ndarray = None                # [NC * Cs + S] int32

    # per-chunk-row sequence index (position in chunk_uids; -1 = padding row)
    # for the packed-flash prefill fast path; decode rows are not included
    row_seg: np.ndarray = None                # [NC * Cs] int32
    # True when this pass is prefill-from-zero only (no decode rows, every
    # chunk sequence starts at position 0): attention then needs no paged
    # reads at all and the engine routes to the packed-flash forward
    pure_prefill: bool = False
    # page-granular KV write plan for pure-prefill passes: each written page
    # is one contiguous run of chunk rows (tokens fill pages in order from
    # slot 0), so the pool update is a scatter of whole [bs, D] windows over
    # ~CT/bs page indices instead of CT*Hkv single rows (TPU scatters cost
    # per index — measured 57 ms -> ~6 ms per wave at 32x128 tokens, v5e-1).
    # page_ids: global page index (NB = padding sentinel, dropped);
    # page_rows: chunk-row index of the page's first token; page_fill: tokens
    # written to that page (stale rows past fill are never read — every
    # reader is bounded by ctx_len).
    page_ids: np.ndarray = None               # [PW] int32
    page_rows: np.ndarray = None              # [PW] int32
    page_fill: np.ndarray = None              # [PW] int32

    def __post_init__(self):
        NC, Cs = self.num_slots, self.slot_size
        S, MB = self.max_sequences, self.max_blocks
        if self.chunk_tokens is None:
            self.chunk_tokens = np.zeros((NC * Cs,), np.int32)
        if self.chunk_positions is None:
            self.chunk_positions = np.zeros((NC * Cs,), np.int32)
        if self.chunk_ntok is None:
            self.chunk_ntok = np.zeros((NC,), np.int32)
        if self.chunk_block_tables is None:
            self.chunk_block_tables = np.zeros((NC, MB), np.int32)
        if self.chunk_q0 is None:
            self.chunk_q0 = np.zeros((NC,), np.int32)
        if self.chunk_ctx_lens is None:
            self.chunk_ctx_lens = np.zeros((NC,), np.int32)
        if self.decode_tokens is None:
            self.decode_tokens = np.zeros((S,), np.int32)
        if self.decode_positions is None:
            self.decode_positions = np.zeros((S,), np.int32)
        if self.decode_block_tables is None:
            self.decode_block_tables = np.zeros((S, MB), np.int32)
        if self.decode_ctx_lens is None:
            self.decode_ctx_lens = np.zeros((S,), np.int32)
        if self.kv_dest is None:
            self.kv_dest = np.zeros((NC * Cs + S,), np.int32)
        if self.row_seg is None:
            self.row_seg = np.full((NC * Cs,), -1, np.int32)
        # page_ids/page_rows/page_fill stay None here: their static size
        # (NC*Cs/bs + NC) needs the cache block size, so the scheduler
        # allocates them (schedule_pass)

    @property
    def current_tokens(self) -> int:
        return int(self.chunk_ntok.sum()) + len(self.decode_uids)

    @property
    def current_sequences(self) -> int:
        return len(self.chunk_uids) + len(self.decode_uids)

    def device_arrays(self) -> Dict[str, Any]:
        """The dict handed to the jitted pass (shapes static across passes)."""
        return {
            "chunk_tokens": self.chunk_tokens,
            "chunk_positions": self.chunk_positions,
            "chunk_ntok": self.chunk_ntok,
            "chunk_block_tables": self.chunk_block_tables,
            "chunk_q0": self.chunk_q0,
            "chunk_ctx_lens": self.chunk_ctx_lens,
            "decode_tokens": self.decode_tokens,
            "decode_positions": self.decode_positions,
            "decode_block_tables": self.decode_block_tables,
            "decode_ctx_lens": self.decode_ctx_lens,
            "kv_dest": self.kv_dest,
            "row_seg": self.row_seg,
            "page_ids": self.page_ids,
            "page_rows": self.page_rows,
            "page_fill": self.page_fill,
        }


@dataclass
class DecodeBatch:
    """BUCKETED decode-only descriptor set for the fused decode programs
    (``decode_steps`` bursts and the double-buffered ``DecodePipeline``).

    Row count is padded to ``bucket = next_pow2(len(uids))`` so every device
    program downstream is keyed by the bucket, not the live count: admitting
    or retiring a sequence moves between cached executables instead of
    triggering a recompile (docs/SERVING.md "bucketing grids"). Pad rows are
    inert fake sequences — position 0, context 1, and a block table that is
    ALL the engine's scratch page, so whatever they read is garbage that
    never reaches a real row and whatever they write lands in the scratch
    page no real sequence maps. This relies on decode being row-independent
    (true for the dense ragged models served here; a capacity-constrained
    MoE router would couple rows and need pad-row masking first).

    Advanced per step by :meth:`advance` — the pipeline's "build step N+1"
    stage is exactly these two tiny allocations, which is why the host side
    of a pipelined decode step is ~free once KV blocks are pre-reserved.
    """
    uids: List[int]
    bucket: int
    positions: np.ndarray       # [bucket] int32; pad rows 0
    block_tables: np.ndarray    # [bucket, MB] int32; pad rows all-scratch
    ctx_lens: np.ndarray        # [bucket] int32; pad rows 1

    @property
    def live(self) -> int:
        return len(self.uids)

    def advance(self, n: int = 1) -> None:
        """Advance every row (pad rows included — their writes stay inside
        the scratch page at any position) by ``n`` generated tokens.

        REBINDS the arrays instead of ``+=``: the previous step's dispatch is
        still in flight and jax's CPU backend may alias host numpy buffers
        zero-copy, so an in-place increment can race the async computation
        reading them (observed as nondeterministic token divergence in the
        pipeline tests; jax arrays made from these buffers must be treated
        as frozen once dispatched)."""
        self.positions = self.positions + np.int32(n)
        self.ctx_lens = self.ctx_lens + np.int32(n)

    def advance_rows(self, counts: np.ndarray) -> None:
        """Per-row variable advance (speculative decode: row i emitted
        ``counts[i]`` tokens this step — accepted draft prefix plus the
        bonus token; pad-row entries advance inside the scratch page like
        :meth:`advance`). Same REBIND discipline as ``advance`` — the
        arrays already uploaded for an in-flight dispatch stay frozen."""
        counts = np.asarray(counts, np.int32)
        assert counts.shape == self.positions.shape, \
            (counts.shape, self.positions.shape)
        self.positions = self.positions + counts
        self.ctx_lens = self.ctx_lens + counts
