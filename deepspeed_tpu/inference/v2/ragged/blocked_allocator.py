"""KV-block allocator.

Parity: ``BlockedAllocator`` (reference ``inference/v2/ragged/blocked_allocator.py``)
— a host-side free list over the fixed pool of KV-cache pages. The reference keeps
an int32 next-pointer linked list in a torch tensor; here a plain python deque (the
pool is host metadata, never shipped to device — only block *tables* are).
"""

from __future__ import annotations

from collections import deque
from typing import Iterable, List

import numpy as np


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free = deque(range(num_blocks))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def allocate(self, num_blocks: int) -> np.ndarray:
        """Pop ``num_blocks`` page ids; raises if the pool is exhausted (the
        scheduler checks ``free_blocks`` first — parity: engine_v2 can_schedule)."""
        if num_blocks > len(self._free):
            raise RuntimeError(
                f"cannot allocate {num_blocks} blocks, only {len(self._free)} free")
        return np.array([self._free.popleft() for _ in range(num_blocks)],
                        dtype=np.int32)

    def free(self, blocks: Iterable[int]) -> None:
        blocks = list(int(b) for b in blocks)
        for b in blocks:
            if not (0 <= b < self._num_blocks):
                raise ValueError(f"block id {b} out of range")
        in_free = set(self._free)
        for b in blocks:
            if b in in_free:
                raise ValueError(f"double free of block {b}")
        self._free.extend(blocks)
