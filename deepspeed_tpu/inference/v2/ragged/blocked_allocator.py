"""KV-block allocator.

Parity: ``BlockedAllocator`` (reference ``inference/v2/ragged/blocked_allocator.py``)
— a host-side free list over the fixed pool of KV-cache pages. The reference keeps
an int32 next-pointer linked list in a torch tensor; here a plain python deque (the
pool is host metadata, never shipped to device — only block *tables* are).

Blocks are reference counted so one physical page can back several sequences
(prefix-cache sharing, ``inference/v2/prefix_cache.py``): ``allocate`` hands out
pages at refcount 1, ``share`` adds a holder, and ``free`` drops one reference —
a page only returns to the free list when its last holder releases it. Callers
that never share (the cache-off engine) see the old allocate/free semantics
unchanged.
"""

from __future__ import annotations

from collections import Counter, deque
from typing import Dict, Iterable, List

import numpy as np


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free = deque(range(num_blocks))
        # block id -> refcount, for every block NOT on the free list. Doubles
        # as the allocated-set for O(k) double-free detection (the old
        # set(self._free) rebuild was O(pool) per free() call).
        self._refs: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def ref_count(self, block: int) -> int:
        """Current reference count (0 = on the free list)."""
        return self._refs.get(int(block), 0)

    def allocate(self, num_blocks: int) -> np.ndarray:
        """Pop ``num_blocks`` page ids at refcount 1; raises if the pool is
        exhausted (the scheduler checks ``free_blocks`` first — parity:
        engine_v2 can_schedule)."""
        if num_blocks > len(self._free):
            raise RuntimeError(
                f"cannot allocate {num_blocks} blocks, only {len(self._free)} free")
        out = [self._free.popleft() for _ in range(num_blocks)]
        for b in out:
            self._refs[b] = 1
        return np.array(out, dtype=np.int32)

    def share(self, blocks: Iterable[int]) -> None:
        """Add one reference to each (already-allocated) block — a second
        holder now backs its sequence with the same physical page."""
        blocks = [int(b) for b in blocks]
        for b in blocks:
            if b not in self._refs:
                raise ValueError(f"cannot share unallocated block {b}")
        for b in blocks:
            self._refs[b] += 1

    def free(self, blocks: Iterable[int]) -> List[int]:
        """Drop one reference per entry; blocks reaching refcount 0 return to
        the free list. Returns the ids actually freed.

        All-or-nothing: every id is validated (range, allocation state, and
        total references dropped IN THIS CALL vs. held) before any state
        mutates, so a bad batch — including duplicate ids within a single
        call, which the old in_free-set check waved through — leaves the
        allocator untouched.
        """
        blocks = [int(b) for b in blocks]
        for b in blocks:
            if not (0 <= b < self._num_blocks):
                raise ValueError(f"block id {b} out of range")
        for b, k in Counter(blocks).items():
            held = self._refs.get(b, 0)
            if k > held:
                raise ValueError(
                    f"double free of block {b}: {k} release(s) in one call, "
                    f"{held} reference(s) held")
        freed: List[int] = []
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
                freed.append(b)
        return freed
