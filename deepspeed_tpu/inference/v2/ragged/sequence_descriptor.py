"""Per-sequence tracking state.

Parity: ``DSSequenceDescriptor`` (reference
``inference/v2/ragged/sequence_descriptor.py``) — seen tokens, owned KV blocks and
the host-side block table row. The pending (unprocessed) prompt tail also lives
here: the scheduler drains it chunk by chunk (Dynamic SplitFuse).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import numpy as np


@dataclass
class DSSequenceDescriptor:
    uid: int
    seen_tokens: int = 0                      # tokens whose KV is in the cache
    blocks: List[int] = field(default_factory=list)
    pending: np.ndarray = field(default_factory=lambda: np.zeros((0,), np.int32))
    in_flight_tokens: int = 0                 # tokens scheduled in the current pass

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self.blocks)

    def kv_blocks_needed(self, new_tokens: int, block_size: int) -> int:
        """Extra blocks required to hold ``new_tokens`` more tokens."""
        total = self.seen_tokens + new_tokens
        needed = -(-total // block_size)      # ceil
        return max(0, needed - len(self.blocks))

    def extend_pending(self, tokens: np.ndarray) -> None:
        self.pending = np.concatenate([self.pending, np.asarray(tokens, np.int32)])

    def block_table(self, max_blocks: int) -> np.ndarray:
        bt = np.zeros((max_blocks,), np.int32)
        n = len(self.blocks)
        if n > max_blocks:
            raise ValueError(f"sequence {self.uid} needs {n} blocks > "
                             f"max_blocks_per_sequence {max_blocks}")
        bt[:n] = self.blocks
        return bt
