"""Per-sequence tracking state.

Parity: ``DSSequenceDescriptor`` (reference
``inference/v2/ragged/sequence_descriptor.py``) — seen tokens, owned KV blocks and
the host-side block table row. The pending (unprocessed) prompt tail also lives
here: the scheduler drains it chunk by chunk (Dynamic SplitFuse).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import numpy as np


@dataclass
class DSSequenceDescriptor:
    uid: int
    seen_tokens: int = 0                      # tokens whose KV is in the cache
    blocks: List[int] = field(default_factory=list)
    pending: np.ndarray = field(default_factory=lambda: np.zeros((0,), np.int32))
    in_flight_tokens: int = 0                 # tokens scheduled in the current pass
    # prefix-cache support (scheduler fills these only when a cache is wired):
    # every token the host has seen for this sequence, in order — the radix
    # tree is keyed on token blocks, so releasing KV pages to the cache needs
    # the ids that produced them. Device-generated tokens the host never saw
    # (fused decode bursts) are NOT here; pages beyond the history are freed,
    # not cached. Buffered as a part-list so the per-decode-token append is
    # O(1) (a flat-array concatenate per token is O(n^2) over a generation);
    # ``history()`` flattens on demand.
    history_parts: List[np.ndarray] = field(default_factory=list)
    history_len: int = 0
    # length of the CONTIGUOUS recorded prefix (None = all of history). The
    # fused device decode loop (scheduler.advance) appends tokens the host
    # never records; any tokens recorded AFTER such a gap sit at later
    # positions than their history index, so keying KV pages by them would
    # poison the radix tree with wrong token->page mappings. advance() seals
    # the valid prefix at the pre-gap length.
    history_valid: "int | None" = None
    cached_tokens: int = 0                    # prompt tokens served from cache
    filed_tokens: int = 0                     # tokens already eager-inserted
    # engine-weight version this sequence's KV is being computed under
    # (stamped at admission when a prefix cache is wired): a flush whose
    # stamp trails the cache's current version frees the pages instead of
    # filing old-weight KV into a post-swap tree (runtime/colocated.py)
    weight_version: int = 0

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self.blocks)

    def kv_blocks_needed(self, new_tokens: int, block_size: int) -> int:
        """Extra blocks required to hold ``new_tokens`` more tokens."""
        total = self.seen_tokens + new_tokens
        needed = -(-total // block_size)      # ceil
        return max(0, needed - len(self.blocks))

    def extend_pending(self, tokens: np.ndarray) -> None:
        self.pending = np.concatenate([self.pending, np.asarray(tokens, np.int32)])

    def record_history(self, tokens: np.ndarray) -> None:
        t = np.asarray(tokens, np.int32)
        self.history_parts.append(t)
        self.history_len += len(t)

    def history(self, n: int | None = None) -> np.ndarray:
        """The recorded token history (first ``n`` tokens). Flattens the part
        buffer in place — called per prompt completion / flush, not per
        token."""
        if len(self.history_parts) != 1:
            self.history_parts = [
                np.concatenate(self.history_parts) if self.history_parts
                else np.zeros((0,), np.int32)]
        h = self.history_parts[0]
        return h if n is None else h[:n]

    def block_table(self, max_blocks: int) -> np.ndarray:
        bt = np.zeros((max_blocks,), np.int32)
        n = len(self.blocks)
        if n > max_blocks:
            raise ValueError(f"sequence {self.uid} needs {n} blocks > "
                             f"max_blocks_per_sequence {max_blocks}")
        bt[:n] = self.blocks
        return bt
