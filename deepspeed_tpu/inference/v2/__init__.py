"""Inference v2 — the FastGen-class ragged/continuous-batching engine.

Parity: reference ``deepspeed/inference/v2`` (``engine_v2.py:30 InferenceEngineV2``,
the ``ragged/`` KV subsystem, and the Dynamic SplitFuse scheduling described in
``blogs/deepspeed-fastgen``). TPU-native design notes live in ``engine_v2.py``.
"""

from deepspeed_tpu.inference.v2.config_v2 import (CompileConfig,
                                                  PrefixCacheConfig,
                                                  PriorityClassConfig,
                                                  RaggedInferenceEngineConfig,
                                                  ServingConfig,
                                                  SpecDecodeConfig)
from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  fetch_to_host)
from deepspeed_tpu.inference.v2.pipeline import DecodePipeline
from deepspeed_tpu.inference.v2.prefix_cache import (PrefixCacheStats,
                                                     RadixPrefixCache)

# the serving frontend (inference/v2/serving/) and the speculative-decode
# subsystem (inference/v2/spec/) are imported lazily via
# engine.serving_frontend() / engine.decode_pipeline() — keeping
# `import deepspeed_tpu.inference.v2` light; the direct paths are
# `from deepspeed_tpu.inference.v2.serving import ServingFrontend` and
# `from deepspeed_tpu.inference.v2.spec import SpecDecodePipeline`.
