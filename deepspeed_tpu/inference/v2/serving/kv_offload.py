"""Preempt-by-offload: victim KV pages round-trip through pinned host buffers.

The serving frontend's admission controller (``admission.py``) relieves
KV-pool pressure by preempting low-priority victims. The cheap way to do that
is vLLM-style *swap-out*: instead of dropping the victim's KV and re-running
its whole prefill on readmit (drop-and-recompute — device compute
proportional to the sequence length), copy the victim's pages to host memory
and scatter them back when capacity returns (host bandwidth proportional to
the pages moved — on a TPU host, a PCIe/DMA copy that overlaps poorly-utilised
link time, not MXU time).

What moves: ONLY the victim's *private tail* — the maximal suffix of its
block table at allocator refcount 1 (``scheduler.private_tail``).
Prefix-cache-shared pages (radix-tree references, co-holding sequences) are
never offloaded: the victim keeps its references across the preemption, the
refcount keeps the pages allocated, and their content is stable by
construction (full shared pages are read-only; partial pages are private via
COW adoption). The refcounted ``BlockedAllocator`` therefore stays exactly
consistent across offload -> restore -> cancel: offload frees refcount-1
pages (content copied out first), restore allocates fresh ids and scatters
the bytes back in the same logical order, cancel releases the host buffers
and lets ``scheduler.flush`` settle the kept references like any other flush.

Alongside the pages, the victim's *last logits row* is parked on host
(``engine._materialize``): restore re-seeds ``engine._last_logits`` with it,
so the decode pipeline's bootstrap sample resumes the stream byte-identically
(greedy argmax over the identical row). Host staging uses the same
page-aligned pinned-buffer pool NVMe swapping stages through
(``runtime/swap_tensor/buffer_pool.py``), so steady-state preemption does
zero host allocations; ``max_bytes`` caps residency — when exhausted, the
frontend falls back to recompute-preemption per victim.

The bucketed page round trip this module rides (``engine.fetch_pages`` /
``put_pages``) doubles as the cluster's KV-TRANSFER FABRIC: the
disaggregated prefill->decode handoff (``cluster.py``/``router.py``) moves
a finished sequence's pages + bootstrap logits row between ENGINES with the
same byte-exact contract — ``engine.export_kv`` is exactly this module's
offload record shipped to a different pool, and ``engine.import_kv`` is its
restore (fresh ids, re-seeded ``_last_logits``), tested below the router in
tests/unit/test_serving_router.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.buffer_pool import SwapBufferPool


@dataclass
class _OffloadRecord:
    kept: int                       # shared-prefix blocks the seq still holds
    bufs: List[np.ndarray]          # one pooled buffer per offloaded page
    shape: Tuple[int, ...]
    dtype: "np.dtype"
    logits: np.ndarray              # last logits row (restore re-seeds it)
    nbytes: int


class KVOffloadManager:
    """Owns the offloaded-page store for one engine's serving frontend."""

    def __init__(self, engine, max_bytes: Optional[int] = None,
                 max_buffers: int = 16):
        self.engine = engine
        self.max_bytes = max_bytes
        self.pool = SwapBufferPool(max_buffers)
        self._recs: Dict[int, _OffloadRecord] = {}
        self.bytes_held = 0
        # cumulative counters (FrontendStats mirrors them into serve/frontend)
        self.offload_bytes_total = 0
        self.restore_bytes_total = 0

    @property
    def page_nbytes(self) -> int:
        # bytes_per_block IS the host page payload for every pool layout —
        # int8 pools ship packed value+scale-tile rows of exactly this size
        # (engine.page_payload_spec) — one source of size truth
        return self.engine.kv.config.bytes_per_block()

    @property
    def uids(self) -> List[int]:
        return list(self._recs)

    def pages_held(self, uid: int) -> int:
        return len(self._recs[uid].bufs)

    def can_offload(self, n_pages: int) -> bool:
        """Would ``n_pages`` more pages fit under ``max_bytes``? The frontend
        checks this BEFORE preempting, and falls back to recompute-preemption
        for the victim when host capacity is exhausted."""
        if self.max_bytes is None:
            return True
        return self.bytes_held + n_pages * self.page_nbytes <= self.max_bytes

    # ------------------------------------------------------------------ #

    def offload(self, uid: int, kept: int, tail: List[int]) -> int:
        """Offload ``tail`` (uid's private-suffix page ids, already split by
        ``scheduler.private_tail``) to pooled host buffers, free the device
        pages, and park the last logits row. Returns bytes moved. The
        sequence descriptor survives with its shared prefix; the uid must
        already be retired from the decode pipeline."""
        e = self.engine
        assert uid not in self._recs, f"uid {uid} already offloaded"
        # the last logits row first: materializing pops the device ref, so a
        # failure mid-offload never leaves a dangling ref to a donated array
        e._materialize([uid])
        logits = e._last_logits.pop(uid)
        bufs: List[np.ndarray] = []
        shape: Tuple[int, ...] = ()
        dtype = None
        nbytes = 0
        if tail:
            # ONE bucketed gather for the whole tail (engine.fetch_pages;
            # fp pools drain in one host transfer, int8 pools in two —
            # values + scale tiles are separate pool leaves — plus a host
            # repack into the packed payload) — page content copied out
            # BEFORE the ids are freed; pinned staging per page so restore
            # can release buffers back to the pool independent of tail
            # length
            pages = e.fetch_pages(tail)
            shape, dtype = pages.shape[1:], pages.dtype
            per = int(pages[0].nbytes)
            for i in range(len(tail)):
                buf = self.pool.get(per)
                np.copyto(self.pool.view(buf, shape, dtype), pages[i])
                bufs.append(buf)
                nbytes += per
        e.scheduler.drop_tail(uid, kept)
        e._last_ref.pop(uid, None)
        self._recs[uid] = _OffloadRecord(kept=kept, bufs=bufs, shape=shape,
                                         dtype=dtype, logits=logits,
                                         nbytes=nbytes)
        self.bytes_held += nbytes
        self.offload_bytes_total += nbytes
        return nbytes

    def restore(self, uid: int) -> int:
        """Scatter the offloaded pages back into fresh pool blocks (appended
        to the block table in the original logical order), release the host
        buffers, and re-seed the last-logits row. Returns bytes moved. The
        caller readmits the uid to the decode pipeline after."""
        e = self.engine
        rec = self._recs.pop(uid)
        ids = e.scheduler.grow_tail(uid, len(rec.bufs))
        if ids:
            # ONE bucketed scatter for the whole tail, original logical order
            e.put_pages(np.stack([self.pool.view(b, rec.shape, rec.dtype)
                                  for b in rec.bufs]), ids)
            for buf in rec.bufs:
                self.pool.put(buf)
        e._last_logits[uid] = rec.logits
        self.bytes_held -= rec.nbytes
        self.restore_bytes_total += rec.nbytes
        return rec.nbytes

    def salvageable(self, uid: int) -> bool:
        """Can ``uid``'s offload record seed a CROSS-REPLICA import? Only
        when the record covers the sequence's ENTIRE logical KV
        (``kept == 0`` — no shared-prefix pages were left behind on the
        now-dead device) is the pinned-host copy a complete handoff
        payload; a partial record forces re-prefill instead."""
        rec = self._recs.get(uid)
        return rec is not None and rec.kept == 0 and bool(rec.bufs)

    def export_record(self, uid: int) -> Tuple[np.ndarray, np.ndarray, int]:
        """Failover SALVAGE (serving/health.py): turn ``uid``'s offload
        record into the ``(pages, logits, nbytes)`` payload
        ``submit_handoff`` ships — the pages this engine's crash stranded
        in pinned host buffers become a survivor's ``import_kv`` input
        instead of being recomputed. Copies the pages out, releases the
        pooled buffers, and drops the record (the dead replica's device
        pages are unreachable either way)."""
        assert self.salvageable(uid), f"uid {uid} is not salvageable"
        rec = self._recs[uid]
        pages = np.stack([self.pool.view(b, rec.shape, rec.dtype)
                          for b in rec.bufs])      # stack copies out
        logits, nbytes = rec.logits, rec.nbytes
        self.drop(uid)
        return pages, logits, nbytes

    def drop(self, uid: int) -> None:
        """Cancel-while-offloaded: release the host buffers; the caller
        flushes the sequence (its kept shared-prefix references settle
        through ``scheduler.flush`` like any other flush)."""
        rec = self._recs.pop(uid)
        for buf in rec.bufs:
            self.pool.put(buf)
        self.bytes_held -= rec.nbytes

    def close(self) -> None:
        for uid in list(self._recs):
            self.drop(uid)
