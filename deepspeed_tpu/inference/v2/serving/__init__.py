"""The SLO-aware serving frontend — PAPER.md layer 6 (MII/FastGen) over
``InferenceEngineV2``.

Four modules:

- ``frontend.py`` — ``ServingFrontend``: persistent engine thread driving
  iteration-level continuous batching over ``engine.decode_pipeline``;
  asyncio-facing ``submit() -> token stream``; cancellation at every
  lifecycle stage.
- ``admission.py`` — multi-tenant admission with priority classes: a
  queue-delay + prefill-cost model decides admit / hold / shed per class
  SLO, and plans preemption under KV-pool pressure.
- ``kv_offload.py`` — preempt-by-offload: victims' private KV pages
  round-trip through pinned host buffers (vLLM swap-out, not
  drop-and-recompute), byte-identical on restore.
- ``loadgen.py`` — Poisson open-loop load generator + goodput-under-SLO
  scoring (``serving_bench.py --frontend`` gates on it).

docs/SERVING.md "Frontend" walks the design; ``serve/frontend/*`` counters
and ``serve/req/*`` trace lanes make it observable.
"""

from deepspeed_tpu.inference.v2.serving.admission import (AdmissionController,
                                                          CostModel)
from deepspeed_tpu.inference.v2.serving.frontend import (RequestHandle,
                                                         ServingFrontend)
from deepspeed_tpu.inference.v2.serving.kv_offload import KVOffloadManager
from deepspeed_tpu.inference.v2.serving.loadgen import (Arrival,
                                                        PoissonLoadGen,
                                                        WorkloadComponent,
                                                        goodput_report,
                                                        replay, slo_met)
