"""The SLO-aware serving stack — PAPER.md layer 6 (MII/FastGen) over
``InferenceEngineV2``, from one frontend to an N-replica cluster.

Seven modules:

- ``frontend.py`` — ``ServingFrontend``: persistent engine thread driving
  iteration-level continuous batching over ``engine.decode_pipeline``;
  asyncio-facing ``submit() -> token stream``; cancellation at every
  lifecycle stage; cross-replica handoff adoption (``submit_handoff``).
- ``admission.py`` — multi-tenant admission with priority classes: a
  queue-delay + prefill-cost model decides admit / hold / shed per class
  SLO, and plans preemption under KV-pool pressure; its per-class
  queue-delay EMAs are the router's federation signal.
- ``kv_offload.py`` — preempt-by-offload: victims' private KV pages
  round-trip through pinned host buffers (vLLM swap-out, not
  drop-and-recompute), byte-identical on restore; the same bucketed page
  path is the cluster's cross-engine KV fabric.
- ``loadgen.py`` — Poisson open-loop load generator (seed-deterministic,
  shared-prefix mixture components) + goodput-under-SLO scoring.
- ``cluster.py`` — ``ServingCluster``: N data-parallel replicas (uniform
  page fabric, replica-labelled monitor surfaces) + ``PrefillWorker``
  (dedicated SplitFuse prefill under disaggregation).
- ``router.py`` — ``ServingRouter``: cache-aware routing over a shared
  radix-prefix chain index, federated SLO admission, disaggregated
  prefill->decode handoff.
- ``health.py`` — ``HealthMonitor``: replica failure detection (liveness +
  decode-progress stall deadlines), request failover with KV salvage over
  the page fabric, self-healing rejoin with off-hot-path re-warm.

docs/SERVING.md ("Frontend", "Multi-replica & disaggregation") walks the
design; ``serve/frontend/*``, ``serve/router/*`` counters and
``serve/req/*``, ``serve/router`` trace lanes make it observable.
"""

from deepspeed_tpu.inference.v2.serving.admission import (AdmissionController,
                                                          CostModel)
from deepspeed_tpu.inference.v2.serving.cluster import (PrefillWorker,
                                                        Replica,
                                                        ServingCluster)
from deepspeed_tpu.inference.v2.serving.frontend import (RequestHandle,
                                                         ServingFrontend)
from deepspeed_tpu.inference.v2.serving.health import (DOWN, DRAINING,
                                                       HEALTHY, REJOINING,
                                                       SUSPECT,
                                                       HealthMonitor)
from deepspeed_tpu.inference.v2.serving.kv_offload import KVOffloadManager
from deepspeed_tpu.inference.v2.serving.loadgen import (Arrival,
                                                        PoissonLoadGen,
                                                        WorkloadComponent,
                                                        goodput_report,
                                                        replay, slo_met)
from deepspeed_tpu.inference.v2.serving.router import (ClusterPrefixIndex,
                                                       ServingRouter)
