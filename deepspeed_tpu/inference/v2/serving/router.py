"""Multi-replica serving router: cache-aware routing, federated SLO
admission, disaggregated prefill/decode (docs/SERVING.md "Multi-replica &
disaggregation").

PRs 8-9 made ONE engine fast and SLO-aware; this layer makes N of them one
service, so capacity comes from adding replicas instead of inflating one
batch. A :class:`ServingRouter` fronts a :class:`ServingCluster` with the
``ServingFrontend.submit`` signature — ``submit(prompt, priority,
max_new_tokens) -> RequestHandle`` — and the handle's stream/cancel/result
semantics pass through UNCHANGED whichever replica serves it.

Three mechanisms (``config_v2.RouterConfig``):

- **Cache-aware routing** (the SGLang-RadixAttention trick at cluster
  scope): a :class:`ClusterPrefixIndex` — chain hashes of token-block paths,
  fed by per-replica insert/evict deltas from ``prefix_cache.py`` — answers
  "which replica already computed this prompt's prefix". Placement maximises
  ``cached_tokens - balance * outstanding``: sticky enough that one replica
  amortises a shared system prompt across every request carrying it, with
  the ``balance`` knob trading stickiness against load spread. The index is
  a HINT: a stale entry (evicted since the last delta) costs a mis-route,
  never correctness — the replica's own ``match`` decides what attaches.

- **Federated admission**: each replica's ``AdmissionController`` already
  keeps the class's queue-delay EMA and a measured prefill/slice cost model;
  the router reads them ALL, skips replicas whose predicted TTFT for this
  request already busts the class SLO (a hot replica sheds load to a cold
  one by never receiving it), and sheds AT THE ROUTER — before any prefill
  burns device time — when every candidate is hot.

- **Disaggregated prefill/decode** (``topology: "disaggregated"``):
  dedicated prefill replicas run SplitFuse passes (``cluster.PrefillWorker``)
  and hand each finished sequence to a decode replica over the KV page
  fabric — ``engine.export_kv`` (one bucketed page gather + the bootstrap
  logits row, the exact record preempt-offload parks) into
  ``engine.import_kv`` on the decode engine (fresh pool ids, byte-exact
  content, ``_last_logits`` re-seeded like a preemption restore). Decode
  replicas then never run a prefill pass, eliminating prefill interference
  on decode TBT — the gate ``serving_bench.py --router`` measures.

Observability: ``serve/router/*`` counters (``monitor/serving.RouterStats``
— placement, cache hits, rebalances, handoff traffic, per-class CLUSTER
goodput rollups) plus ``serve/router/{route,handoff}`` trace spans on a
``serve/router`` lane; replicas' own surfaces carry their replica label.

Fault tolerance (``RouterConfig.health``; docs/SERVING.md "Failure
semantics"): a :class:`~deepspeed_tpu.inference.v2.serving.health.
HealthMonitor` walks replicas through ``healthy -> suspect -> down ->
draining -> rejoining`` — engine-thread/worker liveness plus a decode-step
progress heartbeat with a stall deadline — fences a failed replica,
migrates its in-flight requests to survivors (salvaging preempt-offloaded
KV through the page fabric, re-prefilling sealed histories otherwise), and
self-heals by rebuilding + re-warming a frontend on the recovered engine.
Routing never places a request on a non-``healthy`` replica, and a closed
or crashed replica's prefix-index entries are evicted so stale cache
affinity cannot keep attracting routes.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.inference.v2.config_v2 import RouterConfig
from deepspeed_tpu.inference.v2.prefix_cache import ROOT_CHAIN, chain_hash
from deepspeed_tpu.inference.v2.serving.admission import CostModel
from deepspeed_tpu.inference.v2.serving.cluster import (PrefillWorker,
                                                        Replica,
                                                        ServingCluster)
from deepspeed_tpu.inference.v2.serving.frontend import _DONE, RequestHandle
from deepspeed_tpu.inference.v2.serving.health import HEALTHY, HealthMonitor
from deepspeed_tpu.monitor.serving import RouterStats
from deepspeed_tpu.monitor.trace import tracer as _tracer
from deepspeed_tpu.utils.threads import make_lock


class ClusterPrefixIndex:
    """Shared radix-prefix membership index over token-block chain hashes.

    One dict: ``chain_hash -> {replica names holding that cached path}``,
    maintained from each replica's ``RadixPrefixCache.add_listener`` deltas
    (insert/evict of full-block nodes; the listener replays existing state
    at registration, so a router built over warm replicas starts
    consistent). ``match`` walks a prompt's blocks with the SAME chain
    function the trees use, so membership == path existence — no tree is
    ever locked or walked across threads. O(prompt blocks) per query,
    O(cached blocks x replicas) memory, one lock (deltas are engine-thread
    writes; matches are client-thread reads)."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._lock = make_lock("serving.router.prefix_index")
        self._chains: Dict[int, set] = {}

    def listener(self, replica: str):
        """The delta sink to register on one replica's prefix cache."""
        def on_delta(op: str, chain: int) -> None:
            self.apply(replica, op, chain)
        return on_delta

    def apply(self, replica: str, op: str, chain: int) -> None:
        with self._lock:
            if op == "insert":
                self._chains.setdefault(chain, set()).add(replica)
            else:
                holders = self._chains.get(chain)
                if holders is not None:
                    holders.discard(replica)
                    if not holders:
                        del self._chains[chain]

    @property
    def chains(self) -> int:
        with self._lock:
            return len(self._chains)

    def drop_replica(self, replica: str) -> int:
        """Evict EVERY chain entry held by ``replica`` — a closed or failed
        replica's cached paths must stop attracting routes immediately (its
        delta feed is gone, so the entries would otherwise stay stale
        forever). Returns entries dropped."""
        with self._lock:
            dropped = 0
            for chain in list(self._chains):
                holders = self._chains[chain]
                if replica in holders:
                    holders.discard(replica)
                    dropped += 1
                    if not holders:
                        del self._chains[chain]
            return dropped

    def holders(self, replica: str) -> int:
        """Entries currently attributed to ``replica`` (tests/stats)."""
        with self._lock:
            return sum(1 for h in self._chains.values() if replica in h)

    def match(self, tokens: Sequence[int]) -> Dict[str, int]:
        """Per-replica longest cached match, in TOKENS (whole blocks only,
        capped at ``len(tokens) - 1`` exactly like the trees' ``match``).
        Replicas with no match are absent from the result."""
        tokens = [int(t) for t in np.asarray(tokens, np.int64).reshape(-1)]
        bs = self.block_size
        limit = len(tokens) - 1
        best: Dict[str, int] = {}
        chain = ROOT_CHAIN
        i = 0
        with self._lock:
            while i + bs <= limit:
                chain = chain_hash(chain, tuple(tokens[i:i + bs]))
                holders = self._chains.get(chain)
                if not holders:
                    break
                i += bs
                for name in holders:
                    best[name] = i
        return best


class ServingRouter:

    def __init__(self, cluster: ServingCluster, config=None):
        cfg = config if config is not None else RouterConfig()
        if isinstance(cfg, dict):
            cfg = RouterConfig(**cfg)
        self.cluster = cluster
        self.config = cfg
        if cfg.topology == "disaggregated":
            if not cluster.prefill_replicas or not cluster.decode_replicas:
                raise ValueError(
                    "disaggregated topology needs >= 1 'prefill' and >= 1 "
                    "'decode' replica; got roles "
                    f"{[r.role for r in cluster.replicas]}")
            self._targets = cluster.prefill_replicas
            self._decode = cluster.decode_replicas + cluster.serve_replicas
        else:
            if cluster.prefill_replicas or cluster.decode_replicas:
                raise ValueError(
                    "colocated topology takes only 'serve' replicas; got "
                    f"roles {[r.role for r in cluster.replicas]}")
            self._targets = cluster.serve_replicas
            self._decode = cluster.serve_replicas
        if not self._targets or not self._decode:
            raise ValueError("router needs at least one routable replica")
        # all frontends share one ServingConfig (cluster builds them so);
        # class lookups and SLO bounds read from the first
        self._serving_cfg = self.cluster.frontends[0].frontend.config
        self.stats = RouterStats([r.name for r in cluster.replicas],
                                 [c.name for c in self._serving_cfg.classes])
        for r in cluster.frontends:
            self.stats.register_frontend(r.frontend.stats)
        # the shared prefix index, fed by every routable replica's radix
        # tree (replicas without a prefix cache simply never match)
        self.index = ClusterPrefixIndex(cluster.block_size)
        self._listeners: List[Tuple[str, object, object]] = []
        for r in self._targets:
            self._register_index_listener(r)
        # a replica frontend closed OUT OF BAND (not through router.close)
        # must stop attracting routes and drop its index entries — the
        # listener-lifecycle fix the close-then-route regression test pins
        for r in cluster.frontends:
            self._register_close_listener(r)
        # prefill-replica cost models (fed by PrefillWorker measurements —
        # prefill replicas have no frontend, so federation reads these)
        self._prefill_cost: Dict[str, CostModel] = {
            r.name: CostModel() for r in cluster.prefill_replicas}
        self._workers: Dict[str, PrefillWorker] = {
            r.name: PrefillWorker(r, self) for r in cluster.prefill_replicas}
        self._lock = make_lock("serving.router.state")  # stats + rr + inflight
        self._rr = 0
        self._inflight = 0                 # requests held by prefill workers
        self._uids = itertools.count(1 << 44)   # never collides with the
        # frontends' per-replica (1 << 24)-spaced uid bases (cluster.py):
        # the cluster would need 2^20 frontend lifetimes to reach this
        self._closed = False
        # replica failure detection / failover / self-healing
        # (serving/health.py; no thread unless cfg.health.enabled)
        self.health = HealthMonitor(self, cfg.health)
        if self.health.enabled:
            # managed frontends keep streams OPEN across a loop crash — the
            # monitor migrates them instead of closing them
            for r in cluster.frontends:
                r.frontend._managed = True

    def _register_index_listener(self, r: Replica) -> None:
        if r.engine.prefix_cache is not None:
            fn = self.index.listener(r.name)
            r.engine.prefix_cache.add_listener(fn)
            self._listeners.append((r.name, r.engine.prefix_cache, fn))

    def _register_close_listener(self, r: Replica) -> None:
        r.frontend.add_close_listener(
            lambda name=r.name: self._replica_closed(name))

    def _replica_closed(self, name: str) -> None:
        """A replica frontend is closing (router teardown, an out-of-band
        close, or a failover fence->close): evict its prefix-index entries
        and stop feeding them — routing checks keep it out of rotation."""
        self._drop_replica_routing(name)

    def _drop_replica_routing(self, name: str) -> None:
        self.index.drop_replica(name)
        kept = []
        for rec in self._listeners:
            if rec[0] == name:
                rec[1].remove_listener(rec[2])
            else:
                kept.append(rec)
        self._listeners = kept

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    def start(self) -> "ServingRouter":
        self.cluster.start()
        for w in self._workers.values():
            w.start()
        self.health.start()
        return self

    def __enter__(self) -> "ServingRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until every routed request reaches a terminal state on its
        replica. A replica whose engine thread (or prefill worker) died
        raises HERE, NAMED — a dead replica must not look like a slow
        drain. True = drained; False = timed out."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self.check_health()
            busy = self._inflight > 0 or any(
                r.frontend._inflight > 0 for r in self.cluster.frontends)
            if not busy:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)

    def check_health(self) -> None:
        """Without a health monitor: raise, naming the replica, if any
        engine thread or prefill worker has died (the PR 10 contract — a
        dead replica must not look like a slow drain). With monitoring
        enabled, failures are HANDLED — detected, fenced, migrated — so
        this only polls the monitor and re-raises if the monitor itself
        died."""
        if self.health.enabled:
            self.health.check()
            return
        for r in self.cluster.frontends:
            if r.frontend._loop_exc is not None:
                raise RuntimeError(
                    f"replica {r.name!r} serving loop died") \
                    from r.frontend._loop_exc
        for name, w in self._workers.items():
            if w.exc is not None:
                raise RuntimeError(
                    f"replica {name!r} prefill worker died") from w.exc

    def close(self) -> None:
        """Stop the health monitor and prefill workers, close every replica
        frontend (cancelling whatever is in flight), and deregister the
        prefix-index listeners. Idempotent; a died replica re-raises ONCE,
        named, after the whole cluster is torn down (a failure the health
        monitor already handled does not re-raise)."""
        if self._closed:
            return
        self._closed = True
        self.health.close()
        for w in self._workers.values():
            w.close()
        for _name, cache, fn in self._listeners:
            cache.remove_listener(fn)
        self._listeners = []
        self.cluster.close(ignore=self.health.handled_replicas())

    def rejoin(self, name: str) -> bool:
        """Re-admit a drained replica to routing (``serving/health.py``):
        reset its engine, rebuild its frontend in a fresh uid space, re-warm
        the program grids off the hot path, replay its radix tree into the
        prefix index. True once back in rotation."""
        return self.health.rejoin(name)

    # ------------------------------------------------------------------ #
    # client surface
    # ------------------------------------------------------------------ #

    def submit(self, prompt: Sequence[int], priority: Optional[str] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               adapter: Optional[str] = None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Route one request and submit it; returns the serving replica's
        stream handle (identical semantics to ``ServingFrontend.submit``,
        including the adapter/tenant multi-tenant identity). May return an
        already-SHED handle when federation finds every candidate replica
        SLO-hopeless for this class. Adapter-bound requests route only to
        replicas with the adapter REGISTERED, and a replica with its pages
        already RESIDENT scores like a cache hit — the fleet converges on
        tenant-sticky placement without any explicit pinning."""
        if self._closed:
            raise RuntimeError("router is closed")
        cls = self._serving_cfg.class_for(priority,
                                          tenant if tenant is not None
                                          else adapter)
        if adapter is not None and self.config.topology != "colocated":
            raise NotImplementedError(
                "LoRA adapters over disaggregated prefill/decode are not "
                "wired (the handoff record carries no adapter binding); "
                "run topology='colocated'")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        t0 = time.perf_counter()
        matches = self.index.match(prompt) \
            if self.config.policy == "cache_aware" else {}
        excluded: List[str] = []
        while True:
            target, matched, rebalanced = self._choose(prompt, cls, matches,
                                                       exclude=excluded,
                                                       adapter=adapter)
            t1 = time.perf_counter()
            if target is None:
                # shed at the router: every candidate's predicted TTFT
                # busts the class SLO (federation), or no replica is
                # routable at all — reject before any prefill burns on it
                req = RequestHandle(next(self._uids), prompt, cls,
                                    int(max_new_tokens), eos_token_id, t0)
                if not getattr(self._serving_cfg, "attribution", True):
                    req._ledger = None
                with self._lock:
                    self.stats.router_sheds[cls.name] += 1
                self._finalize_external(req, "shed")
                if _tracer.enabled:
                    _tracer.add("serve/router/route", t0, t1,
                                lane="serve/router", outcome="shed",
                                uid=req.uid, trace_id=req.trace_id,
                                cls=cls.name)
                return req
            if self.config.topology == "colocated":
                # submit FIRST: a validation reject must not count as routed
                try:
                    handle = target.frontend.submit(
                        prompt, priority=priority,
                        max_new_tokens=max_new_tokens,
                        eos_token_id=eos_token_id,
                        adapter=adapter, tenant=tenant)
                except RuntimeError:
                    # the replica went down between _choose and submit (a
                    # failure race, not a validation reject — those raise
                    # ValueError): re-route among the survivors
                    excluded.append(target.name)
                    continue
            else:
                try:
                    handle = self._submit_disaggregated(target, prompt, cls,
                                                        int(max_new_tokens),
                                                        eos_token_id, t0)
                except RuntimeError:
                    # the prefill worker was fenced between _choose and
                    # submit (validation rejects raise ValueError and
                    # propagate): re-route among the survivors
                    excluded.append(target.name)
                    continue
            break
        with self._lock:
            self.stats.routed[target.name] += 1
            if matched:
                self.stats.cache_hit_requests += 1
                self.stats.cache_hit_blocks += matched // self.index.block_size
            if rebalanced:
                self.stats.rebalances += 1
        if _tracer.enabled:
            # the flow chain's first hop: trace_id binds this placement
            # span to every later hop of the request across lanes/threads
            _tracer.add("serve/router/route", t0, t1, lane="serve/router",
                        replica=target.name, cached_tokens=matched,
                        uid=handle.uid, trace_id=handle.trace_id,
                        cls=cls.name)
        return handle

    def write_monitor_events(self, monitor, step: int = 0) -> None:
        """Emit the aggregated ``serve/router/*`` counters plus every
        replica's labelled ``serve/frontend/<replica>/*`` counters through
        one ``monitor/`` backend (``MonitorMaster.write_events`` shape) —
        the rows stay distinguishable by construction."""
        monitor.write_events(self.stats.events(step))
        if self.health.enabled or self.health.stats.migrations:
            monitor.write_events(self.health.stats.events(step))
        for r in self.cluster.frontends:
            r.frontend.write_monitor_events(monitor, step)

    # ------------------------------------------------------------------ #
    # placement
    # ------------------------------------------------------------------ #

    def _routable(self, r: Replica) -> bool:
        """May a NEW placement land on this replica? Closed/fenced/crashed
        frontends (and dead prefill workers) are out even without health
        monitoring — a stale prefix-index hit or round-robin turn must
        never route onto a corpse; with monitoring, only ``healthy``
        replicas (not suspect/down/draining/rejoining) take traffic."""
        if r.role == "prefill":
            w = self._workers[r.name]
            if w.exc is not None or w.fenced:
                return False
        else:
            fe = r.frontend
            if fe is None or fe._closed or fe._fenced \
                    or fe._loop_exc is not None:
                return False
        if self.health.enabled:
            return self.health.state(r.name) == HEALTHY
        return True

    def _load(self, r: Replica) -> int:
        if r.role == "prefill":
            return self._workers[r.name].queued \
                + len(r.engine.scheduler.seqs)
        # _inflight, not outstanding: submit bumps it SYNCHRONOUSLY, so a
        # burst of submits sees its own earlier placements — outstanding is
        # filed by the engine thread and lags by one control-drain
        return r.frontend._inflight

    def _hot(self, r: Replica, cls, prompt_len: int) -> bool:
        """Federation signal: would this replica's measured queue delay +
        prefill cost already bust the class's TTFT SLO? (0 until the
        replica's cost model warms — mirrors the local shed rule.) A
        prefill replica's queue delay is its worker backlog: each queued
        request prefills ahead of this one, so the prediction scales the
        measured per-prompt cost by the queue depth — without it a
        multi-second backlog would never shed a guaranteed TTFT miss."""
        if r.role == "prefill":
            per = self._prefill_cost[r.name].predicted_ttft_s(prompt_len)
            pred = per * (1 + self._workers[r.name].queued)
        else:
            adm = r.frontend.admission
            pred = adm.queue_delay_s(cls.name) \
                + adm.cost.predicted_ttft_s(prompt_len)
        return pred * 1e3 > cls.ttft_slo_ms * self.config.shed_factor

    def _adapter_state(self, r: Replica, adapter: str) -> int:
        """0 = the replica cannot serve this adapter (LoRA disabled or the
        adapter unregistered there), 1 = registered, 2 = registered with
        pages device-RESIDENT right now (no fault-in to admit)."""
        lora = getattr(r.engine, "lora", None)
        if lora is None or adapter not in lora.names:
            return 0
        return 2 if lora.is_resident(adapter) else 1

    def _choose(self, prompt, cls, matches: Dict[str, int],
                exclude: Sequence[str] = (),
                adapter: Optional[str] = None) \
            -> Tuple[Optional[Replica], int, bool]:
        """(target, cached tokens there, rebalanced?). ``None`` target =
        shed (every candidate hot, or no routable replica at all)."""
        cands = [r for r in self._targets
                 if r.name not in exclude and self._routable(r)]
        if adapter is not None:
            cands = [r for r in cands if self._adapter_state(r, adapter)]
            if not cands:
                raise KeyError(
                    f"LoRA adapter {adapter!r} is not registered on any "
                    "routable replica — load it (module_inject."
                    "load_lora_adapter) on each engine that should serve "
                    "this tenant")
        if not cands:
            return None, 0, False
        if self.config.policy == "round_robin":
            with self._lock:
                i = self._rr
                self._rr += 1
            return cands[i % len(cands)], 0, False
        # cold-start affinity: requests whose prefix NOBODY has cached yet
        # still deterministically prefer one replica (hash of the first
        # token block), so a burst sharing a brand-new prefix warms ONE
        # tree instead of paying the prefill once per replica while the
        # index is still cold. One block's worth of score — never enough
        # to override a real cached match or a serious load gap.
        bs = self.index.block_size
        aff = cands[hash(tuple(int(t) for t in prompt[:bs])) % len(cands)]
        # adapter-residency bonus: a replica that already holds the tenant's
        # pages on device admits without a host->device fault-in — worth a
        # cached block, same scale as cold-start affinity (enough to break
        # ties toward tenant stickiness, never enough to override a real
        # prefix match or a serious load gap)
        scored = [(matches.get(r.name, 0)
                   + (bs if r is aff else 0)
                   + (bs if adapter is not None
                      and self._adapter_state(r, adapter) == 2 else 0)
                   - self.config.balance * self._load(r),
                   matches.get(r.name, 0), r) for r in cands]
        pool = scored
        if self.config.federation:
            cold = [s for s in scored
                    if not self._hot(s[2], cls, len(prompt))]
            if not cold:
                return None, 0, False
            pool = cold
        best = max(pool, key=lambda s: s[0])
        cache_best = max(scored, key=lambda s: s[1])
        rebalanced = cache_best[1] > 0 and best[2] is not cache_best[2]
        return best[2], best[1], rebalanced

    def _pick_decode(self, exclude: Sequence[str] = ()) -> Replica:
        """Least-loaded routable decode replica — the handoff destination
        (called by PrefillWorker threads; ``exclude`` carries targets a
        retry already saw fail). Raises :class:`LookupError` when no decode
        replica can take the handoff."""
        cands = [r for r in self._decode
                 if r.name not in exclude and self._routable(r)]
        if not cands:
            raise LookupError(
                "no routable decode replica"
                + (f" (excluded: {list(exclude)})" if exclude else ""))
        return min(cands, key=lambda r: r.frontend._inflight)

    # ------------------------------------------------------------------ #
    # disaggregated path
    # ------------------------------------------------------------------ #

    def _submit_disaggregated(self, target: Replica, prompt, cls,
                              max_new_tokens: int, eos_token_id,
                              arrival_t: float) -> RequestHandle:
        # the budget math ServingFrontend.submit runs — ONE home
        # (check_budget), evaluated against the WEAKEST decode replica:
        # _pick_decode may land the handoff on ANY of them, so a request
        # only enters if every destination could hold its full KV lifetime
        self._decode[0].frontend.check_budget(
            len(prompt), max_new_tokens,
            max_context=min(r.engine.config.state_manager.max_context
                            for r in self._decode),
            total_blocks=min(r.engine.allocator.total_blocks
                             for r in self._decode))
        pre_sm = target.engine.config.state_manager
        if len(prompt) > pre_sm.max_context:
            raise ValueError(
                f"prompt ({len(prompt)}) exceeds prefill replica "
                f"{target.name!r} max_context {pre_sm.max_context}")
        bs = target.engine.kv.config.block_size
        if -(-len(prompt) // bs) > target.engine.allocator.total_blocks:
            raise ValueError(
                f"prompt needs {-(-len(prompt) // bs)} KV blocks but the "
                f"prefill pool holds {target.engine.allocator.total_blocks}")
        req = RequestHandle(next(self._uids), prompt, cls, max_new_tokens,
                            eos_token_id, arrival_t)
        if not getattr(self._serving_cfg, "attribution", True):
            req._ledger = None
        req._router_counted = True     # in _inflight until handoff or final
        with self._lock:
            self._inflight += 1
        try:
            self._workers[target.name].submit(req)
        except RuntimeError:           # worker fenced in the race window:
            req._router_counted = False   # undo the accounting and let the
            with self._lock:              # caller re-route
                self._inflight -= 1
            raise
        return req

    # -- PrefillWorker callbacks ---------------------------------------- #

    def _note_prefill(self, replica: Replica, tokens: int,
                      secs: float) -> None:
        self._prefill_cost[replica.name].update_prefill(tokens, secs)

    def _note_handoff(self, src: Replica, dst: Replica, req,
                      nbytes: int, t0: float) -> None:
        with self._lock:
            if getattr(req, "_router_counted", False):
                req._router_counted = False
                self._inflight -= 1
            self.stats.handoffs += 1
            self.stats.handoff_bytes += nbytes
        if _tracer.enabled:
            _tracer.add("serve/router/handoff", t0, time.perf_counter(),
                        lane="serve/router", uid=req.uid,
                        trace_id=req.trace_id, src=src.name,
                        dst=dst.name, bytes=nbytes)

    def _finalize_external(self, req: RequestHandle, status: str) -> None:
        """Terminal-state a handle the router (or a prefill worker) still
        owns: close the stream and release waiters — the RequestHandle
        contract, preserved outside any frontend. A handle counted in the
        router's in-flight gauge (disaggregated submissions awaiting
        handoff) leaves it here whatever the terminal status."""
        req.status = status
        req._q.put(_DONE)
        req._finished.set()
        if getattr(req, "_router_counted", False):
            req._router_counted = False
            with self._lock:
                self._inflight -= 1
