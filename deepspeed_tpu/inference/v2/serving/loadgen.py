"""Poisson load generator + goodput-under-SLO accounting.

The "millions of users" metric (ROADMAP): a serving stack is judged not by
peak tokens/sec but by *goodput under SLO* — completed tokens/sec counted
ONLY from requests that met their class's TTFT and TBT targets, at a request
rate that saturates the KV pool. A frontend that admits everything and blows
every deadline scores zero; so does one that sheds everything. This module
provides the open-loop workload (seeded, so every preemption-policy leg of
``serving_bench.py --frontend`` replays the identical arrival sequence) and
the scoring.

Arrivals are Poisson (exponential inter-arrival gaps at ``rate``/s — the
standard open-loop serving-bench model; closed-loop clients hide queueing
delay exactly where SLOs live). Each arrival draws a mixture component
(priority class + prompt-length + generation-length choices) by weight, so
one stream carries the mixed multi-tenant traffic admission exists to
arbitrate.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclass
class WorkloadComponent:
    """One mixture component: requests of priority class ``cls`` arriving
    with probability ``weight`` (normalised over the mix), drawing prompt
    and generation lengths uniformly from the given choices.

    ``prefix_len > 0`` gives the component a SHARED PREFIX: one token block
    of that length is drawn per component (seed-keyed, before any arrival —
    see ``arrivals``) and prepended to every prompt the component emits,
    ``prompt_lens`` then sizing only the unique tail. This is the traffic
    shape cache-aware routing exists for (shared system prompts / few-shot
    templates), and the ``serving_bench.py --router`` workload.

    ``adapter_id`` names the LoRA adapter (tenant identity) the component's
    requests decode under: a string pins every arrival to that tenant; a
    sequence of names draws one per arrival (seed-keyed, uniform) — the
    multi-tenant churn ``serving_bench.py --lora`` drives. ``None`` (the
    default) serves the base model AND consumes no randomness, the same
    pin discipline as ``prefix_len``: an adapter-free mix replays its
    pre-LoRA arrival stream byte-for-byte."""
    cls: str
    weight: float
    prompt_lens: Sequence[int]
    gen_lens: Sequence[int]
    prefix_len: int = 0
    adapter_id: object = None


@dataclass
class Arrival:
    t: float                      # seconds from stream start
    cls: str
    prompt: np.ndarray
    max_new_tokens: int
    adapter: Optional[str] = None   # LoRA adapter (tenant), None = base


class PoissonLoadGen:

    def __init__(self, rate: float, mix: Sequence[WorkloadComponent],
                 vocab: int, seed: int = 0):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.mix = [c if isinstance(c, WorkloadComponent)
                    else WorkloadComponent(**c) for c in mix]
        self.vocab = int(vocab)
        self.seed = int(seed)

    def arrivals(self, n: Optional[int] = None,
                 duration: Optional[float] = None) -> List[Arrival]:
        """The deterministic arrival schedule: ``n`` requests, or as many as
        land inside ``duration`` seconds (one of the two must be given).

        A pure function of ``(seed, rate, mix, vocab)`` — the per-request
        ``(class, prompt, arrival, max_new)`` stream is independent of what
        the arrivals are later scored against, so a router-vs-direct
        byte-equality gate replays the EXACT same workload on both sides
        (tests/unit/test_serving_router.py pins this). Shared prefixes are
        drawn first, in mix order, only for components that declare one —
        an all-``prefix_len=0`` mix therefore reproduces the pre-prefix
        stream for a given seed byte-for-byte."""
        if (n is None) == (duration is None):
            raise ValueError("pass exactly one of n / duration")
        rng = np.random.RandomState(self.seed)
        prefixes = [rng.randint(0, self.vocab,
                                size=(c.prefix_len,)).astype(np.int32)
                    if c.prefix_len > 0 else None for c in self.mix]
        w = np.asarray([c.weight for c in self.mix], np.float64)
        w = w / w.sum()
        out: List[Arrival] = []
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / self.rate))
            if duration is not None and t > duration:
                break
            if n is not None and len(out) >= n:
                break
            ci = int(rng.choice(len(self.mix), p=w))
            comp = self.mix[ci]
            plen = int(comp.prompt_lens[int(rng.randint(len(comp.prompt_lens)))])
            glen = int(comp.gen_lens[int(rng.randint(len(comp.gen_lens)))])
            prompt = rng.randint(0, self.vocab, size=(plen,)).astype(np.int32)
            if prefixes[ci] is not None:
                prompt = np.concatenate([prefixes[ci], prompt])
            # tenant draw LAST, and only for components that declare
            # adapters (a fixed string consumes no randomness either) —
            # adapter-free mixes keep their exact pre-LoRA RNG stream
            ad = comp.adapter_id
            if ad is not None and not isinstance(ad, str):
                ad = str(ad[int(rng.randint(len(ad)))])
            out.append(Arrival(t=t, cls=comp.cls, prompt=prompt,
                               max_new_tokens=glen, adapter=ad))
        return out


def replay(frontend, arrivals: Sequence[Arrival], speed: float = 1.0) -> List:
    """Open-loop replay: submit each arrival at its scheduled wall-clock
    time (divided by ``speed``) against a RUNNING frontend — or anything
    with its ``submit`` signature, e.g. a ``ServingRouter`` — returning the
    request handles in arrival order. Late submissions (the loop fell
    behind) fire immediately — open-loop means the generator never waits
    for the server."""
    handles = []
    t0 = time.perf_counter()
    for a in arrivals:
        delay = a.t / speed - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        # adapter-free arrivals call the exact pre-LoRA signature: replay
        # targets only need submit(adapter=) when the mix names tenants
        kw = {} if a.adapter is None else {"adapter": a.adapter}
        handles.append(frontend.submit(a.prompt, priority=a.cls,
                                       max_new_tokens=a.max_new_tokens, **kw))
    return handles


def slo_met(handle) -> bool:
    """Did a FINISHED request meet its class SLOs? TTFT against
    ``ttft_slo_ms``; p95 of its token gaps against ``tbt_slo_ms`` (a single
    preemption blows the gap budget unless the restore was fast — exactly
    the pressure the bench compares preemption policies under)."""
    if handle.status != "finished" or handle.ttft_ms is None:
        return False
    if handle.ttft_ms > handle.cls.ttft_slo_ms:
        return False
    if handle.tbt_ms:
        p95 = float(np.percentile(np.asarray(handle.tbt_ms, np.float64), 95))
        if p95 > handle.cls.tbt_slo_ms:
            return False
    return True


def goodput_report(handles: Sequence, wall_s: float) -> Dict:
    """Score one replay: goodput (SLO-met completed tokens/s), raw
    throughput, and per-class completion/SLO/latency percentiles."""
    per_cls: Dict[str, Dict] = {}
    good_tokens = 0
    total_tokens = 0
    for h in handles:
        c = per_cls.setdefault(h.cls.name, {
            "submitted": 0, "finished": 0, "shed": 0, "cancelled": 0,
            "slo_met": 0, "tokens": 0, "ttft_ms": [], "tbt_ms": []})
        c["submitted"] += 1
        total_tokens += len(h.tokens)
        if h.status == "finished":
            c["finished"] += 1
            c["tokens"] += len(h.tokens)
            if h.ttft_ms is not None:
                c["ttft_ms"].append(h.ttft_ms)
            c["tbt_ms"].extend(h.tbt_ms)
            if slo_met(h):
                c["slo_met"] += 1
                good_tokens += len(h.tokens)
        elif h.status == "shed":
            c["shed"] += 1
        elif h.status == "cancelled":
            c["cancelled"] += 1

    def pct(xs, q):
        return round(float(np.percentile(np.asarray(xs, np.float64), q)), 2) \
            if xs else None

    classes = {}
    for name, c in per_cls.items():
        classes[name] = {
            "submitted": c["submitted"], "finished": c["finished"],
            "shed": c["shed"], "cancelled": c["cancelled"],
            "slo_met": c["slo_met"],
            "ttft_p50_ms": pct(c["ttft_ms"], 50),
            "ttft_p95_ms": pct(c["ttft_ms"], 95),
            "tbt_p50_ms": pct(c["tbt_ms"], 50),
            "tbt_p95_ms": pct(c["tbt_ms"], 95),
        }
    return {
        "wall_s": round(wall_s, 2),
        "goodput_tokens_per_sec": round(good_tokens / wall_s, 1),
        "total_tokens_per_sec": round(total_tokens / wall_s, 1),
        "good_tokens": good_tokens,
        "classes": classes,
    }
