"""Multi-tenant admission: priority classes, SLO cost model, preemption plan.

The frontend's engine thread calls :meth:`AdmissionController.plan` once per
iteration (between ``DecodePipeline.run`` bursts). The controller owns the
pending queues — one FIFO per priority class, strict priority between
classes — and turns queue state + pool capacity into an ordered action list
the frontend executes verbatim:

    [("shed", req), ("restore", req), ("preempt", victim), ("admit", req)]

Decisions (Orca/FastGen-style iteration-level scheduling, vLLM-style
preemption):

- **shed**: a queued request whose *best-case* TTFT already misses its class
  SLO — ``elapsed + predicted_prefill + one_slice > ttft_slo * shed_factor``
  — is rejected now, before its prefill burns device time on a guaranteed
  miss (the load-shedding half of goodput-under-SLO). Predictions come from
  :class:`CostModel`, an EMA over *measured* prefill throughput and slice
  wall time; until the first measurement the model predicts 0 and nothing
  is shed.
- **restore**: preempted requests re-enter — highest class first, oldest
  preemption first — whenever spare capacity (beyond the live set's
  next-slice funding) covers their pages. Restores outrank new admissions,
  so a victim is never starved by the class that preempted it.
- **admit**: strict ``(priority desc, FIFO)`` order, head-of-line blocking
  within the whole queue (no bypass — a lower class never jumps a held
  higher-class request). A request is admitted when the pool funds its
  prompt plus near-term decode growth and a decode row is free; under
  ``preemption: "none"`` the funding test is the request's FULL
  ``prompt + max_new_tokens`` KV lifetime (conservative reject-only
  admission — nothing can be evicted later, so nothing optimistic is
  admitted).
- **preempt**: when an admit (or the live set's own next-slice funding)
  doesn't fit, victims are chosen strictly-lower-priority-first, newest
  admission first within a class (LIFO — preserves older requests'
  progress), and only for a strictly higher-priority requester. The
  frontend offloads each victim's private KV tail (``kv_offload.py``),
  falling back to recompute when host capacity is exhausted.

Multi-tenant LoRA joins the same plan: a request bound to an adapter
(``RequestHandle.adapter``) admits/restores only when its adapter is
fundable in the ADAPTER page pool too (``LoraAdapterRegistry.can_admit`` —
resident, or free + idle-evictable pages cover its rank), and adapter pool
pressure preempts strictly-lower-priority binding holders exactly like KV
pressure preempts block holders. The frontend then acquires the binding in
the admission round — the fault-in (host -> device page scatter) lands
there, never inside a decode slice, so a cold adapter can't stall a hot
tenant's token cadence (docs/SERVING.md "Multi-tenant LoRA").

Everything here is host metadata — the controller never touches a device
array; block math rides the scheduler's refcounted accounting
(``scheduler.available_blocks`` / ``blocks_needed``).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from deepspeed_tpu.inference.v2.config_v2 import ServingConfig


class CostModel:
    """EMA queue-delay + prefill-cost model behind admit/hold/shed.

    Two measured rates, updated by the frontend from wall-clock it already
    takes: ``prefill_tok_s`` (prompt tokens through scheduler passes per
    second) and ``slice_s`` (one decode-slice ``run()`` burst). Predictions
    are conservative best-case: a request admitted *now* sees its own
    prefill plus one slice boundary before its first token drains."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self.prefill_tok_s: Optional[float] = None
        self.slice_s: Optional[float] = None

    def _ema(self, cur: Optional[float], obs: float) -> float:
        return obs if cur is None else (1 - self.alpha) * cur + self.alpha * obs

    def update_prefill(self, tokens: int, secs: float) -> None:
        if tokens > 0 and secs > 0:
            self.prefill_tok_s = self._ema(self.prefill_tok_s, tokens / secs)

    def update_decode(self, secs: float) -> None:
        if secs > 0:
            self.slice_s = self._ema(self.slice_s, secs)

    def predicted_ttft_s(self, prompt_tokens: int) -> float:
        p = prompt_tokens / self.prefill_tok_s if self.prefill_tok_s else 0.0
        return p + (self.slice_s or 0.0)


Action = Tuple[str, object]     # ("shed"|"restore"|"preempt"|"admit", req)


class AdmissionController:

    def __init__(self, engine, config: ServingConfig):
        self.engine = engine
        self.config = config
        self.cost = CostModel()
        # one FIFO per class, iterated in strict priority order
        self._order = sorted(config.classes, key=lambda c: -c.priority)
        self._queues: Dict[str, Deque] = {c.name: deque() for c in self._order}
        # per-class queue-delay EMA (arrival -> admit), updated as plans
        # admit: the federation signal a multi-replica ServingRouter
        # aggregates across replicas — a hot replica's rising delay steers
        # new arrivals to a cold one before the local shed rule ever fires
        self._qdelay: Dict[str, Optional[float]] = {
            c.name: None for c in self._order}

    # ------------------------------------------------------------------ #
    # queue management (engine thread only)
    # ------------------------------------------------------------------ #

    @property
    def queued(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def slice_tokens(self) -> int:
        """KV tokens one decode slice reserves per row (the per-run
        ``decode_batch`` reservation the funding math must match): the
        plain pipeline reserves ``decode_slice + 1``; speculative decoding
        reserves for FULL acceptance — ``decode_slice * (k + 1) + 1`` —
        with run-end rollback returning what rejection left unused. A
        frontend pinned to the plain pipeline via ``ServingConfig.spec =
        False`` funds at the plain rate even on a spec-enabled engine
        (funding at the spec rate would over-reserve ~(k+1)x and preempt
        or shed requests the pool can actually serve)."""
        sd = self.engine.config.spec_decode
        mult = sd.k + 1 if (sd.enabled and self.config.spec) else 1
        return self.config.decode_slice * mult + 1

    def enqueue(self, req) -> bool:
        """False = queue full; the caller sheds the request immediately."""
        if self.queued >= self.config.max_queue:
            return False
        self._queues[req.cls.name].append(req)
        return True

    def remove(self, req) -> None:
        q = self._queues[req.cls.name]
        try:
            q.remove(req)
        except ValueError:
            pass                      # already popped by a plan

    def _iter_queued(self):
        """Queued requests in strict (priority desc, FIFO) order."""
        for cls in self._order:
            for req in self._queues[cls.name]:
                yield req

    # ------------------------------------------------------------------ #
    # the planner
    # ------------------------------------------------------------------ #

    def _blocks(self, n_tokens: int) -> int:
        bs = self.engine.kv.config.block_size
        return -(-int(n_tokens) // bs)

    def _admit_cost(self, req, slice_tokens: int) -> int:
        """Blocks an admission must fund up front. Preemptive modes admit
        optimistically (prompt + one slice of decode growth); reject-only
        funds the full KV lifetime — with no eviction lever, optimism would
        strand the live set mid-decode."""
        if self.config.preemption == "none":
            return self._blocks(len(req.prompt) + req.max_new_tokens + 1)
        return self._blocks(len(req.prompt) + slice_tokens)

    def _restore_cost(self, req, offload, slice_tokens: int) -> int:
        """Blocks a restore consumes: the offloaded page count (offload) or
        a full re-prefill of prompt + generated-so-far (recompute), plus a
        slice of growth either way."""
        grow = self._blocks(slice_tokens)
        if offload is not None and req.uid in offload._recs:
            return offload.pages_held(req.uid) + grow
        return self._blocks(len(req.prompt) + len(req.tokens) + 1) + grow

    def _freeable(self, uid: int) -> int:
        """Pool blocks preempting ``uid`` returns right now: its private
        tail (offload/recompute both free exactly these to the free list;
        shared-prefix pages only move to the radix tree, where they are
        already counted evictable)."""
        return len(self.engine.scheduler.private_tail(uid)[1])

    def queue_delay_s(self, cls_name: str) -> float:
        """The class's admitted queue-delay EMA in seconds (0 until the
        first admission) — read by ``ServingRouter`` for federated
        placement/shedding; see ``_qdelay`` above."""
        return self._qdelay.get(cls_name) or 0.0

    def _note_queue_delay(self, cls_name: str, delay_s: float) -> None:
        a = self.cost.alpha
        cur = self._qdelay[cls_name]
        self._qdelay[cls_name] = delay_s if cur is None \
            else (1 - a) * cur + a * delay_s

    def hopeless(self, req, now: float) -> bool:
        """Best-case TTFT already misses the class SLO: shed, don't burn."""
        elapsed = now - req.arrival_t
        predicted = self.cost.predicted_ttft_s(len(req.prompt))
        return (elapsed + predicted) * 1e3 > \
            req.cls.ttft_slo_ms * self.config.shed_factor

    def plan(self, now: Optional[float], live: Dict[int, object],
             preempted: Dict[int, object], offload=None) -> List[Action]:
        """One admission round's ordered action list (see module docstring).
        ``live``/``preempted`` map uid -> request for the frontend's current
        decoding / preempted sets; ``offload`` is the KVOffloadManager (None
        under recompute/none preemption)."""
        if now is None:
            now = time.perf_counter()
        cfg = self.config
        sched = self.engine.scheduler
        sm = self.engine.config.state_manager
        slice_tokens = self.slice_tokens
        actions: List[Action] = []

        # simulated capacity: every planned action moves these two counters,
        # so one plan never over-commits what its own admissions consume
        budget = sched.available_blocks \
            - sched.blocks_needed(list(live), slice_tokens)
        rows_free = sm.max_ragged_sequence_count - len(live)
        slots_free = sm.max_tracked_sequences - len(sched.seqs)

        # 0. sheds: SLO-hopeless queued requests, any class
        for req in list(self._iter_queued()):
            if req.cancelled:
                self.remove(req)      # frontend finalizes via its own sweep
            elif self.hopeless(req, now):
                self.remove(req)
                actions.append(("shed", req))

        # adapter-aware planning: admits/restores of LoRA-bound requests
        # also need their adapter fundable in the ADAPTER page pool
        # (resident, or free + idle-evictable pages >= rank) — checked with
        # the same simulate-the-plan discipline as the block budget, where
        # a planned preempt releases its victim's adapter binding
        lora = getattr(self.engine, "lora", None)
        releasing: List[int] = []

        def _adapter_ok(req) -> bool:
            a = getattr(req, "adapter", None)
            if a is None or lora is None:
                return True
            return lora.can_admit(a, releasing=releasing)

        # 1. restores outrank admissions (priority desc, oldest preempt first)
        order = {c.name: i for i, c in enumerate(self._order)}
        for req in sorted(preempted.values(),
                          key=lambda r: (order[r.cls.name], r.preempt_t)):
            if req.cancelled or rows_free <= 0:
                continue
            if not _adapter_ok(req):
                continue      # adapter pool pressure: stay preempted
            # a recompute-preempted victim was flushed — readmitting it
            # re-creates its sequence, so it needs a tracked slot too
            needs_slot = offload is None or req.uid not in offload._recs
            if needs_slot and slots_free <= 0:
                continue
            cost = self._restore_cost(req, offload, slice_tokens)
            if cost <= budget:
                actions.append(("restore", req))
                budget -= cost
                rows_free -= 1
                slots_free -= needs_slot

        # 2. admits: strict priority FIFO with head-of-line blocking;
        #    preemption may fund a strictly-higher-priority head
        # pop() takes from the END: sort so the tail is (lowest priority,
        # NEWEST admission) — LIFO within a class preserves older requests'
        # progress (a 90-token victim loses more than a 2-token one)
        victims = sorted(
            (r for r in live.values()),
            key=lambda r: (order[r.cls.name], r.admit_t))
        for req in list(self._iter_queued()):
            if req.cancelled:
                continue
            if rows_free <= 0 or slots_free <= 0:
                break
            need = self._admit_cost(req, slice_tokens)
            while need > budget and cfg.preemption != "none" and victims:
                v = victims[-1]
                if v.cls.priority >= req.cls.priority:
                    break             # never preempt same-or-higher priority
                victims.pop()
                gain = self._freeable(v.uid)
                if gain <= 0 and rows_free > 0:
                    continue          # nothing to reclaim from this victim
                actions.append(("preempt", v))
                budget += gain
                rows_free += 1
                releasing.append(v.uid)
            # adapter pool pressure funds the same way KV pressure does:
            # preempt strictly-lower-priority rows whose released bindings
            # make enough idle pages evictable — but only rows that HOLD an
            # adapter binding (an adapterless victim frees no adapter pages)
            while not _adapter_ok(req) and cfg.preemption != "none" \
                    and victims:
                v = victims[-1]
                if v.cls.priority >= req.cls.priority:
                    break
                victims.pop()
                if getattr(v, "adapter", None) is None:
                    continue
                actions.append(("preempt", v))
                budget += self._freeable(v.uid)
                rows_free += 1
                releasing.append(v.uid)
            if not _adapter_ok(req):
                break                 # head-of-line holds; no bypass
            if need <= budget:
                self.remove(req)
                actions.append(("admit", req))
                # intentionally async: queue delay is host wall time the
                # request ALREADY waited (arrival -> this admit), no device
                # work is being timed
                self._note_queue_delay(req.cls.name, now - req.arrival_t)  # jaxlint: disable=JL001
                budget -= need
                rows_free -= 1
                slots_free -= 1
            else:
                break                 # head-of-line holds; no bypass
        return actions

    def slice_shortfall(self, live_uids: List[int]) -> int:
        """Blocks the NEXT decode slice still needs beyond what the pool can
        provide — the frontend's pre-run emergency-preemption trigger (>0
        only when optimistic admission outran generation-driven growth)."""
        need = self.engine.scheduler.blocks_needed(
            list(live_uids), self.slice_tokens)
        return need - self.engine.scheduler.available_blocks
