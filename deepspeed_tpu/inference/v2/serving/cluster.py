"""Replica management for multi-replica serving (docs/SERVING.md
"Multi-replica & disaggregation").

A :class:`ServingCluster` turns N data-parallel ``InferenceEngineV2``
instances (same model, same weights, independent KV pools) into the replica
set a :class:`~deepspeed_tpu.inference.v2.serving.router.ServingRouter`
fronts:

- builds one ``ServingFrontend`` per serving replica from ONE shared
  ``ServingConfig`` (uniform priority classes — federation compares
  like-for-like SLO state);
- labels every replica's monitor surfaces (``FrontendStats.replica`` /
  ``SpecDecodeStats.replica``) so N frontends fanning into one monitor
  backend emit ``serve/frontend/<replica>/*`` rows instead of colliding;
- validates the KV page fabric is uniform (block size + page layout), the
  precondition for byte-exact cross-engine handoffs
  (``engine.export_kv``/``import_kv``);
- under a disaggregated topology, runs a :class:`PrefillWorker` per
  ``prefill`` replica: queued requests prefill in SplitFuse-composed batches
  through the engine's scheduler passes, then each finished sequence's KV
  pages + bootstrap logits row move to a decode replica over the bucketed
  page gather — the same pinned-host round trip preempt-offload rides
  (``kv_offload.py``), re-seeding ``_last_logits`` exactly like a
  preemption restore.

Roles: ``"serve"`` (prefill + decode — the colocated default),
``"prefill"`` (SplitFuse passes only, no frontend), ``"decode"``
(handoff-fed decode frontend).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

from deepspeed_tpu.monitor.trace import tracer as _tracer
from deepspeed_tpu.utils.fault_injection import maybe_fail
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.resilience import call_with_deadline

_ROLES = ("serve", "prefill", "decode")


class Replica:
    """One engine (+ its serving frontend, unless role ``prefill``) under a
    stable name — the unit the router places requests on."""

    def __init__(self, name: str, engine, role: str = "serve",
                 frontend=None):
        self.name = name
        self.engine = engine
        self.role = role
        self.frontend = frontend

    def __repr__(self) -> str:
        return f"Replica({self.name!r}, role={self.role!r})"


class ServingCluster:

    def __init__(self, engines: Sequence, serving=None,
                 roles: Optional[Sequence[str]] = None,
                 names: Optional[Sequence[str]] = None):
        engines = list(engines)
        if not engines:
            raise ValueError("a cluster needs at least one engine")
        roles = list(roles) if roles is not None else ["serve"] * len(engines)
        names = list(names) if names is not None \
            else [f"r{i}" for i in range(len(engines))]
        if not (len(engines) == len(roles) == len(names)):
            raise ValueError(
                f"engines ({len(engines)}), roles ({len(roles)}) and names "
                f"({len(names)}) must align")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        bad = [r for r in roles if r not in _ROLES]
        if bad:
            raise ValueError(f"unknown replica roles {bad}; valid: {_ROLES}")
        # the page fabric is only byte-exact between identical layouts:
        # block size, page shape and dtype must match across every replica
        ref = engines[0].kv.config
        for e, name in zip(engines[1:], names[1:]):
            c = e.kv.config
            mismatched = [f for f in ("num_layers", "num_kv_heads", "head_dim",
                                      "block_size", "dtype", "quantized")
                          if getattr(c, f) != getattr(ref, f)]
            if mismatched:
                raise ValueError(
                    f"replica {name!r} KV layout differs from "
                    f"{names[0]!r} on {mismatched} — cross-replica KV "
                    "handoff would not be byte-exact")
        self.replicas: List[Replica] = []
        # disjoint per-frontend uid spaces ((1 << 24)-spaced — 16.7M
        # requests per frontend lifetime): a request migrated off a failed
        # replica keeps its uid on the survivor, so two frontends must
        # never mint the same one; a rejoin-rebuilt frontend draws a FRESH
        # space (alloc_uid_base) for the same reason
        self._uid_spaces = itertools.count(1)
        for engine, role, name in zip(engines, roles, names):
            frontend = None
            if role != "prefill":
                frontend = engine.serving_frontend(
                    config=serving, uid_base=self.alloc_uid_base())
                frontend.stats.replica = name
            engine.spec_stats.replica = name
            self.replicas.append(Replica(name, engine, role, frontend))

    def alloc_uid_base(self) -> int:
        """A fresh, never-reused uid space for one frontend lifetime."""
        return (1 << 24) * next(self._uid_spaces)

    # ------------------------------------------------------------------ #

    @property
    def block_size(self) -> int:
        return self.replicas[0].engine.kv.config.block_size

    @property
    def frontends(self) -> List[Replica]:
        return [r for r in self.replicas if r.frontend is not None]

    @property
    def prefill_replicas(self) -> List[Replica]:
        return [r for r in self.replicas if r.role == "prefill"]

    @property
    def decode_replicas(self) -> List[Replica]:
        """Replicas that can decode handed-off sequences."""
        return [r for r in self.replicas if r.role == "decode"]

    @property
    def serve_replicas(self) -> List[Replica]:
        """Colocated (prefill + decode) replicas."""
        return [r for r in self.replicas if r.role == "serve"]

    def replica(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"unknown replica {name!r}; configured: "
                       f"{[r.name for r in self.replicas]}")

    def start(self) -> "ServingCluster":
        """Start every replica frontend (idempotent per frontend — a bench
        or test may warm frontends before handing the cluster to a
        router)."""
        for r in self.frontends:
            if r.frontend._thread is None and not r.frontend._closed:
                r.frontend.start()
        return self

    def close(self, ignore: Sequence[str] = ()) -> None:
        """Close every frontend; the FIRST replica whose close raises (a
        died engine thread) is re-raised NAMED after all replicas are torn
        down — a dead replica must not leave its siblings running.
        ``ignore`` names replicas whose failure was already HANDLED (the
        router's health monitor migrated their requests) — their close
        still runs, but a died-loop re-raise is suppressed rather than
        reported twice."""
        failed = []
        for r in self.frontends:
            try:
                r.frontend.close()
            except BaseException as exc:
                if r.name not in ignore:
                    failed.append((r.name, exc))
        if failed:
            name, exc = failed[0]
            raise RuntimeError(f"replica {name!r} failed at close") from exc


class PrefillWorker:
    """Dedicated prefill executor for one ``prefill``-role replica.

    Drains its queue in batches: every queued request's prompt enters the
    scheduler together, so the SplitFuse passes COMPOSE concurrent prompts
    (multiple chunk slots per pass — the same batching a colocated frontend
    gets, without a decode set to interfere with). Each finished sequence is
    exported (``engine.export_kv``: one bucketed page gather + the bootstrap
    logits row) and handed to the least-loaded decode replica
    (``ServingFrontend.submit_handoff``). Client disconnects are polled at
    pass boundaries exactly like ``ServingFrontend._prefill``.

    A worker that dies surfaces at the ROUTER's ``drain()``/``close()`` with
    the replica named (``exc``), and every request it still held has its
    stream closed so clients never hang."""

    def __init__(self, replica: Replica, router):
        self.replica = replica
        self.router = router
        self.q: "queue.Queue" = queue.Queue()
        self.exc: Optional[BaseException] = None
        # requests this worker currently owns (popped from the queue, not
        # yet handed off / finalized) — the crash handler closes exactly
        # these streams, never one a decode replica already adopted
        self._owned: Dict[int, object] = {}
        self._stop = threading.Event()
        self._fenced = False
        self._site = f"serve.prefill_worker.{replica.name}"
        self._thread: Optional[threading.Thread] = None

    @property
    def queued(self) -> int:
        return self.q.qsize()

    @property
    def fenced(self) -> bool:
        return self._fenced

    def submit(self, req) -> None:
        if self._fenced:
            raise RuntimeError(
                f"prefill worker {self.replica.name!r} is fenced")
        self.q.put(req)

    def fence(self) -> None:
        """Declare this worker DOWN (serving/health.py): even a wedged
        thread that wakes later bails at the next batch/pass boundary
        without exporting or handing anything off — its queue and owned
        requests now belong to the failover migration."""
        self._fenced = True
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"dstpu-prefill-{self.replica.name}",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        # abandon whatever is still queued: close the streams (cancelled)
        while True:
            try:
                req = self.q.get_nowait()
            except queue.Empty:
                break
            self.router._finalize_external(req, "cancelled")

    # -- the worker thread --------------------------------------------- #

    def _finalize(self, req, status: str) -> None:
        self._owned.pop(req.uid, None)
        self.router._finalize_external(req, status)

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    req = self.q.get(timeout=0.02)
                except queue.Empty:
                    continue
                batch = [req]
                while True:            # batch everything already queued
                    try:
                        batch.append(self.q.get_nowait())
                    except queue.Empty:
                        break
                # own the batch BEFORE the chaos site: a crash (or wedge)
                # here must leave every popped request reachable by the
                # failover sweep, never stranded in a dead thread's locals
                for r in batch:
                    self._owned[r.uid] = r
                # chaos site (raise = crash this worker, stall = wedge it);
                # the fence check follows so a stalled thread that wakes
                # post-failover re-queues the batch untouched and exits
                maybe_fail(self._site)
                if self._fenced:
                    for r in batch:    # migration drains the queue
                        self._owned.pop(r.uid, None)
                        self.q.put(r)
                    return
                self._process(batch)
        except BaseException as exc:   # surface at router drain()/close()
            # (or at the health monitor, which migrates _owned instead)
            self.exc = exc
            if not self.router.health.enabled:
                for req in list(self._owned.values()):
                    self._finalize(req, "cancelled")

    def _process(self, batch: List) -> None:
        e = self.replica.engine
        pending = list(batch)
        while pending:
            if self._fenced:
                for req in pending:    # migration takes them back
                    self._owned.pop(req.uid, None)
                    self.q.put(req)
                return
            live = []
            while pending:
                req = pending[0]
                if req.cancelled:
                    self._finalize(req, "cancelled")
                    pending.pop(0)
                    continue
                if not e.can_schedule([req.uid], [len(req.prompt)]):
                    if not live:
                        # router.submit validated the prompt against the
                        # pool, so an empty engine always fits one — a
                        # stuck full pool here is a real bug, not load
                        raise RuntimeError(
                            f"prefill replica {self.replica.name!r} cannot "
                            f"fit prompt of {len(req.prompt)} tokens")
                    break              # drain what we have, then continue
                t = time.perf_counter()
                # from the phase stamp, not arrival: a failover-requeued
                # request already attributed arrival..migration — this
                # stint is only the wait in THIS worker's queue
                req._ledger_add("queued", req._phase_t0, t)
                if _tracer.enabled:
                    _tracer.add("serve/req/queued", req._phase_t0, t,
                                lane=f"serve/req/u{req.uid}", uid=req.uid,
                                trace_id=req.trace_id)
                e.scheduler.add_tokens(req.uid, req.prompt)
                req.status = "prefill"
                req._phase_t0 = t
                live.append(req)
                pending.pop(0)
            self._prefill_and_handoff(live)

    def _prefill_and_handoff(self, live: List) -> None:
        e = self.replica.engine
        t0 = time.perf_counter()
        tokens = sum(len(r.prompt) for r in live)
        while e.scheduler.has_pending():
            e._run_pass()
            for req in live:
                if req.cancelled and req.status == "prefill":
                    e.flush([req.uid])
                    self._finalize(req, "cancelled")
        live = [r for r in live if r.status == "prefill"]
        t1 = time.perf_counter()
        if live:
            # same loop-observed cadence the colocated frontend feeds its
            # cost model — the router's federation reads this replica's rate
            self.router._note_prefill(self.replica, tokens, t1 - t0)  # jaxlint: disable=JL001
        for req in live:
            req._ledger_add("prefill", req._phase_t0, t1)
            if _tracer.enabled:
                _tracer.add("serve/req/prefill", req._phase_t0, t1,
                            lane=f"serve/req/u{req.uid}", uid=req.uid,
                            trace_id=req.trace_id)
            # the decode replica's handoff_wait stint starts here: the
            # ledger must cover export + fabric wait + import as one span
            req._phase_t0 = t1
            self._handoff(req)

    def _handoff(self, req) -> None:
        """Export one prefilled sequence and hand it to a decode replica
        under the router's bounded retry/timeout budget
        (``RouterConfig.handoff_retries`` / ``handoff_timeout_s`` /
        ``handoff_backoff_s``; ``utils/resilience``): each attempt is
        deadline-wrapped (a wedged decode replica raises
        :class:`~deepspeed_tpu.utils.resilience.IOTimeout` here instead of
        stalling this worker unboundedly) and RE-PLANNED against a decode
        replica the earlier attempts have not seen fail. A request that
        exhausts the budget is shed with the error NAMED on its handle
        (``req.error`` — re-raised by ``result()``), never swallowed."""
        e = self.replica.engine
        cfg = self.router.config
        h0 = time.perf_counter()
        pages, logits = e.export_kv(req.uid)
        tried: List[str] = []
        delay = cfg.handoff_backoff_s
        last: Optional[BaseException] = None
        for attempt in range(cfg.handoff_retries):
            try:
                # prefer a replica earlier attempts have NOT seen fail;
                # with every one tried (or only one configured), retry the
                # least-loaded anyway — attempt-scoped faults are transient
                try:
                    target = self.router._pick_decode(exclude=tried)
                except LookupError:
                    target = self.router._pick_decode()
            except LookupError as exc:
                last = exc
                break
            # `abandoned` makes a timed-out attempt inert: if the wedged
            # call wakes after we moved on, it must not ALSO submit — two
            # replicas serving one stream is worse than a retry. The lock
            # makes submit-vs-abandon atomic: a late waker either finds
            # `abandoned` set and raises, or its submit LANDED before the
            # flag flipped — in which case `submitted` tells this loop the
            # attempt actually succeeded and there is nothing to retry.
            state = {"abandoned": False, "submitted": False}
            state_lock = threading.Lock()

            def _attempt(target=target, state=state):
                maybe_fail("serve.handoff")
                maybe_fail(f"serve.handoff.{self.replica.name}")
                with state_lock:
                    if state["abandoned"]:
                        raise RuntimeError("handoff attempt abandoned "
                                           "after timeout")
                    target.frontend.submit_handoff(req, pages, logits)
                    state["submitted"] = True

            try:
                call_with_deadline(
                    _attempt, cfg.handoff_timeout_s,
                    describe=f"handoff uid {req.uid} "
                             f"{self.replica.name!r}->{target.name!r}")
            except (OSError, RuntimeError) as exc:   # incl. IOTimeout,
                with state_lock:                     # InjectedFault, fenced
                    state["abandoned"] = True
                    landed = state["submitted"]
                if not landed:
                    last = exc
                    tried.append(target.name)
                    if attempt < cfg.handoff_retries - 1:
                        time.sleep(delay)
                        delay *= 2.0
                    continue
            self._owned.pop(req.uid, None)
            self.router._note_handoff(self.replica, target, req,
                                      int(pages.nbytes), h0)
            return
        err = RuntimeError(
            f"handoff of request {req.uid} from prefill replica "
            f"{self.replica.name!r} exhausted its retry budget "
            f"({cfg.handoff_retries} attempts, tried {tried or 'none'})")
        err.__cause__ = last
        req.error = err
        log_dist(f"{err} — shedding the request", ranks=[0])
        with self.router._lock:
            self.router.stats.handoff_failures += 1
        self._finalize(req, "shed")
