"""Replica failure detection, request failover, and self-healing membership
for the multi-replica serving router (docs/SERVING.md "Failure semantics").

PRs 8-10 built a serving cluster that treats a replica death as terminal:
the crashed engine thread closed its streams, its prefix-index entries went
stale forever, and the router could only NAME the corpse at
``drain()``/``close()``. This module is the PR 6 robustness discipline
(detect deterministically, recover byte-exactly, prove it under injected
chaos) applied to the serving half.

**Detection.** A ``dstpu-health`` thread polls every replica on a fixed
interval: engine-thread / prefill-worker LIVENESS (a died loop is ``down``
immediately) plus a PROGRESS heartbeat derived from counters the stats
already track — the decode pipeline's step counter and the scheduler's
prefill-token counter. A replica with work in flight whose counters freeze
is *wedged*, not idle: it turns ``suspect`` after
``HealthConfig.suspect_after_s`` and ``down`` after ``down_after_s``
(states: ``healthy -> suspect -> down -> draining -> rejoining``).

**Failover.** ``down`` FENCES the replica (``ServingFrontend.fence`` /
``PrefillWorker.fence``): even a wedged thread that wakes later emits
nothing — every in-flight stream now belongs to the migration. Each request
is SEALED under its handle's emit lock (an exact prompt+emitted snapshot no
straggling emission can race), then moved, not killed:

- a preempt-offloaded victim whose WHOLE KV sits in pinned host buffers
  (``KVOffloadManager.salvageable``) is SALVAGED — the buffers become a
  survivor's ``import_kv`` payload over the page fabric, zero recompute;
- a queued disaggregated handoff (pages already host-side) is RE-PLANNED to
  another decode replica;
- everything else RE-PREFILLS its sealed history on a survivor through the
  recompute-restore path (``ServingFrontend.submit_resume``) — where the
  cluster prefix index steered placement onto a replica with the prefix
  cached, the radix match skips that span;

and the stream resumes byte-identically from the last emitted token, with a
``RequestHandle.migrated`` marker. No survivor able to fund it -> a clean
shed, never a hung stream. The dead replica's chain-hash entries leave the
``ClusterPrefixIndex`` at fence time.

**Self-healing.** Once the failed thread has actually exited, ``rejoin``
resets the engine (flush stranded sequences, drop stranded offload
records), rebuilds a frontend in a FRESH uid space, re-warms the pow2
program grids OFF the routing hot path (zero new compiles on an
already-warm engine — gated by ``serving_bench.py --chaos``), re-registers
the prefix-index delta feed (replaying the engine's surviving radix tree),
and only then returns the replica to routing.

Everything here is host metadata + thread-safe frontend surfaces; the only
device work is the survivor-side import/re-prefill, on the survivor's own
engine thread. Observability: ``monitor/serving.HealthStats``
(``serve/health/*``) and ``serve/health/{detect,migrate,rejoin}`` trace
spans from the same perf stamps (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.inference.v2.config_v2 import HealthConfig
from deepspeed_tpu.inference.v2.serving.frontend import (CANCELLED, FINISHED,
                                                         SHED, _DONE)
from deepspeed_tpu.monitor.serving import HealthStats
from deepspeed_tpu.monitor.trace import tracer as _tracer
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.threads import make_rlock, thread_role

# replica health states (docs/SERVING.md "Failure semantics")
HEALTHY = "healthy"        # in routing rotation
SUSPECT = "suspect"        # progress stalled past suspect_after_s
DOWN = "down"              # declared failed (liveness, or stall deadline)
DRAINING = "draining"      # fenced; in-flight requests migrating / migrated
REJOINING = "rejoining"    # frontend rebuilt, warming off the hot path


class _ReplicaRecord:
    __slots__ = ("name", "state", "progress", "stall_since", "last_ok",
                 "handled", "want_rejoin", "busy")

    def __init__(self, name: str):
        self.name = name
        self.state = HEALTHY
        self.progress: Optional[Tuple] = None
        self.stall_since: Optional[float] = None
        self.last_ok = time.perf_counter()
        self.handled = False           # a failure this monitor failed over
        self.want_rejoin = False
        self.busy = False              # claimed by a failover/rejoin actor


class HealthMonitor:
    """Owns the replica health state machine for one ``ServingRouter``.

    ``poll()`` is ONE detection pass — the background thread calls it on
    ``HealthConfig.interval_s``, ``router.drain`` calls it through
    ``check()``, and tests drive it synchronously for determinism.

    Locking discipline (threadlint TL002 shaped this): detection and every
    state transition run under ``_lock``, but the BLOCKING legs of a
    failover/rejoin — fence joins, ``old.close()``, ``engine.warmup()`` —
    run with the lock RELEASED. A record is CLAIMED (``rec.busy``) under
    the lock before any actor starts handling it and released when the
    actor finishes, so a failure is still handled exactly once no matter
    who observed it, while ``all_healthy()``/``handled_replicas()`` never
    wait out a wedged replica's join timeout behind the monitor lock."""

    def __init__(self, router, config: Optional[HealthConfig] = None):
        cfg = config if config is not None else HealthConfig()
        if isinstance(cfg, dict):
            cfg = HealthConfig(**cfg)
        self.router = router
        self.config = cfg
        self.stats = HealthStats([r.name for r in router.cluster.replicas])
        self._recs: Dict[str, _ReplicaRecord] = {
            r.name: _ReplicaRecord(r.name) for r in router.cluster.replicas}
        self._lock = make_rlock("serving.health.monitor")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="dstpu-health", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    @thread_role("dstpu-health")
    def _run(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.poll()
            except BaseException as exc:    # surfaced at check()/drain()
                self._exc = exc
                return

    def check(self) -> None:
        """Router-facing health check: run a poll inline and re-raise a
        monitor-thread failure (the monitor dying must not silently turn
        back into hung streams)."""
        if self._exc is not None:
            raise RuntimeError("health monitor died") from self._exc
        self.poll()

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #

    def state(self, name: str) -> str:
        return self._recs[name].state

    def handled_replicas(self) -> List[str]:
        """Replicas whose failure this monitor already failed over —
        ``router.close`` suppresses their died-loop re-raise."""
        with self._lock:
            return [r.name for r in self._recs.values() if r.handled]

    def all_healthy(self) -> bool:
        with self._lock:
            return all(r.state == HEALTHY for r in self._recs.values())

    def wait_all_healthy(self, timeout: float) -> bool:
        """Poll until every replica is back in rotation (benches wait for
        self-healing to complete before scoring baselines)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.poll()
            if self.all_healthy():
                return True
            time.sleep(min(self.config.interval_s, 0.02))
        return self.all_healthy()

    # ------------------------------------------------------------------ #
    # detection
    # ------------------------------------------------------------------ #

    def _liveness_exc(self, replica) -> Optional[BaseException]:
        if replica.role == "prefill":
            return self.router._workers[replica.name].exc
        fe = replica.frontend
        return None if fe is None else fe._loop_exc

    def _progress(self, replica) -> Tuple[Tuple, bool]:
        """(progress snapshot, busy?). The snapshot folds the counters the
        replica moves when it COMPLETES work — the decode pipeline's step /
        token counters and prefill tokens completed — so forward motion
        resets the stall clock; ``busy`` gates the clock so an idle replica
        is never suspected. Deliberately NOT in the snapshot: the in-flight
        count — new arrivals landing on a wedged replica would reset its
        stall clock forever (measured: a stalled replica under steady
        Poisson traffic was never declared down)."""
        e = replica.engine
        if replica.role == "prefill":
            w = self.router._workers[replica.name]
            return ((e.scheduler.prefill_tokens_completed,),
                    w.queued > 0 or bool(w._owned))
        fe = replica.frontend
        snap = (e.pipeline_stats.steps, e.pipeline_stats.tokens,
                e.scheduler.prefill_tokens_completed)
        return snap, fe._inflight > 0

    def _transition(self, rec: _ReplicaRecord, new: str) -> None:
        with self._lock:
            old = rec.state
            if old == new:
                return
            rec.state = new
            self.stats.record_transition(rec.name, old, new)
        if _tracer.enabled:
            _tracer.instant("serve/health/state", lane="serve/health",
                            replica=rec.name, frm=old, to=new)

    def poll(self) -> None:
        """One detection pass over every replica (reentrant-safe). The
        scan CLAIMS records needing a failover/rejoin under the lock; the
        blocking handling runs after the lock is released."""
        # the scan's SUSPECT/HEALTHY transitions emit tracer instants while
        # the monitor lock is held; a thread's first record registers its
        # ring under monitor.trace.registry — pre-register outside the lock
        # so that acquisition order never exists
        _tracer.register_thread()
        actions: List[Tuple[str, object, _ReplicaRecord, str]] = []
        with self._lock:
            now = time.perf_counter()
            for replica in self.router.cluster.replicas:
                rec = self._recs[replica.name]
                if rec.busy:
                    continue           # another actor is mid-handling
                if rec.state in (DOWN, DRAINING):
                    if rec.want_rejoin:
                        rec.busy = True
                        actions.append(("rejoin", replica, rec, ""))
                    continue
                if rec.state == REJOINING:
                    continue               # rejoin completes synchronously
                exc = self._liveness_exc(replica)
                if exc is not None:
                    rec.busy = True
                    actions.append(("down", replica, rec, "liveness"))
                    continue
                prog, busy = self._progress(replica)
                if prog != rec.progress or not busy:
                    rec.progress = prog
                    rec.stall_since = None
                    rec.last_ok = now
                    if rec.state == SUSPECT:
                        self._transition(rec, HEALTHY)
                    continue
                if rec.stall_since is None:
                    rec.stall_since = now
                    continue
                # intentionally async: the stall clock measures HOST wall
                # time since the counters froze — no device work is timed
                stalled = now - rec.stall_since  # jaxlint: disable=JL001
                if stalled >= self.config.down_after_s:
                    rec.busy = True
                    actions.append(("down", replica, rec, "stall"))
                elif stalled >= self.config.suspect_after_s \
                        and rec.state == HEALTHY:
                    self._transition(rec, SUSPECT)
        for act, replica, rec, kind in actions:
            try:
                if act == "down":
                    self._declare_down(replica, rec, kind, now)
                else:
                    self._try_rejoin(replica, rec)
            finally:
                rec.busy = False

    def _declare_down(self, replica, rec: _ReplicaRecord, kind: str,
                      now: float) -> None:
        """Handle one declared failure. The caller has CLAIMED ``rec``
        (``rec.busy``); everything blocking here runs without the monitor
        lock."""
        t0 = rec.stall_since if kind == "stall" else rec.last_ok
        self._transition(rec, DOWN)
        with self._lock:
            self.stats.record_detection(kind, now - t0)
        if _tracer.enabled:
            _tracer.add("serve/health/detect", t0, now, lane="serve/health",
                        replica=rec.name, kind=kind)
        log_dist(f"health: replica {rec.name!r} is DOWN ({kind}); "
                 "fencing and migrating its requests", ranks=[0])
        self._failover(replica, rec)
        with self._lock:
            rec.handled = True
            rec.want_rejoin = bool(self.config.auto_rejoin)
        if rec.want_rejoin:
            self._try_rejoin(replica, rec)

    # ------------------------------------------------------------------ #
    # failover
    # ------------------------------------------------------------------ #

    def _failover(self, replica, rec: _ReplicaRecord) -> None:
        self.router._drop_replica_routing(replica.name)
        if replica.role == "prefill":
            self._failover_prefill(replica, rec)
            return
        fe = replica.frontend
        fe.fence()
        fe.join(self.config.fence_join_s)   # best effort; seal covers races
        self._transition(rec, DRAINING)
        self._collect_and_migrate(replica, fe)

    def _collect_and_migrate(self, replica, fe) -> None:
        """Migrate every request a fenced/dead frontend still holds — its
        filed dicts plus control messages the loop never drained (each was
        counted in ``_inflight`` at submit but never filed). Re-run at
        rejoin time to catch a straggler a wedged thread raced past the
        first scrape."""
        items: List[Tuple] = []             # (req, handoff_rec)
        for kind, payload in fe._scrape_control():
            with fe._inflight_lock:
                fe._inflight -= 1
            if kind == "submit":
                items.append((payload, None))
            elif kind == "handoff":
                items.append((payload[0], payload))
            elif kind == "resume":
                items.append((payload[0], None))
        for req in list(fe._reqs.values()):
            items.append((req, fe.disown(req)))
        for req, handoff in items:
            self._migrate_one(replica, fe, req, handoff)

    def _failover_prefill(self, replica, rec: _ReplicaRecord) -> None:
        """A dead/wedged prefill worker: its queued + owned requests hold no
        device state (an exported sequence already left with its handoff) —
        re-queue them on a surviving prefill worker, or shed cleanly."""
        w = self.router._workers[replica.name]
        w.fence()
        w.join(self.config.fence_join_s)
        self._transition(rec, DRAINING)
        self._requeue_prefill(self._drain_worker(w), exclude=replica.name)

    def _drain_worker(self, w) -> List:
        """Every request a fenced/dead prefill worker still holds (owned +
        queued, deduped — a fenced thread re-queues what it owned)."""
        reqs = list(w._owned.values())
        w._owned.clear()
        while True:
            try:
                reqs.append(w.q.get_nowait())
            except Exception:
                break
        seen = set()
        out = []
        for req in reqs:
            if req.uid not in seen:
                seen.add(req.uid)
                out.append(req)
        return out

    def _requeue_prefill(self, reqs: List, exclude: str) -> None:
        """Place each request on SOME routable prefill worker (least-queued
        first, next survivor on a fence race — the prefill twin of
        ``_migrate_one``'s target loop), shedding only when none can take
        it."""
        router = self.router
        for req in reqs:
            t0 = time.perf_counter()
            if req.cancelled:
                self.stats.migration_cancels += 1
                router._finalize_external(req, CANCELLED)
                continue
            self._close_phase(req, t0)
            placed = None
            survivors = sorted(
                (r for r in router.cluster.prefill_replicas
                 if r.name != exclude and router._routable(r)),
                key=lambda r: router._workers[r.name].queued)
            for target in survivors:
                # the migration stint lands on the ledger BEFORE the
                # publish: the moment submit() succeeds the survivor's
                # worker thread may pop the handle and record its own
                # 'queued' stint from _phase_t0 — writing after the
                # publish would race it (overlapping stints, a clobbered
                # phase stamp). An unplaceable request sheds terminally,
                # so a stint recorded for a fenced-then-shed attempt is
                # never read by the finished-ledger gates.
                t1 = time.perf_counter()
                req._ledger_add("migration", t0, t1)
                req._phase_t0 = t1
                try:
                    router._workers[target.name].submit(req)
                    placed = target
                    break
                except RuntimeError:
                    if req._ledger:
                        req._ledger.pop()   # fenced: the stint never ran
                    req._phase_t0 = t0
                    continue           # next survivor
            if placed is not None:
                req.migrated += 1
                self.stats.record_migration("reprefill", len(req.prompt))
                self._migrate_span(req, t0, "requeue", placed.name)
            else:
                self.stats.migration_sheds += 1
                router._finalize_external(req, SHED)

    #: RequestHandle.status -> ledger phase label for seal-time closes
    _PHASE_OF = {"queued": "queued", "prefill": "prefill",
                 "decoding": "decode", "preempted": "preempted"}

    def _close_phase(self, req, t: float, phase: Optional[str] = None) -> None:
        """Close the phase a dead replica's request was orphaned in: the
        stint from its last phase stamp to the failover stamp ``t`` lands
        on the ledger (and the trace lane) — the wedge/crash window is
        attributed, not lost — and ``_phase_t0`` re-bases to ``t`` so the
        ``migration`` stint recorded at adoption starts exactly here."""
        if phase is None:
            phase = self._PHASE_OF.get(req.status)
        if phase is not None and t > req._phase_t0:
            req._ledger_add(phase, req._phase_t0, t)
            if _tracer.enabled:
                _tracer.add(f"serve/req/{phase}", req._phase_t0, t,
                            lane=f"serve/req/u{req.uid}", uid=req.uid,
                            trace_id=req.trace_id, cls=req.cls.name,
                            orphaned=True)
        req._phase_t0 = t

    def _migrate_span(self, req, t0: float, mode: str, dst: str) -> None:
        if _tracer.enabled:
            _tracer.add("serve/health/migrate", t0, time.perf_counter(),
                        lane="serve/health", uid=req.uid,
                        trace_id=req.trace_id, mode=mode, dst=dst)

    def _finalize_handle(self, fe, req, status: str) -> None:
        """Terminal-state a handle the dead replica still owned, releasing
        host-side resources (offload buffers); the dead engine's
        device-side state is reclaimed wholesale at rejoin."""
        if fe.offload is not None and req.uid in fe.offload._recs:
            fe.offload.drop(req.uid)
        req.status = status
        req._q.put(_DONE)
        req._finished.set()

    def _resume_targets(self, history, exclude: Sequence[str]) -> List:
        """Decode-capable survivors, best first: longest cluster-cached
        prefix of ``history`` (the index salvage — a re-prefill there skips
        the cached span), then least loaded."""
        router = self.router
        cands = [r for r in router._decode
                 if r.name not in exclude and router._routable(r)]
        matches = router.index.match(history) \
            if cands and router.config.policy == "cache_aware" else {}
        cands.sort(key=lambda r: (-matches.get(r.name, 0),
                                  r.frontend._inflight))
        return cands

    def _migrate_one(self, replica, fe, req, handoff: Optional[Tuple]) -> None:
        t0 = time.perf_counter()
        history = req._seal()
        if req.cancelled:
            self._close_phase(req, t0,
                              phase="handoff_wait" if handoff is not None
                              else None)
            self.stats.migration_cancels += 1
            self._finalize_handle(fe, req, CANCELLED)
            return
        done = (len(req.tokens) >= req.max_new_tokens
                or (req.eos_token_id is not None and req.tokens
                    and req.tokens[-1] == req.eos_token_id))
        if done:
            # the crash raced the finish line: the stream is complete. Its
            # closing stint ends at the LAST EMISSION — the client-visible
            # end the finished-ledger tiling invariant is defined over —
            # not at the seal stamp a failure-detection window later
            end = req._last_emit_t if req._last_emit_t is not None else t0
            self._close_phase(req, min(end, t0))
            self._finalize_handle(fe, req, FINISHED)
            return
        # attribute the orphaned stint (a queued handoff's wait keeps its
        # handoff_wait label — the status still says prefill) and re-base
        # the phase clock to the seal: the survivor's adoption records the
        # migration stint from exactly here, so the ledger stays gapless
        self._close_phase(req, t0,
                          phase="handoff_wait" if handoff is not None
                          else None)
        # pick the payload ONCE (salvage exports destroy the record)
        mode, payload, nbytes = "reprefill", None, 0
        if handoff is not None:
            # a queued cross-replica handoff: pages already host-side —
            # re-plan it to another decode replica untouched. The import
            # there is this request's migration landing, not a routine
            # handoff wait — the flag makes the ledger say so
            mode, payload = "replan", handoff
            req._migrating = True
        elif fe.offload is not None and fe.offload.salvageable(req.uid):
            pages, logits, nbytes = fe.offload.export_record(req.uid)
            mode, payload = "salvage", (req, pages, logits, history)
        elif fe.offload is not None and req.uid in fe.offload._recs:
            # partial record (shared-prefix pages died with the device):
            # the host copy alone cannot rebuild the KV — re-prefill
            fe.offload.drop(req.uid)
        # the handle stays SEALED until the survivor's engine thread adopts
        # it (the frontend control handlers unseal) — a dead replica's
        # thread blocked inside one last _on_tokens call can never slip a
        # post-snapshot token into the stream the survivor resumes
        last: Optional[BaseException] = None
        tried: List[str] = [replica.name]
        while True:
            targets = self._resume_targets(history, exclude=tried)
            if not targets:
                break
            target = targets[0]
            try:
                if payload is not None:
                    target.frontend.submit_handoff(
                        payload[0], payload[1], payload[2],
                        history=payload[3] if len(payload) > 3 else None)
                else:
                    target.frontend.submit_resume(req, history)
            except (RuntimeError, ValueError) as exc:
                last = exc
                tried.append(target.name)
                continue
            if mode == "replan":
                self.stats.handoffs_replanned += 1
            else:
                self.stats.record_migration(mode, len(history), nbytes)
            req.migrated += 1
            self._migrate_span(req, t0, mode, target.name)
            return
        self.stats.migration_sheds += 1
        log_dist(f"health: no survivor could adopt request {req.uid} from "
                 f"replica {replica.name!r} ({last}); shedding", ranks=[0])
        self._finalize_handle(fe, req, SHED)

    # ------------------------------------------------------------------ #
    # self-healing: rejoin
    # ------------------------------------------------------------------ #

    def rejoin(self, name: str) -> bool:
        """Manually rejoin a drained replica (the ``auto_rejoin=False``
        path). True once the replica is back in rotation; False while its
        old thread is still wedged (or another actor is mid-rejoin)."""
        with self._lock:
            replica = self.router.cluster.replica(name)
            rec = self._recs[name]
            if rec.state == HEALTHY:
                return True
            if rec.state not in (DOWN, DRAINING) or rec.busy:
                return False
            rec.busy = True
        try:
            return self._try_rejoin(replica, rec)
        finally:
            rec.busy = False

    def _try_rejoin(self, replica, rec: _ReplicaRecord) -> bool:
        """Rebuild and re-admit one drained replica. The caller has CLAIMED
        ``rec``; the joins/warmup below block WITHOUT the monitor lock
        (router-side readers of ``_workers``/``replica.frontend`` never
        synchronized on it — the claim is what serializes monitor actors).
        """
        router = self.router
        if replica.role == "prefill":
            if not router._workers[replica.name].join(0):
                return False           # still wedged; retry next poll
        else:
            if not replica.frontend.join(0):
                return False           # still wedged; retry next poll
        with self._lock:
            rec.want_rejoin = False
        self._transition(rec, REJOINING)
        t0 = time.perf_counter()
        engine = replica.engine
        if replica.role != "prefill":
            old = replica.frontend
            # a wedged thread may have raced one request past the failover
            # scrape (popped a control message as the fence landed): with
            # the thread now joined, a second sweep migrates any straggler
            self._collect_and_migrate(replica, old)
            try:
                old.close()            # idempotent teardown; the died-loop
            except RuntimeError:       # re-raise was already handled here
                pass
        # reclaim the dead lifetime's device state: stranded sequences
        # release their pages (prefix-shared ones settle into the radix
        # tree, which survives and replays into the index below)
        for uid in list(engine.scheduler.seqs):
            engine.flush([uid])
        warmup_s = 0.0
        if self.config.rejoin_warmup:
            w0 = time.perf_counter()
            engine.warmup()            # off the hot path; zero new programs
            # warmup() block_until_ready's every program it executes — the
            # delta is real execution time, not dispatch
            warmup_s = time.perf_counter() - w0  # jaxlint: disable=JL001
        stragglers: List = []
        if replica.role == "prefill":
            from deepspeed_tpu.inference.v2.serving.cluster import \
                PrefillWorker
            # a wedged thread may have re-queued requests into the OLD
            # worker after the failover sweep: drain it before discarding
            # (the prefill twin of the decode branch's second
            # _collect_and_migrate); re-placed below once this replica is
            # HEALTHY again, so its own new worker is a valid target
            stragglers = self._drain_worker(router._workers[replica.name])
            w = PrefillWorker(replica, router)
            router._workers[replica.name] = w
            w.start()
        else:
            fe = engine.serving_frontend(
                config=router._serving_cfg,
                uid_base=router.cluster.alloc_uid_base())
            fe.stats.replica = replica.name
            fe._managed = True
            replica.frontend = fe
            router.stats.register_frontend(fe.stats)
            router._register_close_listener(replica)
            fe.start()
        if replica in router._targets:
            router._register_index_listener(replica)   # replays the tree
        with self._lock:
            rec.handled = False
            rec.progress = None
            rec.stall_since = None
            rec.last_ok = time.perf_counter()
            self.stats.record_rejoin(warmup_s)
        if _tracer.enabled:
            _tracer.add("serve/health/rejoin", t0, time.perf_counter(),
                        lane="serve/health", replica=replica.name,
                        warmup_ms=round(1e3 * warmup_s, 3))
        self._transition(rec, HEALTHY)
        if stragglers:
            self._requeue_prefill(stragglers, exclude="")
        log_dist(f"health: replica {replica.name!r} rejoined "
                 f"(warmup {1e3 * warmup_s:.0f} ms)", ranks=[0])
        return True
