"""SLO-aware serving frontend: the persistent MII/FastGen layer over the v2
engine.

``ServingFrontend`` turns the batch-script engine into a server: a dedicated
engine thread (``dstpu-serve``) owns the ``DecodePipeline`` and runs the
continuous-batching loop; clients — sync threads or asyncio tasks — call
:meth:`submit` from anywhere and read a token stream off the returned
:class:`RequestHandle`.

The loop is iteration-level continuous batching at pipeline *run boundaries*
(Orca's iteration-level scheduling on PR 3's double-buffered hot path): each
iteration drains control traffic, executes one admission plan
(``admission.py`` — shed / restore / preempt / admit), runs prefill passes
for the admitted batch (Dynamic SplitFuse composition, cancellation polled
at pass boundaries), then drives one ``decode_slice``-step ``run()`` burst.
Tokens drain one step late (PR 3's overlap discipline); the per-step
``on_tokens`` callback only stamps clocks, appends ints and feeds stream
queues — no device fetch, no formatting — so serving adds zero host syncs to
the gated hot path. Admission and retirement move the live set between pow2
buckets the engine pre-compiled (``engine.warmup()``), so steady-state
admission adds ZERO compiles after warmup (gated by
``serving_bench.py --frontend``).

Under KV-pool pressure the admission plan PREEMPTS low-priority victims by
offloading their private KV tail to pinned host buffers
(``kv_offload.py`` — vLLM swap-out, not drop-and-recompute), restoring
byte-identically on readmit; recompute is the per-victim fallback when host
capacity is exhausted, and a config-selected baseline. Request lifecycle
spans (``serve/req/{queued,prefill,decode,preempted,restore}``) land on a
per-request trace lane and the aggregate counters in
``monitor/serving.FrontendStats`` (``serve/frontend/*``); docs/SERVING.md
"Frontend" walks the whole design.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.v2.config_v2 import ServingConfig
from deepspeed_tpu.inference.v2.serving.admission import AdmissionController
from deepspeed_tpu.inference.v2.serving.kv_offload import KVOffloadManager
from deepspeed_tpu.monitor.serving import FrontendStats
from deepspeed_tpu.monitor.trace import tracer as _tracer
from deepspeed_tpu.utils.fault_injection import maybe_fail
from deepspeed_tpu.utils.threads import make_lock, thread_role

_DONE = object()      # stream sentinel

# request lifecycle states
QUEUED = "queued"
PREFILL = "prefill"
DECODING = "decoding"
PREEMPTED = "preempted"
FINISHED = "finished"
CANCELLED = "cancelled"
SHED = "shed"
_TERMINAL = (FINISHED, CANCELLED, SHED)


#: process-lifetime flow-id mint. trace_id CANNOT be the uid: uid bases
#: restart with every cluster/frontend lifetime while tracer rings (and
#: the exporter's flow synthesizer) span the whole process, so uid reuse
#: across successive clusters — every bench rep, any in-process serving
#: restart — would merge unrelated requests' hops into one bogus chain.
#: The pid prefix keeps ids distinct across the subprocess workers whose
#: files ``trace_merge.py`` stitches into one timeline.
_TRACE_IDS = itertools.count(1)


def _mint_trace_id() -> int:
    # pid <= 2^22 (linux pid_max ceiling) and a 31-bit counter keep ids
    # inside the 2^53 exact-double range Chrome-trace ids must survive;
    # the counter wraps only past 2.1e9 submits per process
    return (os.getpid() << 31) | (next(_TRACE_IDS) & 0x7FFFFFFF)


def attribution_epsilon(client_s: float) -> float:
    """The ONE tolerance for "this request's ledger sums to its
    client-measured latency": max(5 ms, 1%). Shared by the
    ``serve/slo/attr_consistent`` stat (``_finalize``) and the bench
    attribution gates (``serving_bench._attribution_gate``) so the two can
    never quietly measure different things (docs/OBSERVABILITY.md
    "SLO-miss attribution")."""
    return max(0.005, 0.01 * client_s)


class RequestHandle:
    """One submitted request: a thread-safe token stream plus lifecycle
    state. Clients iterate tokens (``for t in handle`` or ``async for t in
    handle.astream()``), or block for the full result; ``cancel()`` models a
    client disconnect — the engine thread retires the uid at the next run
    boundary and releases its KV through ``scheduler.flush``."""

    def __init__(self, uid: int, prompt: np.ndarray, cls, max_new_tokens: int,
                 eos_token_id: Optional[int], arrival_t: float,
                 adapter: Optional[str] = None):
        self.uid = uid
        #: LoRA adapter (tenant identity) this request decodes under; None =
        #: the base model. The engine thread acquires/releases the registry
        #: binding around the request's decoding lifetime (``_lora_held``).
        self.adapter = adapter
        self._lora_held = False
        #: process-unique request flow id, minted at submit and carried by
        #: every hop span (router placement, prefill, KV handoff, decode
        #: stints, failover migration) — the exporter binds spans sharing it
        #: into one Perfetto flow chain across lanes/threads/files. NOT the
        #: uid (uid bases restart per cluster lifetime; see
        #: ``_mint_trace_id``) — but like the uid it rides the handle, so a
        #: migrated request keeps it on the survivor and the chain survives
        #: failover.
        self.trace_id = _mint_trace_id()
        self.prompt = prompt
        self.cls = cls                      # PriorityClassConfig
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.arrival_t = arrival_t          # perf_counter at submit
        self.tokens: List[int] = []
        self.status = QUEUED
        self.ttft_ms: Optional[float] = None
        self.tbt_ms: List[float] = []       # gaps between streamed tokens
        self.preemptions = 0
        self.migrated = 0                   # replica-failure migrations
        #: a named, non-swallowed failure (e.g. an exhausted disaggregated
        #: handoff retry budget) — re-raised by result()
        self.error: Optional[BaseException] = None
        self._q: "queue.Queue" = queue.Queue()
        self._cancel = threading.Event()
        self._finished = threading.Event()
        # migration seal (serving/health.py): emission happens under this
        # lock, and failover takes it to seal the handle + snapshot
        # ``tokens`` at one exact instant — the stream a survivor resumes
        # from can never race a straggling emission off the dead replica
        self._emit_lock = make_lock("serving.request.emit")
        self._sealed = False
        # engine-thread bookkeeping (phase stamps for spans + victim order)
        self.admit_t: Optional[float] = None
        self.preempt_t: Optional[float] = None
        self._phase_t0 = arrival_t
        self._last_emit_t: Optional[float] = None
        self._resume_tokens: Optional[np.ndarray] = None   # recompute restore
        self._stop_status = FINISHED            # set on mid-run retirement
        #: set by failover while a RE-PLANNED cross-replica handoff (pages
        #: already host-side, no salvage payload) is in flight to a
        #: survivor: the decode-side import labels its stint ``migration``
        #: instead of ``handoff_wait`` and clears the flag
        self._migrating = False
        #: the phase ledger: (phase, t0, t1) stints built from the SAME
        #: perf stamps the serve/req trace spans record — where this
        #: request's time went, summing to the client-measured latency for
        #: finished requests. ``None`` when attribution is disabled
        #: (``ServingConfig.attribution``).
        self._ledger: Optional[List[tuple]] = []

    # -- phase attribution (docs/OBSERVABILITY.md "SLO-miss attribution") -- #

    def _ledger_add(self, phase: str, t0: float, t1: float) -> None:
        if self._ledger is not None:
            self._ledger.append((phase, t0, t1))

    def timeline(self) -> List[tuple]:
        """The per-request phase ledger: ``(phase, t0, t1)`` stints in
        record order (``time.perf_counter`` endpoints — the same stamps the
        ``serve/req/*`` trace spans carry). Phases: ``queued``,
        ``admission``, ``prefill``, ``handoff_wait``, ``decode``,
        ``preempted``, ``restore``, ``migration``. For a finished request
        the stints tile ``arrival_t .. last-emission`` with no gaps, so
        their durations sum to the client-measured latency
        (TTFT + Σ TBT). Empty when attribution is disabled."""
        return list(self._ledger or ())

    def attribution(self) -> Dict[str, object]:
        """Phase attribution summary derived from :meth:`timeline`:
        per-phase totals, the dominant phase (where most of the latency
        went — the ``serve/slo/*`` bucketing key for SLO misses), the
        ledger total, and the client-measured latency (arrival to last
        emission; ``None`` before any token)."""
        phases: Dict[str, float] = {}
        for phase, t0, t1 in (self._ledger or ()):
            phases[phase] = phases.get(phase, 0.0) + max(0.0, t1 - t0)
        total = sum(phases.values())
        client = (self._last_emit_t - self.arrival_t
                  if self._last_emit_t is not None else None)
        dominant = max(phases, key=lambda p: phases[p]) if phases else None
        return {"phases": phases, "dominant": dominant,
                "total_s": total, "client_s": client,
                "residual_s": None if client is None else client - total}

    # -- client surface ------------------------------------------------ #

    def cancel(self) -> None:
        self._cancel.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    @property
    def finished(self) -> bool:
        return self._finished.is_set()

    def __iter__(self):
        while True:
            t = self._q.get()
            if t is _DONE:
                return
            yield t

    async def astream(self):
        """Async token stream (``async for tok in handle.astream()``): each
        blocking queue read rides the event loop's default executor, so the
        loop never blocks on the engine thread."""
        import asyncio
        loop = asyncio.get_running_loop()
        while True:
            t = await loop.run_in_executor(None, self._q.get)
            if t is _DONE:
                return
            yield t

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request reaches a terminal state; returns the
        generated tokens (possibly partial for cancelled/shed requests).
        A request shed with a NAMED failure (``self.error``, e.g. an
        exhausted handoff retry budget) re-raises it here — surfaced, never
        swallowed."""
        if not self._finished.wait(timeout):
            raise TimeoutError(f"request {self.uid} still {self.status} "
                               f"after {timeout}s")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def _seal(self) -> "np.ndarray":
        """Seal emission and snapshot ``prompt + tokens`` atomically — the
        exact resume point a failover migration continues from
        (serving/health.py). The survivor unseals on adoption."""
        with self._emit_lock:
            self._sealed = True
            return np.concatenate(
                [self.prompt, np.asarray(self.tokens, np.int32)])


class ServingFrontend:

    def __init__(self, engine, config=None, uid_base: int = 1 << 20):
        cfg = config if config is not None else engine.config.serving
        if isinstance(cfg, dict):
            cfg = ServingConfig(**cfg)
        if cfg.preemption != "none" and engine.scheduler.window is not None:
            raise NotImplementedError(
                "preemption with a sliding-window page ring is not wired "
                "(the logical block list aliases physical pages) — run "
                "preemption='none'")
        if cfg.preemption == "recompute" and getattr(engine, "lora", None) \
                is not None:
            raise NotImplementedError(
                "preemption='recompute' with LoRA serving is not wired: "
                "decode-written KV carries the adapter's k/v deltas, and a "
                "recompute restore re-prefills it base-only — a silently "
                "byte-divergent stream; run preemption='offload' (byte-exact "
                "restore) or 'none'")
        self.engine = engine
        self.config = cfg
        # phase-ledger recording (RequestHandle.timeline / serve/slo/*);
        # off = handles carry no ledger and misses go unattributed
        self._attribution = bool(getattr(cfg, "attribution", True))
        self.stats = FrontendStats([c.name for c in cfg.classes])
        # KV-pool gauges (monitor/serving.py): pool dtype + bytes/token are
        # static facts of the engine build; the capacity doubling an int8
        # pool buys (same HBM budget -> ~2x+ blocks) is then observable in
        # the same serve/frontend/* surface the latency counters live on
        kvc = engine.kv.config
        import jax.numpy as jnp
        self.stats.set_kv_pool(
            dtype_bits=8 if kvc.quantized
            else 8 * jnp.dtype(kvc.dtype).itemsize,
            bytes_per_token=kvc.bytes_per_block() / kvc.block_size,
            pool_tokens=engine.allocator.total_blocks * kvc.block_size,
            max_context=engine.config.state_manager.max_context,
            block_size=kvc.block_size)
        self.stats.kv_free_blocks = engine.allocator.free_blocks
        self.stats.kv_resident_seqs = len(engine.scheduler.seqs)
        self.admission = AdmissionController(engine, cfg)
        self.offload: Optional[KVOffloadManager] = (
            KVOffloadManager(engine, max_bytes=cfg.max_offload_bytes,
                             max_buffers=cfg.offload_buffers)
            if cfg.preemption == "offload" else None)
        if cfg.spec:
            self._pipe = engine.decode_pipeline(())
        else:
            # per-frontend spec opt-out (ServingConfig.spec): greedy
            # serving pinned to the plain pipeline even on a spec-enabled
            # engine
            from deepspeed_tpu.inference.v2.pipeline import DecodePipeline
            self._pipe = DecodePipeline(engine, ())
        # speculative pipeline: steps emit token BATCHES (accepted draft
        # prefix + bonus) — on_tokens shape and TBT accounting branch on it
        self._spec = bool(getattr(self._pipe, "spec", False))
        self._ctl: "queue.Queue" = queue.Queue()
        self._reqs: Dict[int, RequestHandle] = {}       # every non-terminal
        self._live: Dict[int, RequestHandle] = {}       # in the pipeline
        self._preempted: Dict[int, RequestHandle] = {}
        self._run_stopped: List[RequestHandle] = []     # retired mid-run
        # thread-safe counter; ``uid_base`` keeps a cluster's frontends
        # (including a rejoin-rebuilt one) in DISJOINT uid spaces so a
        # migrated request can never collide on its new replica
        self._uid_iter = itertools.count(int(uid_base))
        # in-flight count bumped in submit() BEFORE the control message is
        # posted: drain() polling len(_reqs)/_ctl alone races the window
        # where the engine thread has popped the message but not yet filed
        # the handle
        self._inflight = 0
        self._inflight_lock = make_lock("serving.frontend.inflight")
        # cross-replica handoffs awaiting KV import (engine thread only —
        # failover's disown() writes too, but only once the loop is fenced
        # or dead, so the two writers are temporally exclusive by design)
        self._handoffs: List[tuple] = []  # threadlint: guarded-by=none
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._loop_exc: Optional[BaseException] = None
        self._closed = False
        # fenced = declared down by a health monitor: the loop (even a
        # wedged one that wakes later) must emit nothing further — every
        # in-flight stream now belongs to the replica it migrated to
        self._fenced = False
        # managed = a router health monitor owns this frontend's failure
        # handling: a crashed loop must NOT close its streams (that would
        # terminate clients the monitor is about to migrate)
        self._managed = False
        self._fault_site = "serve.engine_step"          # set at start()
        self._close_listeners: List = []                # called at close()

    # ------------------------------------------------------------------ #
    # client surface (any thread / asyncio)
    # ------------------------------------------------------------------ #

    def submit(self, prompt: Sequence[int], priority: Optional[str] = None,
               max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               adapter: Optional[str] = None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Enqueue one request; returns immediately with its stream handle.
        ``priority`` names a configured class; admission decides admit /
        hold / shed against that class's TTFT/TBT SLOs. ``adapter`` names a
        registered LoRA adapter to decode under (the tenant identity);
        ``tenant`` overrides the identity used for class mapping when it
        differs from the adapter name. An explicit ``priority`` wins;
        otherwise ``ServingConfig.tenant_classes`` maps the tenant to its
        class (default "standard")."""
        if self._closed or self._fenced:
            raise RuntimeError("frontend is closed"
                               if self._closed else
                               "frontend is fenced (replica down)")
        cls = self.config.class_for(priority,
                                    tenant if tenant is not None else adapter)
        if adapter is not None:
            lora = getattr(self.engine, "lora", None)
            if lora is None:
                raise RuntimeError(
                    "this engine serves no LoRA adapters — enable "
                    "RaggedInferenceEngineConfig.lora")
            lora.rank(adapter)      # raises for an unregistered adapter
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        self.check_budget(len(prompt), int(max_new_tokens))
        req = RequestHandle(next(self._uid_iter), prompt, cls,
                            int(max_new_tokens), eos_token_id,
                            time.perf_counter(), adapter=adapter)
        if not self._attribution:
            req._ledger = None
        with self._inflight_lock:
            self._inflight += 1
        self._ctl.put(("submit", req))
        return req

    def check_budget(self, n_prompt: int, max_new_tokens: int,
                     max_context: Optional[int] = None,
                     total_blocks: Optional[int] = None) -> None:
        """Raise ValueError unless a request of this shape can EVER be
        served here: every run-boundary reservation must fit max_context (a
        row one token from its budget still funds a whole slice at run
        start; speculative slices reserve ``decode_slice * (k + 1) + 1``),
        and the full KV lifetime must fit the pool — a request admitted
        optimistically past it would grow, be preempted, and wedge forever
        un-restorable. ONE home for the budget math: ``submit`` checks this
        frontend, and a ``ServingRouter`` passes the WEAKEST decode
        replica's ``max_context``/``total_blocks`` so a handoff can land on
        any of them."""
        sm = self.engine.config.state_manager
        if max_context is None:
            max_context = sm.max_context
        if total_blocks is None:
            total_blocks = self.engine.allocator.total_blocks
        slice_tokens = self.admission.slice_tokens
        need = n_prompt + max_new_tokens + slice_tokens
        if need > max_context:
            raise ValueError(
                f"prompt ({n_prompt}) + max_new_tokens ({max_new_tokens}) "
                f"+ slice reservation ({slice_tokens}) = {need} "
                f"exceeds max_context {max_context}")
        bs = self.engine.kv.config.block_size
        if -(-need // bs) > total_blocks:
            raise ValueError(
                f"request needs {-(-need // bs)} KV blocks at its budget but "
                f"the pool holds {total_blocks}")

    def submit_handoff(self, req: RequestHandle, pages, logits,
                       history=None) -> None:
        """Adopt a request PREFILLED ON ANOTHER REPLICA — the decode half of
        the disaggregated prefill/decode topology (``serving/cluster.py``).
        ``pages``/``logits`` are ``engine.export_kv``'s output from the
        prefill engine; the engine thread imports them (``engine.import_kv``
        — fresh pool ids, byte-exact content, re-seeded bootstrap row, the
        same restore discipline preemption uses) once the pool funds the
        pages plus a decode slice of growth, then admits the row directly to
        the decode pipeline. The handle's stream/cancel/result semantics are
        unchanged: tokens flow on this replica as if it had prefilled
        locally.

        ``history`` overrides the token record the import is keyed on
        (default: ``req.prompt``) — a failover SALVAGE of a
        preempt-offloaded victim (serving/health.py) hands off
        mid-generation, so its KV covers prompt + generated-so-far."""
        if self._closed or self._fenced:
            raise RuntimeError("frontend is closed"
                               if self._closed else
                               "frontend is fenced (replica down)")
        with self._inflight_lock:
            self._inflight += 1
        self._ctl.put(("handoff", (req, pages, logits, history)))

    def submit_resume(self, req: RequestHandle, history) -> None:
        """Adopt a request MIGRATED off a failed replica with no salvageable
        KV (serving/health.py): ``history`` is the sealed
        prompt + emitted-tokens snapshot. The engine thread files it as a
        recompute-preempted victim, so the existing restore path re-prefills
        the full history (radix-cache matches skip whatever a shared prefix
        already covers here) and the stream resumes byte-identically from
        the last emitted token. Raises when this replica cannot EVER fund
        the request (the caller tries the next survivor)."""
        if self._closed or self._fenced:
            raise RuntimeError("frontend is closed"
                               if self._closed else
                               "frontend is fenced (replica down)")
        self.check_budget(len(history),
                          max(1, req.max_new_tokens - len(req.tokens)))
        with self._inflight_lock:
            self._inflight += 1
        self._ctl.put(("resume", (req, np.asarray(history, np.int32))))

    def swap_weights(self, new_weights, version: Optional[int] = None,
                     timeout: Optional[float] = None) -> int:
        """Swap the engine's weights in place at the next run boundary —
        the serving half of the colocated rollout loop
        (``runtime/colocated.py``; docs/SERVING.md "Colocated rollout").

        The swap executes ON the engine thread between decode slices,
        exactly where preemption executes: every live request is
        recompute-preempted (KV dropped, prompt + tokens-so-far remembered;
        restore re-prefills under the NEW weights), offload-preempted
        victims and pending cross-replica handoffs convert to recompute
        victims too (their parked KV pages are old-weight state), and the
        prefix cache flushes by weight-version stamp. Adapter-bound live
        requests shed honestly — the same rule as ``_preempt``'s
        host-capacity fallback (a base-only re-prefill of adapter-delta KV
        would silently diverge). No stream is ever silently served across
        the boundary with stale KV.

        Blocks until the swap is applied (or refused); a refusal raises
        here and the loop keeps serving the OLD weights — engine validation
        happens before any rebinding. Called inline when no engine thread
        is running (synchronous ``step()`` drivers). Returns the new
        ``weight_version``."""
        if self._closed or self._fenced:
            raise RuntimeError("frontend is closed"
                               if self._closed else
                               "frontend is fenced (replica down)")
        if self._thread is None or not self._thread.is_alive():
            return self._apply_swap(new_weights, version)
        done = threading.Event()
        box: Dict[str, object] = {}
        self._ctl.put(("swap", (new_weights, version, done, box)))
        if not done.wait(timeout if timeout is not None else 120.0):
            raise TimeoutError(
                "weight swap not applied within the timeout — the engine "
                "thread is wedged or a decode slice is extremely long")
        if "exc" in box:
            raise box["exc"]
        return box["version"]    # type: ignore[return-value]

    @property
    def outstanding(self) -> int:
        """Non-terminal requests (queued + prefilling + decoding +
        preempted)."""
        return len(self._reqs)

    def start(self) -> "ServingFrontend":
        if self._thread is not None:
            raise RuntimeError("frontend already started")
        # replica-scoped fault site (utils/fault_injection.py): a chaos plan
        # can target ONE replica's loop deterministically
        if self.stats.replica:
            self._fault_site = f"serve.engine_step.{self.stats.replica}"
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="dstpu-serve", daemon=True)
        self._thread.start()
        return self

    def fence(self) -> None:
        """Declare this frontend DOWN (serving/health.py): stop the loop and
        guarantee that nothing further is emitted into any stream — even if
        the engine thread is wedged inside a device call and only wakes
        later, ``_on_tokens``/``step`` observe the fence and drop
        everything. Migration then owns the in-flight handles."""
        self._fenced = True
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the engine thread to exit (True = it has; a wedged
        thread may outlive ``timeout`` — rejoin waits for a real join)."""
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def add_close_listener(self, fn) -> None:
        """``fn()`` runs at the START of ``close()`` — the router uses this
        to evict a closed replica's prefix-index entries and stop routing to
        it (a closed frontend must not keep attracting placements)."""
        self._close_listeners.append(fn)

    # -- failover support (serving/health.py; fenced/dead frontends only) -- #

    def _scrape_control(self) -> List[tuple]:
        """Drain the control queue WITHOUT handling (failover only: the
        loop is fenced or dead, and each undelivered message's request must
        migrate instead of vanishing)."""
        out = []
        while True:
            try:
                out.append(self._ctl.get_nowait())
            except queue.Empty:
                return out

    def disown(self, req: RequestHandle):
        """Remove every host-side trace of ``req`` from this fenced/dead
        frontend — dicts, admission queue, in-flight accounting — WITHOUT
        touching engine/device state (the dead engine is reclaimed
        wholesale at rejoin). Returns the request's pending handoff record,
        if any, so the migration can re-plan it."""
        uid = req.uid
        self._reqs.pop(uid, None)
        self._live.pop(uid, None)
        self._preempted.pop(uid, None)
        self.admission.remove(req)
        rec = None
        if self._handoffs:
            kept = []
            for h in self._handoffs:
                if h[0].uid == uid:
                    rec = h
                else:
                    kept.append(h)
            self._handoffs = kept
        with self._inflight_lock:
            self._inflight -= 1
        return rec

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until every submitted request reaches a terminal state (the
        loop keeps serving). True = drained; False = timed out."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._inflight > 0:
            if self._loop_exc is not None:
                raise RuntimeError("serving loop died") from self._loop_exc
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)
        return True

    def close(self) -> None:
        """Stop the engine thread and cancel whatever is still in flight
        (KV flushed, offload buffers released, streams closed). Idempotent:
        double-close and close-before-first-submit are no-ops — a cluster
        teardown sweeping replicas must never trip over one it (or a test)
        already closed. A died engine thread still raises, once, with the
        teardown fully finished first."""
        if self._closed:
            return
        for fn in self._close_listeners:
            fn()
        self._close_listeners = []
        self._stop.set()
        if self._thread is not None:
            # a FENCED frontend may hold a permanently wedged thread (the
            # stall failure mode the health monitor fences around): close
            # must not hang the whole cluster teardown on it. Its requests
            # were already migrated; skip the engine-touching teardown the
            # wedged thread could still race and leave state to rejoin.
            self._thread.join(5.0 if self._fenced else None)
            if self._thread.is_alive():
                from deepspeed_tpu.utils.logging import log_dist
                log_dist("frontend close: engine thread still wedged after "
                         "fence; abandoning it (daemon) without teardown",
                         ranks=[0])
                self._closed = True
                if self._loop_exc is not None:
                    exc, self._loop_exc = self._loop_exc, None
                    raise RuntimeError("serving loop died") from exc
                return
            self._thread = None
        # engine-thread state is safe to touch now (thread joined / never ran)
        self._drain_control()
        for req in list(self._reqs.values()):
            self._teardown(req, CANCELLED)
        if self.offload is not None:
            self.offload.close()
        self._closed = True
        if self._loop_exc is not None:
            exc, self._loop_exc = self._loop_exc, None
            raise RuntimeError("serving loop died") from exc

    def __enter__(self) -> "ServingFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def write_monitor_events(self, monitor, step: int = 0) -> None:
        """Emit the ``serve/frontend/*`` counters through a ``monitor/``
        backend (``MonitorMaster.write_events`` shape)."""
        monitor.write_events(self.stats.events(step))

    # ------------------------------------------------------------------ #
    # the engine thread
    # ------------------------------------------------------------------ #

    @thread_role("dstpu-serve")
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                if not self.step():
                    if self._fenced:
                        break                 # failover owns the queue now
                    try:                      # idle: block on control traffic
                        msg = self._ctl.get(timeout=self.config.idle_wait_s)
                    except queue.Empty:
                        continue
                    if self._fenced:
                        self._ctl.put(msg)    # failover's scrape owns it
                        break
                    self._handle(msg)
        except BaseException as exc:          # surface at drain()/close() —
            self._loop_exc = exc              # a dead server must not hang
            if not self._managed:
                # unmanaged: a dead server must not hang its clients. Under
                # a router health monitor the streams stay OPEN — failover
                # migrates them to a survivor (or terminal-states them)
                for req in list(self._reqs.values()):
                    req._q.put(_DONE)         # unblock stream readers
                    req._finished.set()

    def step(self) -> bool:
        """ONE frontend iteration: control drain -> cancellation sweep ->
        handoff imports -> admission plan -> prefill -> one decode slice.
        Public so tests and deterministic bench phases can drive the loop
        synchronously (no thread); returns False when the iteration found
        no work (idle)."""
        # chaos site (raise = crash this loop, stall = wedge it); the fence
        # check sits AFTER it so a stalled thread that wakes post-failover
        # bails before touching any state migration already disowned
        maybe_fail(self._fault_site)
        if self._fenced:
            return False
        self._drain_control()
        self._sweep_cancels()
        worked = self._execute_handoffs()
        worked = self._admission_round() or worked
        if self._pipe.uids:
            self._decode_slice()
            worked = True
        return worked

    def _handle(self, msg) -> None:
        kind, payload = msg
        if kind == "submit":
            req = payload
            self._reqs[req.uid] = req
            self.stats.record_submit(req.cls.name)
            if not self.admission.enqueue(req):
                self._finalize(req, SHED)     # queue full: immediate shed
        elif kind == "handoff":
            req, pages, logits, history = payload
            with req._emit_lock:
                req._sealed = False    # adoption: emission is ours now (a
                # no-op for normal disagg handoffs, which were never sealed)
            self._reqs[req.uid] = req
            self.stats.record_submit(req.cls.name)
            if len(self._handoffs) >= self.config.max_queue:
                # back-pressure: every held handoff pins a full sequence's
                # KV pages in host memory — past the same bound the local
                # queue sheds at, shed rather than accumulate without limit
                self._finalize(req, SHED)
            else:
                self._handoffs.append((req, pages, logits, history))
        elif kind == "resume":
            # failover migration (serving/health.py): adopt as a
            # recompute-preempted victim — the restore path re-prefills the
            # sealed history and the stream resumes from its last token
            req, history = payload
            with req._emit_lock:
                req._sealed = False    # adoption: emission is ours now
            self._reqs[req.uid] = req
            self.stats.record_submit(req.cls.name)
            req._resume_tokens = history
            now = time.perf_counter()
            # failover re-home: the ``migration`` stint runs from the seal
            # stamp (health.py closes the orphaned phase there and re-bases
            # _phase_t0) to this adoption on the survivor's engine thread
            self._span(req, "migration", req._phase_t0, now)
            req.status = PREEMPTED
            req.preempt_t = req._phase_t0 = now
            self._preempted[req.uid] = req
        elif kind == "swap":
            # weight swap (colocated rollout): executes HERE, on the engine
            # thread between decode slices — the same run boundary
            # preemption owns. A refusal (engine-side validation) reports
            # to the waiting caller and the loop keeps serving old weights.
            new_weights, version, done, box = payload
            try:
                box["version"] = self._apply_swap(new_weights, version)
            except BaseException as exc:
                box["exc"] = exc
            finally:
                done.set()
        # cancellation rides the handle's event (no message): the sweeps /
        # on_tokens observe it within one iteration, and an idle loop ticks
        # every idle_wait_s — disconnects are never waited on indefinitely

    def _drain_control(self) -> None:
        while True:
            try:
                self._handle(self._ctl.get_nowait())
            except queue.Empty:
                return

    def _sweep_cancels(self) -> None:
        """Client disconnects for requests NOT currently decoding (those are
        caught token-by-token in ``_on_tokens``): queued requests leave the
        admission queue; preempted ones drop their offloaded pages / resume
        record and flush their kept KV."""
        for req in list(self._reqs.values()):
            if req.cancelled and req.status in (QUEUED, PREEMPTED):
                self._teardown(req, CANCELLED)

    def _teardown(self, req: RequestHandle, status: str) -> None:
        """Release every resource a request holds in its CURRENT lifecycle
        stage, then finalize. The one path cancellation, shedding and
        close-time abandonment all funnel through — the allocator-leak
        regression test cancels at every stage against this."""
        uid = req.uid
        if req.status == QUEUED:
            self.admission.remove(req)
        if self._handoffs:
            # a handoff still awaiting import holds only host arrays — drop
            # the record so a later import cannot resurrect a finalized uid
            self._handoffs = [h for h in self._handoffs if h[0].uid != uid]
        if uid in self._live:
            self._pipe.retire([uid])
            del self._live[uid]
        if uid in self._preempted:
            del self._preempted[uid]
            if self.offload is not None and uid in self.offload._recs:
                self.offload.drop(uid)
        if uid in self.engine.scheduler.seqs:
            self.engine.flush([uid])
        self._finalize(req, status)

    def _finalize(self, req: RequestHandle, status: str) -> None:
        self._lora_release(req)
        now = time.perf_counter()
        if req.status == DECODING:
            # the ledger's final decode stint ends at the LAST-EMISSION
            # stamp (the client-visible end the SLOs are defined over), so
            # a finished request's stints sum to TTFT + Σ TBT exactly; the
            # trace span keeps the full stint through run-boundary
            # retirement — both read the same stamp set
            self._span(req, "decode", req._phase_t0, now, ledger=False)
            end = req._last_emit_t if (status == FINISHED
                                       and req._last_emit_t is not None
                                       and req._last_emit_t >= req._phase_t0) \
                else now
            req._ledger_add("decode", req._phase_t0, end)
        req.status = status
        self._reqs.pop(req.uid, None)
        if status == FINISHED:
            slo_met = (req.ttft_ms is not None
                       and req.ttft_ms <= req.cls.ttft_slo_ms
                       and (not req.tbt_ms or float(np.percentile(
                            np.asarray(req.tbt_ms, np.float64), 95))
                            <= req.cls.tbt_slo_ms))
            self.stats.record_complete(req.cls.name, req.ttft_ms, req.tbt_ms,
                                       len(req.tokens), slo_met)
            if not slo_met:
                # SLO-miss attribution: bucket the miss by where the
                # latency actually went (serve/slo/* — docs/OBSERVABILITY.md)
                attr = req.attribution()
                client = attr["client_s"]
                consistent = (client is not None
                              and abs(attr["total_s"] - client)
                              <= attribution_epsilon(client))
                self.stats.record_slo_miss(
                    req.cls.name, attr["dominant"] or "unattributed",
                    consistent)
        elif status == SHED:
            self.stats.record_shed(req.cls.name)
            if _tracer.enabled:
                _tracer.instant("serve/req/shed", lane=f"serve/req/u{req.uid}",
                                uid=req.uid, trace_id=req.trace_id,
                                cls=req.cls.name)
        elif status == CANCELLED:
            self.stats.record_cancel(req.cls.name)
            if _tracer.enabled:
                _tracer.instant("serve/req/cancelled",
                                lane=f"serve/req/u{req.uid}", uid=req.uid,
                                trace_id=req.trace_id)
        req._q.put(_DONE)
        req._finished.set()
        with self._inflight_lock:
            self._inflight -= 1

    def _span(self, req: RequestHandle, phase: str, t0: float,
              t1: float, ledger: bool = True) -> None:
        """One phase stint: a ``serve/req/<phase>`` span on the request's
        trace lane AND (unless ``ledger=False`` — used where the ledger
        entry needs different endpoints or a different phase name) an
        attribution-ledger entry, from one set of perf stamps."""
        if ledger:
            req._ledger_add(phase, t0, t1)
        if _tracer.enabled:
            _tracer.add(f"serve/req/{phase}", t0, t1,
                        lane=f"serve/req/u{req.uid}", uid=req.uid,
                        trace_id=req.trace_id, cls=req.cls.name)

    def _admit_pipe(self, req: RequestHandle) -> None:
        """Admit to the decode pipeline; a speculative pipeline gets the
        request's full prompt + generated history so the n-gram proposer
        can match across preempt/restore boundaries (the scheduler's
        recorded history misses device-generated tokens)."""
        if self._spec:
            self._pipe.admit([req.uid], histories=[np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])])
        else:
            self._pipe.admit([req.uid])

    # ------------------------------------------------------------------ #
    # LoRA adapter bindings (engine thread only)
    # ------------------------------------------------------------------ #

    def _lora_acquire(self, req: RequestHandle) -> bool:
        """Bind ``req``'s adapter and make its pages resident (fault-in from
        host under pool pressure happens HERE, in the admission/restore
        round — never inside a decode slice, so a cold adapter fault cannot
        stall a hot tenant's token cadence). False means the pool cannot
        fund the adapter right now (every resident adapter is pinned by
        in-flight rows): the caller defers the request and retries when
        refcounts drop. Chaos faults (``serve.lora_fault``) propagate —
        the loop's crash semantics, same as a KV fetch fault."""
        if req.adapter is None or req._lora_held:
            return True
        try:
            self.engine.lora.acquire(req.uid, req.adapter)
        except RuntimeError:          # pool pressure raced the plan: hold
            return False
        req._lora_held = True
        return True

    def _lora_release(self, req: RequestHandle) -> None:
        """Drop the adapter binding (idempotent). The pages stay resident —
        LRU-cached for the tenant's next request — until pool pressure
        evicts them to pinned host buffers."""
        if req._lora_held:
            self.engine.lora.release(req.uid)
            req._lora_held = False

    # ------------------------------------------------------------------ #
    # cross-replica handoffs (disaggregated prefill/decode)
    # ------------------------------------------------------------------ #

    def _execute_handoffs(self) -> bool:
        """Import pending cross-replica handoffs the pool can fund: fresh
        pages for the KV content plus one decode slice of growth, a decode
        row and a tracked slot — the same budget math the admission plan
        simulates, so a handoff never starves the live set's next slice.
        Unfundable handoffs stay queued and retry next iteration (capacity
        returns through retirement/preemption like any admission)."""
        if not self._handoffs:
            return False
        sched = self.engine.scheduler
        sm = self.engine.config.state_manager
        slice_tokens = self.admission.slice_tokens
        did = False
        held = []
        for rec in self._handoffs:
            if self._fenced:
                held.append(rec)
                continue
            req, pages, logits, history = rec
            if req.cancelled:
                self._finalize(req, CANCELLED)
                did = True
                continue
            need = len(pages) + self.admission._blocks(slice_tokens)
            if need > self.engine.allocator.total_blocks:
                # can NEVER fund on this replica (router validation should
                # have caught it) — shed now rather than hold forever
                self._finalize(req, SHED)
                did = True
                continue
            budget = sched.available_blocks \
                - sched.blocks_needed(list(self._live), slice_tokens)
            if (need > budget
                    or len(self._live) >= sm.max_ragged_sequence_count
                    or len(sched.seqs) >= sm.max_tracked_sequences):
                held.append(rec)
                continue
            if not self._lora_acquire(req):
                held.append(rec)     # adapter pool pressure: retry later
                continue
            t0 = time.perf_counter()
            try:
                self.engine.import_kv(
                    req.uid,
                    req.prompt if history is None else history,
                    pages, logits)
            except (ValueError, RuntimeError) as exc:
                # a malformed/oversized handoff must close ONE stream, not
                # kill the replica's serving loop (and every other stream)
                from deepspeed_tpu.utils.logging import log_dist
                log_dist(f"handoff import for uid {req.uid} failed: {exc}; "
                         "shedding the request", ranks=[0])
                self._finalize(req, SHED)
                did = True
                continue
            t1 = time.perf_counter()
            # import-work span first, then the enclosing wait (inner E
            # before outer E at the shared end ts): ``handoff_wait`` runs
            # from the prefill replica's last stamp to import completion —
            # the cross-replica gap the disaggregated ledger must cover; a
            # failover SALVAGE (history != None) or RE-PLANNED handoff
            # (req._migrating) is a ``migration`` stint from its seal
            # stamp instead
            self._span(req, "handoff", t0, t1, ledger=False)
            self._span(req,
                       "migration" if (history is not None or req._migrating)
                       else "handoff_wait",
                       req._phase_t0, t1)
            req._migrating = False
            req.status = DECODING
            req.admit_t = req._phase_t0 = t1
            self.stats.record_admit(req.cls.name)
            self._admit_pipe(req)
            self._live[req.uid] = req
            did = True
        self._handoffs = held
        return did

    # ------------------------------------------------------------------ #
    # admission round: execute the plan
    # ------------------------------------------------------------------ #

    def _admission_round(self) -> bool:
        now = time.perf_counter()
        actions = self.admission.plan(now, self._live, self._preempted,
                                      self.offload)
        admitted: List[RequestHandle] = []
        for kind, req in actions:
            if kind == "shed":
                self._finalize(req, SHED)
            elif kind == "preempt":
                self._preempt(req)
            elif kind == "restore":
                self._restore(req)
            elif kind == "admit":
                if not self._lora_acquire(req):
                    # adapter pool pressure raced the plan: hold (refcounts
                    # drop as live rows finish; the plan retries next round)
                    self.admission._queues[req.cls.name].appendleft(req)
                    continue
                try:
                    self.engine.scheduler.add_tokens(req.uid, req.prompt)
                except RuntimeError:           # capacity raced the plan: hold
                    self._lora_release(req)
                    self.admission._queues[req.cls.name].appendleft(req)
                    continue
                t = time.perf_counter()
                # ledger splits the wait at this admission round's plan
                # stamp: ``queued`` (arrival -> round) + ``admission``
                # (round -> scheduler attach); the lane span keeps the
                # whole wait as one ``queued`` stint — same stamps
                self._span(req, "queued", req.arrival_t, t, ledger=False)
                if now > req._phase_t0:
                    req._ledger_add("queued", req._phase_t0, now)
                req._ledger_add("admission", max(now, req._phase_t0), t)
                req.status = PREFILL
                req.admit_t = req._phase_t0 = t
                self.stats.record_admit(req.cls.name)
                admitted.append(req)
        if admitted or self.engine.scheduler.has_pending():
            self._prefill(admitted)
        self.stats.queue_depth = self.admission.queued
        # KV-pool residency gauges, refreshed at the same cadence as
        # queue_depth (one admission round): free blocks + tracked
        # sequences feed the resident-sequence-headroom view the capacity
        # doubling is read from (docs/SERVING.md "Quantized KV")
        self.stats.kv_free_blocks = self.engine.allocator.free_blocks
        self.stats.kv_resident_seqs = len(self.engine.scheduler.seqs)
        if _tracer.enabled:
            _tracer.counter("serve/frontend/queue_depth",
                            self.stats.queue_depth, lane="serve/frontend")
            _tracer.counter("serve/frontend/kv_free_blocks",
                            self.stats.kv_free_blocks, lane="serve/frontend")
        return bool(actions)

    def _prefill(self, reqs: List[RequestHandle]) -> None:
        """Drain the admitted batch's prompt chunks through SplitFuse passes,
        polling client disconnects at every pass boundary (cancel-mid-prefill
        retires through ``scheduler.flush`` with partial KV released)."""
        e = self.engine
        t0 = time.perf_counter()
        tokens = sum(len(r.prompt) for r in reqs)
        while e.scheduler.has_pending():
            e._run_pass()
            if self._fenced:
                return       # fenced mid-prefill: failover owns every handle
            for req in reqs:
                if req.cancelled and req.status == PREFILL:
                    self._teardown(req, CANCELLED)
        t1 = time.perf_counter()
        # intentionally async: the EMA cost model wants the loop-observed
        # prefill cadence (what admission actually waits), not device time
        self.admission.cost.update_prefill(tokens, t1 - t0)  # jaxlint: disable=JL001
        for req in reqs:
            if req.status != PREFILL:
                continue                       # cancelled mid-prefill
            self._span(req, "prefill", req._phase_t0, t1)
            req.status = DECODING
            req._phase_t0 = t1
            self._admit_pipe(req)
            self._live[req.uid] = req

    # ------------------------------------------------------------------ #
    # preempt / restore
    # ------------------------------------------------------------------ #

    def _preempt(self, req: RequestHandle) -> None:
        uid = req.uid
        now = time.perf_counter()
        self._span(req, "decode", req._phase_t0, now)
        self._pipe.retire([uid])
        self._live.pop(uid, None)
        kept, tail = self.engine.scheduler.private_tail(uid)
        if self.offload is not None and self.offload.can_offload(len(tail)):
            n = self.offload.offload(uid, kept, tail)
            self.stats.offload_bytes += n
        elif req.adapter is not None:
            # the host-capacity recompute fallback would re-prefill this
            # row's decode-written KV base-only, but it carries the
            # adapter's k/v deltas — a silently byte-divergent stream on
            # restore; shed honestly instead (base rows recompute fine:
            # their zero-page deltas are an exact +0.0)
            self.stats.forced_sheds += 1
            self._teardown(req, SHED)
            return
        else:
            # recompute preemption (the configured baseline, or the
            # host-capacity fallback): drop all KV, remember the tokens —
            # readmission re-prefills prompt + generated-so-far
            req._resume_tokens = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
            self.engine.flush([uid])
            self.stats.recompute_preemptions += 1
        # binding drops across the preempted window (the request holds no
        # decode gathers); _restore re-acquires — faulting pages back in if
        # pressure evicted them meanwhile
        self._lora_release(req)
        req.status = PREEMPTED
        req.preempt_t = req._phase_t0 = now
        req.preemptions += 1
        self._preempted[uid] = req
        self.stats.preemptions += 1

    def _apply_swap(self, new_weights, version: Optional[int]) -> int:
        """Quiesce every holder of old-weight KV, then rebind the engine's
        weights (engine thread / synchronous driver only). See
        ``swap_weights`` for the policy; validation failures raise BEFORE
        any state is touched by the engine, but the quiesce itself is not
        rolled back — preempted requests simply re-prefill under whichever
        weights are live when they restore, which is correct either way."""
        for req in list(self._live.values()):
            self._preempt_for_swap(req)
        if self.offload is not None:
            # offload-preempted victims parked old-weight KV pages on host:
            # a byte-exact restore would resurrect stale state under the
            # new weights, so they convert to recompute victims (re-prefill
            # prompt + generated-so-far; the offload records drop)
            for uid, req in list(self._preempted.items()):
                if uid in self.offload._recs:
                    self.offload.drop(uid)
                    req._resume_tokens = np.concatenate(
                        [req.prompt, np.asarray(req.tokens, np.int32)])
                    self.stats.recompute_preemptions += 1
        if self._handoffs:
            # handoffs awaiting import hold another replica's old-weight KV
            # in host buffers — adopt each as a recompute victim instead
            # (the same shape the failover "resume" path uses)
            now = time.perf_counter()
            for req, _pages, _logits, history in self._handoffs:
                req._resume_tokens = np.asarray(history, np.int32)
                req.status = PREEMPTED
                req.preempt_t = req._phase_t0 = now
                self._preempted[req.uid] = req
            self._handoffs = []
        return self.engine.swap_weights(new_weights, version=version)

    def _preempt_for_swap(self, req: RequestHandle) -> None:
        """Preempt one live request for a weight swap: ALWAYS recompute
        (never offload — parked KV would be stale-weight state on restore),
        and adapter-bound requests shed honestly, the same rule as
        ``_preempt``'s host-capacity fallback (decode-written KV carries
        the adapter's k/v deltas; a base-only re-prefill silently
        diverges)."""
        uid = req.uid
        now = time.perf_counter()
        self._span(req, "decode", req._phase_t0, now)
        self._pipe.retire([uid])
        self._live.pop(uid, None)
        if req.adapter is not None:
            self.stats.forced_sheds += 1
            self._teardown(req, SHED)
            return
        req._resume_tokens = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        self.engine.flush([uid])
        self.stats.recompute_preemptions += 1
        self._lora_release(req)
        req.status = PREEMPTED
        req.preempt_t = req._phase_t0 = now
        req.preemptions += 1
        self._preempted[uid] = req
        self.stats.preemptions += 1

    def _restore(self, req: RequestHandle) -> None:
        uid = req.uid
        if not self._lora_acquire(req):
            return       # adapter pool pressure: stay preempted, retry later
        t0 = time.perf_counter()
        if self.offload is not None and uid in self.offload._recs:
            self._span(req, "preempted", req._phase_t0, t0)
            # re-base NOW, not at the end of the restore: a fence landing
            # mid-restore early-returns before the tail re-base, and the
            # failover's _close_phase would otherwise append a second,
            # overlapping 'preempted' stint from the stale stamp
            req._phase_t0 = t0
            del self._preempted[uid]
            self.stats.restore_bytes += self.offload.restore(uid)
        else:
            try:
                self.engine.scheduler.add_tokens(uid, req._resume_tokens)
            except RuntimeError:
                return              # capacity raced the plan: stay preempted
            self._span(req, "preempted", req._phase_t0, t0)
            req._phase_t0 = t0           # see the offload branch above
            del self._preempted[uid]
            req._resume_tokens = None
            e = self.engine
            while e.scheduler.has_pending():
                e._run_pass()
                if self._fenced or req.cancelled:
                    break
            if self._fenced:
                return       # a wedged restore waking post-failover must not
                # resurrect a handle the migration already re-homed
            if req.cancelled:
                self._teardown(req, CANCELLED)
                return
        t1 = time.perf_counter()
        if self._fenced:
            return
        self._span(req, "restore", t0, t1)
        req.status = DECODING
        req._phase_t0 = t1
        if req.admit_t is None:
            # a failover-migrated request that was still QUEUED on the dead
            # replica reaches the live set through this path without ever
            # being admitted — the victim ordering needs a real stamp
            req.admit_t = t1
        self._admit_pipe(req)
        self._live[uid] = req
        self.stats.restores += 1

    # ------------------------------------------------------------------ #
    # the decode slice
    # ------------------------------------------------------------------ #

    def _ensure_slice_funded(self) -> None:
        """Emergency lever when generation-driven KV growth outruns the
        pool between admission rounds: preempt (or, reject-only, force-shed)
        the newest lowest-priority live rows until the next slice funds."""
        while self._live:
            short = self.admission.slice_shortfall(list(self._live))
            if short <= 0:
                return
            order = {c.name: i for i, c in
                     enumerate(sorted(self.config.classes,
                                      key=lambda c: -c.priority))}
            victim = max(self._live.values(),
                         key=lambda r: (order[r.cls.name], r.admit_t))
            if self.config.preemption == "none":
                self.stats.forced_sheds += 1
                self._teardown(victim, SHED)
            else:
                self._preempt(victim)

    def _on_tokens(self, j: int, uids: List[int], row):
        """Per-step drain callback — the serving hot path. Clock stamps,
        int appends and queue puts only: no device fetch, no formatting
        (jaxlint JL007/JL008 police the module).

        Spec-aware stream accounting: a speculative step delivers each
        row's token BATCH (accepted draft prefix + bonus) in one drain, so
        a k-token accept emits k+1 stream tokens from one step. All of a
        batch becomes host-visible simultaneously — the client-observed
        latency the SLOs are defined over — so the batch's FIRST token
        carries the inter-step gap and the rest record 0 ms TBT; tokens
        past ``max_new_tokens``/EOS within a batch are discarded (in-step
        overshoot, flushed with the request at the run boundary)."""
        now = time.perf_counter()
        if self._fenced:
            return list(uids)                  # down: emit nothing, stop all
        stop = None
        for i, u in enumerate(uids):
            req = self._live.get(u)
            if req is None:
                continue                       # stopped earlier this run
            batch = row[i] if self._spec else row[i:i + 1]
            # emission rides the handle's seal lock (uncontended except at
            # the instant a failover migration snapshots the stream): a
            # sealed handle belongs to another replica now — drop the row
            with req._emit_lock:
                if req._sealed:
                    continue
                for bi in range(len(batch)):
                    t = int(batch[bi])
                    req.tokens.append(t)
                    req._q.put(t)
                    # TTFT/TBT stamp the moment the token became
                    # host-visible — the client-observed latency the SLOs
                    # are defined over; the sync point is the drain inside
                    # pipe.run (fetch_to_host)
                    if req.ttft_ms is None:
                        req.ttft_ms = 1e3 * (now - req.arrival_t)  # jaxlint: disable=JL001
                    elif bi == 0:
                        req.tbt_ms.append(1e3 * (now - req._last_emit_t))  # jaxlint: disable=JL001
                    else:
                        req.tbt_ms.append(0.0)  # same-drain sibling token
                    req._last_emit_t = now
                    done = (len(req.tokens) >= req.max_new_tokens
                            or (req.eos_token_id is not None
                                and t == req.eos_token_id))
                    if done or req.cancelled:
                        del self._live[u]
                        self._run_stopped.append(req)
                        req._stop_status = CANCELLED \
                            if (req.cancelled and not done) else FINISHED
                        if stop is None:
                            stop = []
                        stop.append(u)
                        break
        return stop

    def _decode_slice(self) -> None:
        if self._fenced:
            return
        self._ensure_slice_funded()
        if not self._pipe.uids:
            return
        t0 = time.perf_counter()
        self._pipe.run(self.config.decode_slice, on_tokens=self._on_tokens)
        # run() drains every step's token row (fetch_to_host), so this wall
        # time is real work, not enqueue time
        self.admission.cost.update_decode(time.perf_counter() - t0)  # jaxlint: disable=JL001
        stopped, self._run_stopped = self._run_stopped, []
        for req in stopped:
            # retired mid-run by the callback: the pipeline dropped its refs;
            # release the KV and close the stream at this run boundary
            self.engine.flush([req.uid])
            self._finalize(req, req._stop_status)
