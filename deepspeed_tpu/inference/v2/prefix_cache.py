"""Automatic prefix caching: radix-tree KV block reuse for the v2 engine.

Parity role: SGLang's RadixAttention and vLLM's automatic-prefix-caching, the
standard prefill-cost lever for a paged-KV serving engine (PAPERS.md — serving
traffic is dominated by shared system prompts / few-shot templates / multi-turn
histories). The reference DeepSpeed-FastGen stack recomputes every prompt from
scratch; this subsystem lets a new request adopt the KV pages an earlier request
already computed for the same token prefix.

Structure: a host-side radix tree over TOKEN BLOCKS. Every node owns exactly one
KV page and is keyed by the tuple of tokens that fill it (tuple hashing = the
token-block hash; chained through the path from the root, so a node's page is
valid KV iff the request's tokens match the whole root->node path). Full pages
(``block_size`` tokens) are shared directly — a match bumps the page's allocator
refcount and splices its id into the new sequence's block table with zero
prefill scheduled. A *partial* leaf (a flushed prompt tail that never filled its
last page) cannot be shared in place, because the adopter must keep writing into
the page's empty slots: it is adopted copy-on-write — a fresh page is allocated,
the cached page's contents are copied device-side (``cow_fn``), and the adopter
extends its private copy.

Lifecycle:
  - ``insert`` (eager, at prefill completion, and again at flush) files a live
    sequence's pages into the tree, taking a tree-owned reference per adopted
    page. At flush the sequence's own references transfer/release, so completed
    sequences' pages stay cached — warm, refcount 1 — instead of freeing.
  - ``match`` (at admission) walks the tree and hands back shared pages.
  - ``evict`` LRU-frees refcount-1 leaves (pages nobody but the tree holds)
    when the pool runs dry or the ``max_cached_blocks`` cap is exceeded;
    interior pages become evictable as their children go.

Everything here is host metadata — the only device work is the COW page copy.

Multi-replica support (``serving/router.py``): every full-block node carries a
root->path *chain hash* (``chain_hash``); ``add_listener`` feeds insert/evict
deltas to a cluster-wide prefix index, and ``match_len`` answers the cheap
"how much of this prompt is cached here" query cache-aware routing scores.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator

Event = Tuple[str, float, int]

#: chain hash of the (empty) root path — the seed every token-block chain
#: hash grows from. The multi-replica router's shared prefix index
#: (``serving/router.py ClusterPrefixIndex``) walks a request's blocks with
#: the SAME chain function, so index membership == radix-tree path existence.
ROOT_CHAIN = 0


def chain_hash(parent_chain: int, key: Tuple[int, ...]) -> int:
    """Chained token-block hash identifying one root->node path (stable
    within a process — the router and its replicas share one). A node's
    chain commits to every token block above it, so two trees holding the
    same chain hold the same cached token prefix (modulo hash collisions,
    which cost a mis-route, never correctness — routing is a placement
    hint; the replica's own ``match`` decides what actually attaches)."""
    return hash((parent_chain, key))


@dataclass
class PrefixCacheStats:
    """Counters surfaced through ``monitor/`` (``events()``) and the serving
    bench. ``tokens_saved`` counts prompt tokens whose prefill was skipped."""
    lookups: int = 0
    hits: int = 0                 # lookups that matched at least one block
    misses: int = 0
    matched_blocks: int = 0       # full pages spliced in across all lookups
    partial_hits: int = 0         # COW adoptions of a partial leaf
    tokens_saved: int = 0
    tokens_requested: int = 0
    insertions: int = 0           # nodes created
    evictions: int = 0            # pages LRU-freed back to the pool
    cow_copies: int = 0

    @property
    def hit_rate(self) -> float:
        """Fraction of requested prompt tokens served from cache."""
        return self.tokens_saved / self.tokens_requested \
            if self.tokens_requested else 0.0

    def events(self, step: int = 0) -> List[Event]:
        """Monitor-ready ``(name, value, step)`` tuples (MonitorMaster
        ``write_events`` format)."""
        return [
            ("inference/prefix_cache/hit_rate", float(self.hit_rate), step),
            ("inference/prefix_cache/tokens_saved", float(self.tokens_saved), step),
            ("inference/prefix_cache/matched_blocks", float(self.matched_blocks), step),
            ("inference/prefix_cache/evictions", float(self.evictions), step),
            ("inference/prefix_cache/insertions", float(self.insertions), step),
            ("inference/prefix_cache/cow_copies", float(self.cow_copies), step),
        ]


class _RadixNode:
    __slots__ = ("key", "block_id", "parent", "children", "partials",
                 "last_access", "chain", "version")

    def __init__(self, key: Tuple[int, ...], block_id: Optional[int],
                 parent: Optional["_RadixNode"]):
        self.key = key                    # tokens backing this node's page
        self.block_id = block_id          # None only at the root
        self.parent = parent
        self.children: Dict[Tuple[int, ...], _RadixNode] = {}   # full pages
        self.partials: Dict[Tuple[int, ...], _RadixNode] = {}   # partial leaves
        self.last_access = 0
        # root->node chain hash (chain_hash); None for partial leaves — only
        # full-block nodes are routable (the router delta feed skips partials)
        self.chain: Optional[int] = None
        # weight-version stamp (colocated rollout): the engine weights this
        # node's KV page was computed under. A node whose stamp trails the
        # tree's current version is stale-KV — match/match_len refuse it
        # even if a deferred flush left it in the tree.
        self.version = 0

    @property
    def is_leaf(self) -> bool:
        return not self.children and not self.partials


@dataclass
class PrefixMatch:
    """Result of ``match``: pages the sequence may attach (references already
    taken on its behalf) and how many prompt tokens they cover."""
    blocks: List[int] = field(default_factory=list)
    n_cached: int = 0             # tokens covered (prefill to skip)
    cow: bool = False             # last block is a fresh copy-on-write page


class RadixPrefixCache:

    def __init__(self, allocator: BlockedAllocator, block_size: int,
                 max_cached_blocks: Optional[int] = None,
                 cow_fn: Optional[Callable[[int, int], None]] = None):
        self.allocator = allocator
        self.block_size = block_size
        self.max_cached_blocks = max_cached_blocks
        # device page copy src_block -> dst_block; None disables COW adoption
        # (full-block sharing still works)
        self.cow_fn = cow_fn
        self.root = _RadixNode((), None, None)
        self.root.chain = ROOT_CHAIN
        self._clock = 0                   # monotonic LRU clock
        self._nodes = 0                   # pages the tree holds references to
        # delta sinks (serving/router.py ClusterPrefixIndex): called
        # ``fn("insert"|"evict", chain_hash)`` whenever a full-block node
        # joins or leaves the tree — the per-replica feed a shared
        # cluster-wide prefix index is built from. Partial leaves never emit
        # (not routable: adoption is COW, not sharing).
        self._listeners: List[Callable[[str, int], None]] = []
        self.stats = PrefixCacheStats()
        # the engine-weight version every cached page's KV was computed
        # under (colocated rollout, runtime/colocated.py): a weight swap
        # bumps this through ``set_weight_version``, which flushes the tree
        # — cached KV from the old weights can never satisfy a post-swap
        # match. Inserts stamp nodes with the current version; matches
        # refuse any node whose stamp trails it (defense in depth on top of
        # the eager flush).
        self.weight_version = 0

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #

    @property
    def cached_blocks(self) -> int:
        return self._nodes

    @property
    def evictable_blocks(self) -> int:
        """Pages ``evict()`` can actually reclaim right now: refcount-1 nodes
        whose whole subtree is also refcount-1 (eviction peels leaves, so an
        interior page pinned under a shared descendant is unreachable even at
        refcount 1 — counting it would let can_schedule approve an allocation
        that then fails mid-pass). O(nodes); cached-pool sizes are host
        metadata, thousands at most."""
        # iterative (tree depth = cached-prefix page count, which can exceed
        # Python's recursion limit for long prompts at small block sizes):
        # in reversed preorder every child precedes its parent, so one sweep
        # settles subtree-evictability bottom-up
        order = list(self._iter_nodes())
        free: Dict[int, bool] = {}            # id(node) -> subtree evictable
        total = 0
        for node in reversed(order):
            ok = (self.allocator.ref_count(node.block_id) == 1
                  and all(free[id(ch)] for ch in node.children.values())
                  and all(free[id(ch)] for ch in node.partials.values()))
            free[id(node)] = ok
            total += ok
        return total

    def _iter_nodes(self):
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root:
                yield node
            stack.extend(node.children.values())
            stack.extend(node.partials.values())

    def iter_chains(self):
        """Chain hashes of every full-block node currently cached (partial
        leaves excluded — they are not routable). Used by ``add_listener``
        to replay existing state into a late-registered index."""
        for node in self._iter_nodes():
            if node.chain is not None:
                yield node.chain

    def add_listener(self, fn: Callable[[str, int], None],
                     replay: bool = True) -> None:
        """Register a delta sink; ``replay=True`` first emits an ``insert``
        for every full-block node already in the tree, so an index built
        after the replica served traffic starts consistent."""
        if replay:
            for chain in self.iter_chains():
                fn("insert", chain)
        self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[str, int], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _emit(self, op: str, chain: Optional[int]) -> None:
        if chain is None:
            return
        for fn in self._listeners:
            fn(op, chain)

    def match_len(self, tokens: Sequence[int]) -> int:
        """Tokens the tree could serve for this prompt RIGHT NOW via
        full-block sharing — the cheap longest-cached-match query the
        multi-replica router scores placements with. Pure read: no
        references taken, no LRU touch, no stats, no COW; capped at
        ``len(tokens) - 1`` exactly like ``match`` (the last prompt token
        always prefills fresh)."""
        tokens = [int(t) for t in np.asarray(tokens, np.int64).reshape(-1)]
        bs = self.block_size
        limit = len(tokens) - 1
        node = self.root
        i = 0
        while i + bs <= limit:
            child = node.children.get(tuple(tokens[i:i + bs]))
            if child is None or child.version != self.weight_version:
                break
            node = child
            i += bs
        return i

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _touch_path(self, node: _RadixNode) -> None:
        t = self._tick()
        while node is not None and node is not self.root:
            node.last_access = t
            node = node.parent

    # ------------------------------------------------------------------ #
    # match (admission path)
    # ------------------------------------------------------------------ #

    def match(self, tokens: Sequence[int]) -> PrefixMatch:
        """Match ``tokens`` against the tree. Returns shared page ids covering
        the longest cached prefix, capped at ``len(tokens) - 1`` so at least
        one prompt token always runs through prefill (the engine needs the
        last token's logits computed fresh). Allocator references for the
        returned pages are already taken for the caller; COW pages come
        exclusively owned at refcount 1."""
        tokens = [int(t) for t in np.asarray(tokens, np.int64).reshape(-1)]
        self.stats.lookups += 1
        self.stats.tokens_requested += len(tokens)
        bs = self.block_size
        limit = len(tokens) - 1           # max tokens we may serve from cache
        out = PrefixMatch()
        node = self.root
        i = 0
        while i + bs <= limit:
            child = node.children.get(tuple(tokens[i:i + bs]))
            if child is None or child.version != self.weight_version:
                # a stale-version child holds KV computed under swapped-out
                # weights — a hit here would splice wrong KV into a fresh
                # sequence, so the walk refuses and the tail prefills fresh
                break
            out.blocks.append(child.block_id)
            node = child
            i += bs
        out.n_cached = i
        if out.blocks:
            # take the sequence's references BEFORE anything below can evict:
            # the matched path's pages may be tree-only (refcount 1) right
            # now, and _allocate_for_cow may evict to cover its allocation
            self.allocator.share(out.blocks)
            self._touch_path(node)
        # partial-leaf adoption: a flushed tail whose tokens prefix ours
        best = None
        for key, leaf in node.partials.items():
            p = len(key)
            if (i + p <= limit and tuple(tokens[i:i + p]) == key
                    and leaf.version == self.weight_version
                    and (best is None or p > len(best.key))):
                best = leaf
        if best is not None and self.cow_fn is not None:
            # pin the COW source so the eviction inside _allocate_for_cow
            # cannot free the very page we are about to copy from
            self.allocator.share([best.block_id])
            dst = self._allocate_for_cow()
            if dst is not None:
                self.cow_fn(best.block_id, dst)
                out.blocks.append(dst)
                out.n_cached += len(best.key)
                out.cow = True
                self.stats.partial_hits += 1
                self.stats.cow_copies += 1
                self._touch_path(best)
            self.allocator.free([best.block_id])
        if out.blocks:
            self.stats.hits += 1
        else:
            self.stats.misses += 1
        self.stats.matched_blocks += len(out.blocks) - (1 if out.cow else 0)
        self.stats.tokens_saved += out.n_cached
        return out

    def _allocate_for_cow(self) -> Optional[int]:
        if self.allocator.free_blocks == 0 and self.evict(1) == 0:
            return None
        return int(self.allocator.allocate(1)[0])

    # ------------------------------------------------------------------ #
    # insert (prefill completion + flush)
    # ------------------------------------------------------------------ #

    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               transfer_refs: bool) -> List[int]:
        """File ``blocks`` (logical pages of ``tokens``, in order) into the
        tree.

        ``transfer_refs=False`` (eager insert, sequence still live): the tree
        takes its OWN reference on every page it adopts; the sequence keeps
        all of its references.

        ``transfer_refs=True`` (flush): the sequence's references are consumed
        — transferred to the tree for newly adopted pages, released for pages
        the tree already had (or duplicates of existing content). Returns the
        ids actually freed back to the pool (content already cached under
        other pages, or pages past the known-token coverage).
        """
        tokens = [int(t) for t in np.asarray(tokens, np.int64).reshape(-1)]
        blocks = [int(b) for b in blocks]
        bs = self.block_size
        freed: List[int] = []
        node = self.root
        consumed = 0                      # blocks whose seq-ref we've settled
        stale_stop = False
        i = 0
        while i + bs <= len(tokens) and consumed < len(blocks):
            key = tuple(tokens[i:i + bs])
            blk = blocks[consumed]
            child = node.children.get(key)
            if child is not None and child.version != self.weight_version:
                # a stale-version node survived a deferred flush: never file
                # fresh pages under it (the path above it is unservable) —
                # the remaining refs release below and eviction reclaims it
                stale_stop = True
                break
            if child is None:
                # a partial leaf with this key's prefix may exist; it stays —
                # matches prefer full children, and eviction reclaims it
                child = _RadixNode(key, blk, node)
                child.chain = chain_hash(node.chain, key)
                child.version = self.weight_version
                node.children[key] = child
                self._nodes += 1
                self.stats.insertions += 1
                self._emit("insert", child.chain)
                if not transfer_refs:
                    self.allocator.share([blk])
                # transfer_refs: the seq's reference becomes the tree's
            else:
                if transfer_refs:
                    freed.extend(self.allocator.free([blk]))
            node = child
            consumed += 1
            i += bs
        # partial tail: remaining known tokens that end mid-page
        tip = node                    # deepest node to LRU-touch at the end
        tail = tuple(tokens[i:])
        stale_leaf = (node.partials.get(tail).version != self.weight_version
                      if tail and tail in node.partials else False)
        if tail and consumed < len(blocks) and not stale_stop \
                and not stale_leaf:
            blk = blocks[consumed]
            leaf = node.partials.get(tail)
            if leaf is None:
                leaf = _RadixNode(tail, blk, node)
                leaf.version = self.weight_version
                node.partials[tail] = leaf
                self._nodes += 1
                self.stats.insertions += 1
                if not transfer_refs:
                    self.allocator.share([blk])
            else:
                if transfer_refs:
                    freed.extend(self.allocator.free([blk]))
            # touch through the LEAF: a fresh partial node otherwise keeps
            # last_access=0 and becomes the LRU victim ahead of genuinely
            # old entries — evicting the tail a request just paid to cache
            tip = leaf
            consumed += 1
        if transfer_refs and consumed < len(blocks):
            # pages beyond token coverage (device-generated tokens the host
            # never saw): nothing to key them by — release
            freed.extend(self.allocator.free(blocks[consumed:]))
        self._touch_path(tip)
        if (self.max_cached_blocks is not None
                and self._nodes > self.max_cached_blocks):
            # one call: evict() harvests candidates in a single tree pass
            self.evict(self._nodes - self.max_cached_blocks)
        return freed

    def release(self, tokens: Sequence[int], blocks: Sequence[int]) -> List[int]:
        """Flush-time entry point: insert with reference transfer (completed
        sequences' pages return to the tree, not the free list)."""
        return self.insert(tokens, blocks, transfer_refs=True)

    # ------------------------------------------------------------------ #
    # weight-version flush (colocated rollout weight swap)
    # ------------------------------------------------------------------ #

    def set_weight_version(self, version: int) -> int:
        """Stamp the tree with a new engine-weight version and flush every
        cached page — their KV was computed under the OLD weights, so none
        may satisfy a post-swap match (the cache-invalidation invariant,
        docs/SERVING.md "Colocated rollout"). Called by
        ``engine_v2.swap_weights`` with every sequence already quiesced, so
        the whole tree is refcount-1 and fully evictable; a page still
        shared by a live sequence means the caller broke the quiesce
        contract, and the refusal here surfaces that instead of serving
        stale KV. Eviction deltas flow to the listeners (the cluster prefix
        index must stop routing on the flushed chains). Returns pages
        freed; ``version == weight_version`` is a no-op."""
        if version == self.weight_version:
            return 0
        freed = self.evict(self._nodes) if self._nodes else 0
        if self._nodes:
            raise RuntimeError(
                f"prefix-cache weight-version flush left {self._nodes} "
                "page(s) pinned by live sequences — quiesce (preempt or "
                "flush) every sequence before swapping weights")
        self.weight_version = version
        return freed

    # ------------------------------------------------------------------ #
    # eviction
    # ------------------------------------------------------------------ #

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` cached pages, least-recently-used
        refcount-1 leaves first (a page some sequence still shares is never
        touched). One tree scan harvests the candidate leaves into a heap;
        evicting a leaf may expose its parent, which joins the heap — so the
        whole call is O(nodes + k log nodes), not a rescan per block.
        Returns pages freed."""
        import heapq
        heap = [(node.last_access, id(node), node)
                for node in self._iter_nodes()
                if node.is_leaf and self.allocator.ref_count(node.block_id) == 1]
        heapq.heapify(heap)
        freed = 0
        while freed < n_blocks and heap:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            if victim.key in parent.children \
                    and parent.children[victim.key] is victim:
                del parent.children[victim.key]
                self._emit("evict", victim.chain)
            else:
                del parent.partials[victim.key]
            self.allocator.free([victim.block_id])
            self._nodes -= 1
            freed += 1
            self.stats.evictions += 1
            if (parent is not self.root and parent.is_leaf
                    and self.allocator.ref_count(parent.block_id) == 1):
                heapq.heappush(heap,
                               (parent.last_access, id(parent), parent))
        return freed
