"""Speculative decoding for the v2 serving engine — draft-and-verify layered
onto the steady-state decode hot path (docs/SERVING.md "Speculative
decoding").

Decode is memory-bound (bench_full: hbm_frac 0.62 on MHA-32): every decode
step streams the full model from HBM to emit ONE token per sequence. This
subsystem makes each step pay for up to ``k + 1`` tokens instead:

- ``proposer.py`` — :class:`DraftProposer` (pluggable; a small draft model
  slots in later) with :class:`NGramProposer`, prompt-lookup/n-gram matching
  over each sequence's own token history — no second model, free drafts on
  repetitive/templated text.
- ``pipeline.py`` — :class:`SpecDecodePipeline`: the ``DecodePipeline``
  analog whose step verifies the draft in ONE ragged forward
  (``ragged_model.build_verify_step``: KV written for all k+1 positions,
  greedy accept mask on device, one int32 accept/bonus row per step crossing
  to host) and advances each row by its accepted count — per-step variable
  advance with block-granular rollback of reserved-but-unused pages through
  the refcounted allocator (``scheduler.rollback_reserved``).

Greedy speculation is exactness-preserving: streams are byte-identical to
the spec-off pipeline (``serving_bench.py --spec`` gates it), programs live
on the warmed (bucket, k) grid so speculation adds zero timed compiles, and
``monitor/serving.SpecDecodeStats`` + ``serve/spec/*`` trace lanes make the
acceptance economics observable.
"""

from deepspeed_tpu.inference.v2.spec.pipeline import SpecDecodePipeline
from deepspeed_tpu.inference.v2.spec.proposer import (DraftProposer,
                                                      NGramProposer)
