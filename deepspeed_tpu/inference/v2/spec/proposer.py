"""Draft proposers for speculative decoding.

The verify step (``ragged_model.build_verify_step``) is draft-source
agnostic — ANY proposal is exactness-safe because acceptance compares each
draft token against the greedy argmax the full model computes in the same
pass; a bad draft costs wasted verify rows, never a wrong token. That makes
the proposer a pure quality/throughput knob behind a one-method interface:

- :class:`NGramProposer` (the default): prompt-lookup decoding — match the
  longest recent suffix of the sequence's own token history against earlier
  history and propose the continuation of the most recent match. No second
  model, no device work; repetitive/templated text (code, JSON, multi-turn
  boilerplate) drafts itself.
- A small draft *model* proposer slots into the same interface later (the
  classic two-model speculative decoding); the pipeline only ever calls
  ``propose``.
"""

from __future__ import annotations

import numpy as np


class DraftProposer:
    """Interface: propose up to ``k`` draft tokens continuing ``history``.

    ``history`` is the sequence's token ids so far (prompt + emitted
    generation, int32, host-side); implementations return an int32 array of
    length <= k — empty means "no proposal" and the verify step degenerates
    to a plain decode step for that row. Called once per live row per
    pipeline step, on the host hot loop: keep it allocation-light.
    """

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        raise NotImplementedError


class NGramProposer(DraftProposer):
    """Prompt-lookup / n-gram drafting over the sequence's own history.

    For n from ``max_ngram`` down to ``min_match``: take the history's
    n-token suffix, find its most recent earlier occurrence, and propose the
    k tokens that followed it. Longer matches are tried first (they predict
    better). Among a suffix's occurrences, the most recent one with a FULL
    k-token continuation wins: the very latest occurrence sits near the end
    of history with almost nothing after it, and a truncated draft wastes
    verify rows the budget already paid for (in a loop of period p every
    occurrence continues identically, so preferring an older full one loses
    nothing). O(len(history) * n) per call via one vectorised window
    comparison, fine at serving history lengths.
    """

    def __init__(self, min_match: int = 2, max_ngram: int = 4):
        if min_match < 1 or max_ngram < min_match:
            raise ValueError(f"need 1 <= min_match <= max_ngram, got "
                             f"({min_match}, {max_ngram})")
        self.min_match = min_match
        self.max_ngram = max_ngram

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        h = np.asarray(history, np.int32)
        T = len(h)
        if k < 1:
            return h[:0]
        for n in range(self.max_ngram, self.min_match - 1, -1):
            if T < n + 1:
                continue
            suffix = h[T - n:]
            # all n-windows strictly before the suffix itself
            win = np.lib.stride_tricks.sliding_window_view(h, n)[:T - n]
            hits = np.nonzero((win == suffix).all(axis=1))[0]
            if len(hits):
                full = hits[hits + n + k <= T]
                start = int(full[-1] if len(full) else hits[-1]) + n
                cont = h[start:start + k]
                if len(cont):
                    return cont
        return h[:0]
