"""Speculative decode pipeline — draft, verify in one ragged forward, accept.

``SpecDecodePipeline`` is the ``DecodePipeline`` analog for speculation: the
same admit/retire/run surface over a fixed live set, the same bucketed
descriptors and warmed program grid, but each step advances every row by a
VARIABLE count — the accepted draft prefix plus one greedy bonus token:

    host:   draft (n-gram match over each row's history) -> upload [S, k]
    device: ONE ragged forward scores all k+1 rows per sequence, writes
            their KV, computes the greedy accept mask + bonus token
    host:   drain ONE int32 [2, S] row (accept counts + bonus tokens),
            reconstruct the emitted tokens from the draft it proposed,
            advance rows, draft the next step

The drain is synchronous per step — speculation trades PR 3's one-step-late
overlap for k-token amortization, because the NEXT draft must extend the
tokens this step actually emitted (the device-resident bonus token and the
accept count are unknowable one step early). The per-step host transfer is
still one small int32 row, and a k-token accept amortises the full-model
HBM stream (the reason decode is slow) over k+1 emitted tokens.

Correctness: greedy speculation is exactness-preserving — the emitted
stream is BYTE-IDENTICAL to the spec-off pipeline (ragged_model.
build_verify_step's induction; gated end-to-end by ``serving_bench.py
--spec``). Rejection never touches prefix-cache-shared pages: stale
rejected-token KV sits past the advanced context inside pages the sequence
owns (ctx-bounded readers never see it; the next write overwrites it), and
run-end ``scheduler.rollback_reserved`` frees whole reserved-but-unused
pages back to the refcounted allocator — reject-heavy runs return the pool
to baseline (tests/unit/test_spec_decode.py pins all of it).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.engine_v2 import fetch_to_host
from deepspeed_tpu.inference.v2.spec.proposer import (DraftProposer,
                                                      NGramProposer)
from deepspeed_tpu.monitor.trace import tracer as _tracer


class _TokenBuf:
    """Amortized-growth int32 token history: appends are element stores
    into a doubling buffer and the proposer reads a zero-copy view — a
    plain Python list re-converted with ``np.asarray`` per step costs an
    O(T) copy per verify step (O(T^2) over a generation) on the
    drain-synchronous host loop the draft budget pays for."""

    __slots__ = ("a", "n")

    def __init__(self, toks):
        t = np.asarray(toks, np.int32).reshape(-1)
        self.a = np.empty((max(64, 2 * len(t)),), np.int32)
        self.a[:len(t)] = t
        self.n = len(t)

    def _grow(self, need: int) -> None:
        if self.n + need > len(self.a):
            a = np.empty((max(2 * len(self.a), self.n + need),), np.int32)
            a[:self.n] = self.a[:self.n]
            self.a = a

    def append(self, t: int) -> None:
        self._grow(1)
        self.a[self.n] = t
        self.n += 1

    def extend(self, toks) -> None:
        t = np.asarray(toks, np.int32).reshape(-1)
        self._grow(len(t))
        self.a[self.n:self.n + len(t)] = t
        self.n += len(t)

    def pop(self) -> None:
        self.n -= 1

    def view(self) -> np.ndarray:
        return self.a[:self.n]


class SpecDecodePipeline:
    """Draft-and-verify decode over a fixed live set of sequences.

    Drive it exactly like ``DecodePipeline`` (``engine.decode_pipeline``
    returns this class when ``config.spec_decode.enabled`` and the request
    is greedy)::

        pipe = engine.decode_pipeline(uids)      # SpecDecodePipeline
        toks = pipe.run(16)      # list of per-row token lists (ragged:
                                 # each step emits 1..k+1 tokens per row)
        pipe.retire(done); engine.flush(done); pipe.admit(new)

    ``spec`` is True (callers branch their ``on_tokens`` shape on it).
    Greedy streams are byte-identical to the spec-off pipeline; sampling is
    not supported here (the engine routes sampled pipelines to the plain
    ``DecodePipeline`` with a one-time warning).
    """

    spec = True

    def __init__(self, engine, uids: Sequence[int],
                 proposer: Optional[DraftProposer] = None):
        self.engine = engine
        cfg = engine.config.spec_decode
        self.k = int(cfg.k)
        self.adaptive = bool(cfg.adaptive)
        self.proposer = proposer if proposer is not None else NGramProposer(
            min_match=cfg.min_match, max_ngram=cfg.max_ngram)
        self.uids: List[int] = []
        self.stats = engine.spec_stats
        # per-uid host state: token history (prompt + emitted — what the
        # proposer matches over) and the adaptive per-row draft budget
        self._hist: Dict[int, _TokenBuf] = {}
        self._k_eff: Dict[int, int] = {}
        self.admit(uids)

    # ------------------------------------------------------------------ #
    # live-set management (between runs)
    # ------------------------------------------------------------------ #

    def retire(self, uids: Iterable[int]) -> None:
        """Drop sequences from the live set (engine state untouched — flush
        them to release KV; their draft history goes with them)."""
        gone = {int(u) for u in uids}
        self.uids = [u for u in self.uids if u not in gone]
        for u in gone:
            self._hist.pop(u, None)
            self._k_eff.pop(u, None)

    def admit(self, uids: Iterable[int],
              histories: Optional[Sequence[Sequence[int]]] = None) -> None:
        """Add prefilled sequences (after ``engine.put``). ``histories``
        optionally seeds each row's draft history; by default the
        scheduler's recorded history is used (the engine records it whenever
        spec decode is enabled), so prompt-lookup can match into the prompt
        from the first step. A short/empty history only degrades draft
        quality, never correctness."""
        e = self.engine
        uids = [int(u) for u in uids]
        if histories is not None and len(histories) != len(uids):
            raise ValueError("histories must align with uids")
        for i, u in enumerate(uids):
            seq = e.scheduler.seqs.get(u)
            if seq is None or len(seq.pending):
                raise ValueError(f"uid {u} is not in steady decode state")
            if u not in e._last_ref and u not in e._last_logits:
                raise ValueError(f"uid {u} has no last-logits state to "
                                 "sample from (run put() first)")
            if u in self.uids:
                raise ValueError(f"uid {u} already in the pipeline")
            self.uids.append(u)
            self._hist[u] = _TokenBuf(histories[i] if histories is not None
                                      else seq.history())
            self._k_eff[u] = self.k

    # ------------------------------------------------------------------ #
    # the hot loop
    # ------------------------------------------------------------------ #

    def _tune_k(self, u: int, proposed: int, accepted: int) -> None:
        """Per-sequence adaptive draft budget (MIMD): a full accept DOUBLES
        the budget (up to k — a row riding a repetitive span reaches full
        k within log2(k) steps); any reject drops it to accepted + 1,
        keeping a probe of 1 alive so a row re-entering a repetitive span
        is detected without paying for dead full-k drafts meanwhile."""
        if not self.adaptive or proposed < 1:
            return
        if accepted >= proposed:
            self._k_eff[u] = min(self.k, max(2 * self._k_eff[u], 1))
        else:
            self._k_eff[u] = max(1, accepted + 1)

    def run(self, n_steps: int,
            on_tokens: Optional[Callable] = None) -> List[List[int]]:
        """Run ``n_steps`` verify steps; returns each live row's emitted
        tokens (ragged — between ``n_steps`` and ``n_steps * (k + 1)`` per
        row) in ``self.uids`` order at run start.

        ``on_tokens(step, uids, toks)`` is called after each step's
        accept-row drain with ``toks`` a list of int32 arrays — row i's
        tokens emitted THIS step (1..k+1 of them, host-visible
        simultaneously). Its truthy return value is an iterable of uids to
        retire: recording (and drafting) for them stops, their continuation
        refs drop, and they leave the live set — but their device rows run
        to the end of the burst (bucket shapes are static), exactly the
        ``DecodePipeline`` retirement trade. If the callback raises, state
        settles first (histories advanced to the drained spans, reserved
        pages rolled back, refs dropped, all uids leave the pipeline —
        flush or re-``put`` before reuse).
        """
        e = self.engine
        uids = list(self.uids)
        S = len(uids)
        if S == 0 or n_steps <= 0:
            return [[] for _ in range(S)]
        assert not e.scheduler.has_pending(), \
            "spec decode pipeline requires a drained scheduler"
        perf = time.perf_counter
        K1 = self.k + 1
        # reserve for FULL acceptance up front (the verify step writes up to
        # k+1 positions ahead per step with no host intervention); run-end
        # rollback returns whatever rejection left unused
        db = e.scheduler.decode_batch(uids, n_steps * K1 + 1,
                                      e.scratch_block)
        # each step dispatches the SMALLEST (bucket, k) rung covering its
        # longest draft — a mostly-unrepetitive batch pays 2-row verifies,
        # not full-k ones; draft-empty steps (cold history, post-reject
        # backoff) dispatch the PLAIN fused decode step — bit-identical to
        # a verify step's row 0 for full-precision pools, value-identical
        # up to cross-kernel float noise for int8 pools (both attend the
        # quantized pool values; docs/SERVING.md "Quantized KV").
        # Everything here is on the warmed grid:
        # the ladder tops out at exactly self.k (both read config k), the
        # invariant the zero-compile gate rests on.
        ladder = e.spec_k_ladder
        rb = e.lora_rank_bucket
        plain = e._decode_step_prog(db.bucket, False, 0, rb)
        temp = jnp.float32(1.0)
        block_tables = jnp.asarray(db.block_tables)
        # run-invariant LoRA operands, like block_tables (empty at rb=0);
        # verify programs repeat each row's pages over its K+1 token rows
        # in-jit, so the SAME [bucket, rb] table feeds both program kinds
        lora_args = e._lora_operands(uids, db.bucket, rb)
        ids, _ = e._sample_device_padded(uids, False, 1.0, 0)
        assert ids.shape[0] == db.bucket
        if hasattr(ids, "copy_to_host_async"):
            ids.copy_to_host_async()
        # the run's ONE extra drain: the bootstrap row. Step j emits the
        # COMMITTED tokens — the carry (step j-1's bonus; this bootstrap at
        # step 0, stream-identical to DecodePipeline's first drained row)
        # plus the accepted drafts; the bonus becomes step j+1's carry, and
        # the final step's bonus stays un-emitted, re-derived from the
        # logits refs exactly like DecodePipeline's final sampled row.
        carry = fetch_to_host(ids)

        outs: List[List[int]] = [[] for _ in range(S)]
        live = np.ones((S,), bool)
        # tokens whose history/advance is settled (drained steps), per row
        emitted = np.zeros((S,), np.int64)
        recorded = np.zeros((S,), np.int64)
        row_of = {u: i for i, u in enumerate(uids)}
        final_logits = None
        # the carry token continues each row's history — drafts extend it
        for i, u in enumerate(uids):
            self._hist[u].append(int(carry[i]))
        try:
            for j in range(n_steps):
                t0 = perf()
                draft, n_draft = self._draft_step(uids, live, db.bucket)
                t1 = perf()
                kmax = int(n_draft.max())
                if kmax > 0:
                    k_step = next(k_ for k_ in ladder if k_ >= kmax)
                    prog = e._verify_prog(db.bucket, k_step, rb)
                    accept_row, nxt, final_logits, new_kv = prog(
                        e.weights, e.kv.kv, ids,
                        jnp.asarray(draft[:, :k_step]),
                        jnp.asarray(n_draft),
                        db.positions, block_tables, db.ctx_lens,
                        *lora_args)
                else:
                    # nothing to verify anywhere: one plain decode step
                    # (greedy ignores the key; bit-identical to a verify
                    # step's row 0)
                    nxt, final_logits, new_kv = plain(
                        e.weights, e.kv.kv, ids, db.positions,
                        block_tables, db.ctx_lens, e._rng_key, temp,
                        *lora_args)
                    accept_row = None
                e.kv.update(new_kv)
                drain_src = accept_row if accept_row is not None else nxt
                if hasattr(drain_src, "copy_to_host_async"):
                    drain_src.copy_to_host_async()
                t2 = perf()
                # the ONE per-step drain: accept counts + bonus tokens
                # (a fallback step's bonus row with implicit zero accepts)
                host = fetch_to_host(drain_src)
                row = host if accept_row is not None else np.stack(
                    [np.zeros_like(host), host])
                t3 = perf()
                counts = row[0] + 1                  # emitted per device row
                step_tokens = proposed = accepted = 0
                empty = np.zeros((0,), np.int32)
                toks: List[np.ndarray] = [empty] * S
                for i, u in enumerate(uids):
                    a = int(row[0, i])
                    emitted[i] += a + 1
                    if not live[i]:
                        continue
                    # step j's stream tokens: the carry (committed by this
                    # step's row 0) + the accepted drafts; the bonus
                    # row[1, i] becomes the next carry (in history for
                    # drafting, not yet in the stream)
                    tk = np.concatenate(
                        [carry[i:i + 1], draft[i, :a]]).astype(np.int32)
                    toks[i] = tk
                    self._hist[u].extend(draft[i, :a])
                    self._hist[u].append(int(row[1, i]))
                    # rows retired THIS step (below) still record this
                    # step's tokens — same policy as DecodePipeline
                    outs[i].extend(int(t) for t in tk)
                    recorded[i] = emitted[i]
                    step_tokens += a + 1
                    proposed += int(n_draft[i])
                    accepted += a
                    self._tune_k(u, int(n_draft[i]), a)
                carry = row[1]
                tc = tc2 = t3
                if on_tokens is not None:
                    tc = perf()
                    stop = on_tokens(j, uids, toks)
                    tc2 = perf()
                    for u in (stop or ()):
                        i = row_of.get(int(u))
                        if i is not None and live[i]:
                            live[i] = False
                            self._hist.pop(int(u), None)
                            self._k_eff.pop(int(u), None)
                # device rows advance by what the device actually wrote —
                # retired rows included (their positions must keep tracking
                # the KV writes their still-running row performs), pad rows
                # by their own device-reported count (always 1: no draft)
                db.advance_rows(counts)
                ids = nxt
                t4 = perf()
                live_rows = int(live.sum())
                self.stats.record_step(
                    rows=live_rows, proposed=proposed, accepted=accepted,
                    tokens=step_tokens, draft_s=t1 - t0,
                    verify_s=(t3 - t1), fetch_bytes=host.nbytes)
                if _tracer.enabled:
                    _tracer.add("serve/spec/draft", t0, t1,
                                lane="serve/spec", step=j)
                    _tracer.add("serve/spec/dispatch", t1, t2,
                                lane="serve/spec", step=j)
                    _tracer.add("serve/spec/drain", t2, t3,
                                lane="serve/spec", step=j)
                    if on_tokens is not None:
                        _tracer.add("serve/spec/callback", tc, tc2,
                                    lane="serve/spec", step=j)
                    _tracer.add("serve/spec/step", t0, t4,
                                lane="serve/spec", step=j,
                                tokens=step_tokens, accepted=accepted)
        except BaseException:
            # settle like DecodePipeline: drained spans become history,
            # reserved pages roll back, refs drop, all uids leave — flush
            # (or re-put) before reuse
            for i, u in enumerate(uids):
                e.scheduler.advance(u, int(recorded[i]))
                e.scheduler.rollback_reserved(u)
                e._last_ref.pop(u, None)
                e._last_logits.pop(u, None)
                self._hist.pop(u, None)
                self._k_eff.pop(u, None)
            self.uids = []
            raise
        for i, u in enumerate(uids):
            if live[i]:
                e.scheduler.advance(u, int(emitted[i]))
                e._last_ref[u] = (final_logits, i)
                e._last_logits.pop(u, None)
                # drop the trailing un-emitted bonus from the draft history:
                # the next run re-derives it from the refs and re-appends it
                # as its carry (a double entry would skew n-gram matching)
                self._hist[u].pop()
            else:
                # retired mid-run: only the recorded span becomes history;
                # overrun tokens' KV is overwritten by any later decode at
                # the same positions. Refs would point past the recorded
                # span — drop them (flush or re-put).
                e.scheduler.advance(u, int(recorded[i]))
                e._last_ref.pop(u, None)
                e._last_logits.pop(u, None)
            # block-granular rollback: reserved pages the (possibly
            # reject-heavy) run never reached return to the allocator
            e.scheduler.rollback_reserved(u)
        self.uids = [u for i, u in enumerate(uids) if live[i]]
        return outs

    # ------------------------------------------------------------------ #

    def _draft_step(self, uids: List[int], live: np.ndarray, bucket: int):
        """Draft for the live rows only (retired rows stop proposing — their
        device row decays to plain single-token decode)."""
        draft = np.zeros((bucket, self.k), np.int32)
        n_draft = np.zeros((bucket,), np.int32)
        for i, u in enumerate(uids):
            if not live[i]:
                continue
            budget = self._k_eff[u] if self.adaptive else self.k
            if budget < 1:
                continue
            d = self.proposer.propose(self._hist[u].view(), budget)
            if len(d):
                draft[i, :len(d)] = d
                n_draft[i] = len(d)
        return draft, n_draft
