"""Async double-buffered decode pipeline — the v2 steady-state serving loop.

Why this exists: BENCH_r06 showed the prefix cache cutting prefill tokens 83%
while wall clock moved ~5% — steady-state serving cost had become per-step
HOST work, not device compute. The per-token loop paid, per generated token:
a device dispatch, a BLOCKING logits/token fetch, scheduler bookkeeping, a
full ragged descriptor build, and another dispatch — all serialised. This
pipeline restructures that into two overlapped stages (the TPU-jit analog of
DeepSpeed's fused CUDA sampling + persistent decode loops, and of the
host/device overlap in continuous-batching servers like Orca/NanoFlow):

    device:  [ step N-1 ]  [ step N ]  [ step N+1 ]
    host:          | dispatch N | drain N-1's row | build N+1 | dispatch N+1 |

- **Sampling is fused into the decode program** (``build_decode_step``):
  step N's dispatch consumes step N-1's token row *on device* — no host
  round trip sits between consecutive forward passes, and the only per-step
  device->host transfer is one int32 row (4 bytes/slot, vs the [S, V]
  logits block), started asynchronously right after dispatch and drained
  ONE STEP LATE while the device runs ahead.
- **Descriptors are bucketed** (``DecodeBatch``): rows, block tables and
  position ids are padded to ``next_pow2(live)``, so admission/retirement
  moves between cached executables (pre-compiled by ``engine.warmup()``)
  instead of recompiling; KV blocks are pre-reserved per run, so the
  "build step N+1" stage is two array increments.

Consequence of the one-step-late drain: the host OBSERVES token j while the
device is already computing token j+1. A stop decision made on token j (EOS,
budget) therefore lands after one extra token of device work — that token is
wasted compute in the scratch-of-the-sequence sense, the standard price of
any lookahead/continuation-style serving loop, and the reason ``on_tokens``
retirement stops *recording* rather than the device.

Per-step phase timings land in ``engine.pipeline_stats``
(``monitor/serving.py``) so the overlap is observable; docs/SERVING.md walks
the whole path.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.engine_v2 import fetch_to_host
from deepspeed_tpu.monitor.trace import tracer as _tracer


class DecodePipeline:
    """Double-buffered decode over a fixed live set of sequences.

    All ``uids`` must be in steady decode state: known to the scheduler, no
    pending host tokens, last-logits refs available (i.e. after ``put()`` /
    ``decode_steps`` / a previous run). Drive it as::

        pipe = engine.decode_pipeline(uids)
        tokens = pipe.run(64)            # [len(uids), 64], greedy
        pipe.retire(done_uids); engine.flush(done_uids)
        pipe.admit(new_uids)             # after engine.put() prefilled them
        tokens2 = pipe.run(64)

    Greedy streams are byte-identical to ``decode_steps`` bursts and to the
    per-token ``sample_next``/``put`` loop (same forward math; pinned by
    tests/unit/test_decode_pipeline.py). Sampled streams are valid draws but
    bucket-dependent (see ``decode_steps``' docstring).
    """

    def __init__(self, engine, uids: Sequence[int], do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0):
        self.engine = engine
        self.uids: List[int] = []
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.stats = engine.pipeline_stats
        # same validation as later admissions: fail with a clear error NOW,
        # not as a KeyError deep inside scheduler.reserve at run() time
        self.admit(uids)

    # ------------------------------------------------------------------ #
    # live-set management (between runs)
    # ------------------------------------------------------------------ #

    def retire(self, uids: Iterable[int]) -> None:
        """Drop sequences from the live set (their engine state is untouched
        — flush them to release KV). The next run uses the smaller bucket."""
        gone = {int(u) for u in uids}
        self.uids = [u for u in self.uids if u not in gone]

    def admit(self, uids: Iterable[int]) -> None:
        """Add prefilled sequences (after ``engine.put``) to the live set."""
        e = self.engine
        for u in uids:
            u = int(u)
            seq = e.scheduler.seqs.get(u)
            if seq is None or len(seq.pending):
                raise ValueError(f"uid {u} is not in steady decode state")
            if u not in e._last_ref and u not in e._last_logits:
                raise ValueError(f"uid {u} has no last-logits state to sample "
                                 "from (run put() first)")
            if u in self.uids:
                raise ValueError(f"uid {u} already in the pipeline")
            self.uids.append(u)

    # ------------------------------------------------------------------ #
    # the hot loop
    # ------------------------------------------------------------------ #

    def run(self, n_steps: int,
            on_tokens: Optional[Callable] = None) -> np.ndarray:
        """Generate ``n_steps`` tokens per live sequence; returns the ids
        [live, n_steps] in ``self.uids`` order at run start.

        ``on_tokens(step, uids, row)`` is called as each step's token row is
        DRAINED (observed one step late; ``row`` is int32 [live]). Its return
        value, if truthy, is an iterable of uids to retire: recording for
        them stops (their later entries in the returned array are padding
        noise), their continuation refs are dropped (flush or re-``put``
        them before reuse), and they leave the pipeline's live set. The
        device finishes the in-flight burst regardless — stopping the world
        on a retirement would forfeit the overlap this loop exists for.
        Stop-set uids not live in this run are ignored.

        If the callback raises (or the run is interrupted), the exception
        propagates AFTER state is settled: every row's history is advanced
        to its drained span, continuation refs are dropped, and all uids
        leave the pipeline — flush (or re-``put``) them before reuse.
        """
        e = self.engine
        uids = list(self.uids)
        S = len(uids)
        if S == 0 or n_steps <= 0:
            return np.zeros((S, 0), np.int32)
        assert not e.scheduler.has_pending(), \
            "decode pipeline requires a drained scheduler"
        perf = time.perf_counter
        st = self.stats
        del st.step_wall_ms[:]   # per-run latencies (cumulative fields stay)
        # stage-0 setup: pre-reserve KV for the whole run; bucketed
        # descriptors; grid-warm program; on-device bootstrap sample
        db = e.scheduler.decode_batch(uids, n_steps + 1, e.scratch_block)
        rb = e.lora_rank_bucket
        prog = e._decode_step_prog(db.bucket, self.do_sample, self.top_k, rb)
        e._rng_key, base = jax.random.split(e._rng_key)
        temp = jnp.float32(self.temperature)
        # block tables are invariant for the whole run (KV pre-reserved):
        # commit them to device ONCE instead of re-uploading [bucket, MB]
        # ints with every per-token dispatch
        block_tables = jnp.asarray(db.block_tables)
        # LoRA operands are run-invariant too (adapter bindings are frozen
        # while a request is in flight — the registry's refcount gate): empty
        # at rb=0, so adapter-free engines dispatch the identical program
        lora_args = e._lora_operands(uids, db.bucket, rb)
        ids, _ = e._sample_device_padded(uids, self.do_sample,
                                         self.temperature, self.top_k)
        assert ids.shape[0] == db.bucket
        if hasattr(ids, "copy_to_host_async"):
            ids.copy_to_host_async()

        out = np.empty((n_steps, S), np.int32)
        live = np.ones((S,), bool)
        recorded = np.full((S,), n_steps, np.int32)
        row_of = {u: i for i, u in enumerate(uids)}
        logits = None
        steps_drained = 0
        try:
            for j in range(n_steps):
                t0 = perf()
                # dispatch step j: consumes the device-resident row `ids`
                # (= token j, sampled by step j-1 / the bootstrap), writes its
                # KV, samples token j+1 — one program, no host round trip
                nxt, logits, new_kv = prog(e.weights, e.kv.kv, ids,
                                           db.positions, block_tables,
                                           db.ctx_lens,
                                           jax.random.fold_in(base, j), temp,
                                           *lora_args)
                e.kv.update(new_kv)
                if hasattr(nxt, "copy_to_host_async"):
                    nxt.copy_to_host_async()  # D2H queued behind step j, free
                t1 = perf()
                # drain stage: token j's row (its transfer started last
                # iteration; blocks only if the device is still on step j-1)
                row = fetch_to_host(ids)
                t2 = perf()
                out[j] = row[:S]
                steps_drained = j + 1
                # rows retired THIS step still had token j drained + recorded
                drained_tokens = int(live.sum())
                cb_s = 0.0
                tc = tc2 = t2
                if on_tokens is not None:
                    tc = perf()
                    stop = on_tokens(j, uids, out[j])
                    tc2 = perf()
                    cb_s = tc2 - tc      # callback cost -> bubble, not build
                    for u in (stop or ()):
                        # uids not in THIS run (already retired, foreign) are
                        # ignored rather than aborting a healthy burst
                        i = row_of.get(int(u))
                        if i is not None and live[i]:
                            live[i] = False
                            recorded[i] = j + 1
                # build stage: step j+1's descriptors (blocks pre-reserved,
                # so this is the whole of it)
                db.advance(1)
                ids = nxt
                t3 = perf()
                st.record_step(dispatch_s=t1 - t0, drain_s=t2 - t1,
                               build_s=(t3 - t2) - cb_s, wall_s=t3 - t0,
                               fetch_bytes=row.nbytes,
                               live_tokens=drained_tokens)
                if _tracer.enabled:
                    # timeline view of the SAME per-step phase measurements
                    # the stats aggregate (docs/OBSERVABILITY.md): zero-sync,
                    # perf_counter pairs already taken above. The stats
                    # charge callback time to bubble, not build — so the
                    # build span excludes the callback window too (emitted
                    # as its own serve/decode/callback span)
                    _tracer.add("serve/decode/dispatch", t0, t1,
                                lane="serve/decode", step=j)
                    _tracer.add("serve/decode/drain", t1, t2,
                                lane="serve/decode", step=j)
                    if on_tokens is not None:
                        _tracer.add("serve/decode/build", t2, tc,
                                    lane="serve/decode", step=j)
                        _tracer.add("serve/decode/callback", tc, tc2,
                                    lane="serve/decode", step=j)
                        _tracer.add("serve/decode/build", tc2, t3,
                                    lane="serve/decode", step=j)
                    else:
                        _tracer.add("serve/decode/build", t2, t3,
                                    lane="serve/decode", step=j)
                    _tracer.add("serve/decode/step", t0, t3,
                                lane="serve/decode", step=j,
                                live=drained_tokens)
        except BaseException:
            # an escaping on_tokens (or interrupt) must not leave sequence
            # state desynchronized from the KV already written: settle every
            # row's history at its drained span and drop now-stale refs —
            # the uids leave the pipeline and need a flush (or re-put)
            for i, u in enumerate(uids):
                e.scheduler.advance(u, min(int(recorded[i]), steps_drained))
                e._last_ref.pop(u, None)
                e._last_logits.pop(u, None)
            self.uids = []
            raise
        # the final step's sampled row (token n_steps) stays on device,
        # discarded — identical policy to decode_steps; continuation
        # re-derives it from the final logits refs (greedy: same token)
        for i, u in enumerate(uids):
            if live[i]:
                e.scheduler.advance(u, n_steps)
                e._last_ref[u] = (logits, i)
                e._last_logits.pop(u, None)
            else:
                # mid-run retirement: only the recorded span becomes sequence
                # history; the overrun tokens' KV is overwritten by any later
                # decode at the same positions. Continuation refs would point
                # past the recorded span — drop them (flush or re-put).
                e.scheduler.advance(u, int(recorded[i]))
                e._last_ref.pop(u, None)
                e._last_logits.pop(u, None)
        self.uids = [u for i, u in enumerate(uids) if live[i]]
        return out.T.copy()
