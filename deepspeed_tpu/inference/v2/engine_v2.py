"""Inference engine v2 — continuous batching over a paged KV cache.

Parity: ``InferenceEngineV2`` (reference ``inference/v2/engine_v2.py:30``):
``put(uids, tokens) -> logits`` (:107), ``query`` (:153), ``can_schedule`` (:179),
``flush``, plus a convenience ``generate`` driving continuous batching the way
MII's serving loop drives the reference engine.

TPU-native structure per pass (one jitted call, static shapes):

    host: DynamicSplitFuseScheduler builds RaggedBatch descriptor arrays
      |                                   (``scheduler.py``)
    device: ragged forward — scan over layers; paged KV write + chunk/decode
      Pallas attention; MoE grouped GEMM      (``ragged_model.py``)
    host: sample / collect last-token logits, advance descriptors

KV pages are donated through the pass (XLA aliases them in HBM — the functional
analog of the reference writing its blocked KV cache in place).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import (TENSOR_AXIS, MeshTopology, build_topology,
                                     set_topology)
from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache, KVCacheConfig
from deepspeed_tpu.inference.v2.ragged_model import adapt_model, build_ragged_forward
from deepspeed_tpu.inference.v2.scheduler import DynamicSplitFuseScheduler
from deepspeed_tpu.utils.logging import log_dist


import functools


@functools.partial(jax.jit, static_argnums=(3, 4))
def _dev_sample(arr, rows, key, do_sample: bool, top_k: int, temperature=1.0):
    """Gather rows + greedy / temperature / top-k sampling, ONE device call.
    arr [P, V] (or [V] with rows=None semantics handled by caller reshaping);
    rows [n] int32."""
    logits = arr[rows]
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    z = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jax.lax.top_k(z, top_k)[0][:, -1:]
        z = jnp.where(z < kth, -jnp.inf, z)
    return jax.random.categorical(key, z, axis=-1)


class InferenceEngineV2:

    def __init__(self,
                 model: Any = None,
                 config: Optional[RaggedInferenceEngineConfig] = None,
                 model_parameters: Any = None,
                 family: Optional[str] = None,
                 mesh_topology: Optional[MeshTopology] = None):
        self.config = RaggedInferenceEngineConfig.load(config)
        cfg = self.config
        tp = cfg.tensor_parallel
        if mesh_topology is not None:
            self.topology = set_topology(mesh_topology)
        else:
            n = len(jax.devices())
            self.topology = set_topology(build_topology(
                MeshConfig(tensor=tp, data=n // tp, fsdp=1)))

        model_config = getattr(model, "config", None)
        if model_config is None:
            raise ValueError("InferenceEngineV2 needs a model with .config")
        if family is None:
            family = _guess_family(model)
        self.family = family
        if model_parameters is None:
            raise ValueError("InferenceEngineV2 needs model_parameters")
        from deepspeed_tpu.utils.tree import tree_cast
        params = tree_cast(model_parameters, cfg.dtype)
        self.spec, weights = adapt_model(family, params, model_config,
                                         max_context=cfg.state_manager.max_context)
        self.spec.dtype = cfg.dtype
        if cfg.quantization.weight_bits in (4, 8):
            if tp > 1:
                raise NotImplementedError(
                    "weight-only int4/int8 with tensor_parallel > 1 is not "
                    "wired yet (the AutoTP rule walker shards plain arrays); "
                    "run quantized at tp=1 or bf16 under tp")
            from deepspeed_tpu.inference.v2.ragged_model import (
                quantize_weights_int4, quantize_weights_int8)
            weights = (quantize_weights_int8(weights)
                       if cfg.quantization.weight_bits == 8
                       else quantize_weights_int4(weights))
        self.weights = self._shard_weights(weights)

        # KV cache + allocator + scheduler
        sm = cfg.state_manager
        nb = cfg.kv_cache.num_blocks
        if nb is None:
            # pool sized to hold max_tracked_sequences at max_context (CPU tests);
            # on TPU prefer an explicit num_blocks or memory-fraction sizing
            per_seq = -(-sm.max_context // cfg.kv_cache.block_size)
            nb = per_seq * sm.max_tracked_sequences
        if cfg.kv_quant.enabled:
            if tp > 1:
                raise NotImplementedError(
                    "kv_quant with tensor_parallel > 1 is not wired")
            if (self.spec.head_dim % 128 != 0
                    or cfg.kv_cache.block_size % 128 != 0):
                raise ValueError(
                    "kv_quant needs head_dim % 128 == 0 and "
                    "block_size % 128 == 0 (got head_dim="
                    f"{self.spec.head_dim}, block_size="
                    f"{cfg.kv_cache.block_size})")
        kv_cfg = KVCacheConfig(
            num_layers=self.spec.num_layers,
            num_kv_heads=self.spec.num_kv_heads,
            head_dim=self.spec.head_dim,
            block_size=cfg.kv_cache.block_size,
            num_blocks=nb,
            dtype=cfg.dtype,
            quantized=cfg.kv_quant.enabled)
        self.kv = BlockedKVCache(kv_cfg, self.topology)
        self.allocator = BlockedAllocator(nb)
        self.prefix_cache = None
        if cfg.prefix_cache.enabled:
            if self.spec.window is not None:
                raise NotImplementedError(
                    "prefix_cache with a sliding-window model is not wired: "
                    "the page ring overwrites pages in place, which would rot "
                    "cached content under a live sharer")
            if cfg.kv_quant.enabled:
                raise NotImplementedError(
                    "prefix_cache with int8 KV pages is not wired (the COW "
                    "page copy does not handle the tiled scale layout)")
            from deepspeed_tpu.inference.v2.prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(
                self.allocator, kv_cfg.block_size,
                max_cached_blocks=cfg.prefix_cache.max_cached_blocks,
                cow_fn=self.kv.copy_page)
        self.scheduler = DynamicSplitFuseScheduler(sm, self.kv, self.allocator,
                                                   prefix_cache=self.prefix_cache)
        # sliding-window serving (Mistral/Qwen2): the scheduler ring-reuses
        # each sequence's pages beyond the window so KV stays bounded
        self.scheduler.window = self.spec.window

        if self.spec.alibi and tp > 1:
            # the paged kernels compute ALiBi slopes from shard-LOCAL head
            # indices; under head-sharded TP every shard would reuse the
            # first shard's half-sized slope schedule (review r5: measured
            # 0.72 max abs err on 8 virtual devices) — refuse until the
            # kernels take a global head offset
            raise NotImplementedError(
                "ALiBi models with tensor_parallel > 1 are not wired in the "
                "ragged engine (shard-local slope schedules would be wrong); "
                "run tp=1 or serve through init_inference")
        eff_tp = tp if (tp > 1 and self.spec.num_kv_heads % tp == 0
                        and self.spec.num_heads % tp == 0) else 1
        self._eff_tp = eff_tp
        fwd = build_ragged_forward(self.spec, mesh=self.topology.mesh, tp=eff_tp)
        self._pass = jax.jit(fwd, donate_argnums=(1,))
        self._pass_prefill = None  # built on the first pure-prefill pass
        self._rng = np.random.RandomState(cfg.seed)
        self._rng_key = jax.random.PRNGKey(cfg.seed)
        self._last_logits: Dict[int, np.ndarray] = {}
        # device-resident logits refs: uid -> (device_array, row).
        # Materialised to numpy lazily (put()) or sampled on device without
        # ever shipping the [S, V] tensor to host (sample_next()).
        self._last_ref: Dict[int, Tuple[Any, int]] = {}
        # LRU-bounded compiled multistep programs: keyed by (n_steps, S,
        # do_sample, top_k); serving with many batch sizes must not accumulate
        # XLA executables without eviction (round S to buckets upstream when
        # batch sizes vary a lot)
        from deepspeed_tpu.utils.caching import LRUCache
        self._multistep: LRUCache = LRUCache(maxsize=8)
        log_dist(f"engine_v2: family={family} tp={eff_tp} blocks={nb} "
                 f"block_size={kv_cfg.block_size} budget={sm.max_ragged_batch_size}",
                 ranks=[0])

    # ------------------------------------------------------------------ #

    def _shard_weights(self, weights):
        """TP sharding of the canonical stacked weights via the shared AutoTP
        rule walker (``parallel/tensor_parallel.py``) — one source of truth for
        column/row assignments; non-divisible dims warn and replicate."""
        topo = self.topology
        tp = topo.tp_world_size
        if tp <= 1:
            return jax.device_put(weights, topo.replicated())
        from deepspeed_tpu.parallel.tensor_parallel import (
            RAGGED_STACKED_TP_RULES, derive_tp_specs)
        specs = derive_tp_specs(weights, RAGGED_STACKED_TP_RULES, tp)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(topo.mesh, s), specs,
            is_leaf=lambda s: isinstance(s, P))
        return jax.device_put(weights, shardings)

    # ------------------------------------------------------------------ #
    # public API (parity: engine_v2.py put/query/can_schedule/flush)
    # ------------------------------------------------------------------ #

    def put(self, uids: Sequence[int], tokens_list: Sequence[np.ndarray],
            do_checks: bool = True) -> np.ndarray:
        """Schedule these tokens and run passes until all are consumed. Returns
        next-token logits [len(uids), vocab] in the order given."""
        uids = [int(u) for u in uids]
        if do_checks and not self.scheduler.can_schedule(
                uids, [len(t) for t in tokens_list]):
            raise RuntimeError("cannot schedule: insufficient KV blocks or "
                               "sequence slots (check can_schedule first)")
        for uid, toks in zip(uids, tokens_list):
            self.scheduler.add_tokens(uid, np.asarray(toks, np.int32))

        want = set(uids)
        while self.scheduler.has_pending():
            self._run_pass()
        self._materialize(want)
        missing = want - set(self._last_logits)
        if missing:
            raise RuntimeError(f"no logits produced for uids {sorted(missing)}")
        return np.stack([self._last_logits[u] for u in uids])

    def _put_nofetch(self, uids: Sequence[int],
                     tokens_list: Sequence[np.ndarray]) -> None:
        """Like put(), but leaves the logits on device (see sample_next)."""
        uids = [int(u) for u in uids]
        for uid, toks in zip(uids, tokens_list):
            self.scheduler.add_tokens(uid, np.asarray(toks, np.int32))
        while self.scheduler.has_pending():
            self._run_pass()

    def _materialize(self, uids) -> None:
        """Fetch pending device logits to numpy, one transfer per pass array."""
        by_array: Dict[int, Tuple[Any, list]] = {}
        for uid in uids:
            ref = self._last_ref.pop(uid, None)
            if ref is None:
                continue
            arr, row = ref
            by_array.setdefault(id(arr), (arr, []))[1].append((uid, row))
        for arr, pairs in by_array.values():
            host = np.asarray(arr)
            for uid, row in pairs:
                self._last_logits[uid] = host[row]

    def sample_next(self, uids: Sequence[int], do_sample: bool = False,
                    temperature: float = 1.0, top_k: int = 0) -> np.ndarray:
        """Sample the next token for each uid ON DEVICE from its last logits,
        fetching only the token ids (4 bytes/seq instead of the [S, V] logits
        tensor — through a remote tunnel or PCIe this is the difference between
        transfer-bound and compute-bound decode)."""
        padded, n = self._sample_device_padded([int(u) for u in uids],
                                               do_sample, temperature, top_k)
        # slice AFTER the host fetch: a device-side [:n] would compile a new
        # tiny executable for every distinct live-sequence count
        return np.asarray(padded)[:n]

    def _sample_device(self, uids: Sequence[int], do_sample: bool,
                       temperature: float, top_k: int):
        """Sample next tokens on device, returning a device array aligned with
        ``uids`` (no host fetch). Prefer :meth:`_sample_device_padded` where a
        padded result is acceptable — the exact-length slice here compiles one
        tiny program per distinct ``len(uids)``."""
        padded, n = self._sample_device_padded(uids, do_sample, temperature,
                                               top_k)
        return padded[:n]

    def _sample_device_padded(self, uids: Sequence[int], do_sample: bool,
                              temperature: float, top_k: int):
        """Like :meth:`_sample_device` but returns ``(padded_ids, n)`` where
        ``padded_ids`` has a power-of-two length >= n: every device program in
        here is then keyed by the BUCKET size, so a serving loop whose live
        set shrinks by one each retirement reuses cached executables instead
        of recompiling per count (~seconds each through a remote-compile
        tunnel; measured 5 s/iteration in benchmarks/serving_bench.py)."""
        if not uids:
            return jnp.zeros((1,), jnp.int32), 0
        order = np.empty(len(uids), np.int64)
        parts = []
        by_array: Dict[int, Tuple[Any, list]] = {}
        host_rows, host_idx = [], []
        for i, uid in enumerate(uids):
            ref = self._last_ref.get(int(uid))
            if ref is None:
                # logits were materialised to host (a prior put()); re-upload
                host_idx.append(i)
                host_rows.append(self._last_logits[int(uid)])
                continue
            arr, row = ref
            by_array.setdefault(id(arr), (arr, []))[1].append((i, row))
        if host_rows:
            arr = jnp.asarray(np.stack(host_rows))
            by_array[id(arr)] = (arr, [(i, j) for j, i in enumerate(host_idx)])
        n_done = 0
        for arr, pairs in by_array.values():
            rows = [r for _, r in pairs]
            if do_sample:
                self._rng_key, sub = jax.random.split(self._rng_key)
            else:
                sub = self._rng_key
            # pad the row set to the next power of two: a serving loop calls
            # this with a DIFFERENT number of live sequences every time a
            # sequence retires, and each distinct length would recompile
            # _dev_sample (~seconds through a remote-compile tunnel; measured
            # 5 s/iteration in benchmarks/serving_bench.py). Extra rows
            # resample row 0 and are sliced off.
            n_real = len(rows)
            n_pad = 1 << (n_real - 1).bit_length() if n_real > 1 else 1
            rows = rows + [rows[0]] * (n_pad - n_real)
            out = _dev_sample(arr, np.asarray(rows, np.int32), sub,
                              bool(do_sample), int(top_k),
                              float(temperature))
            parts.append(out)                 # padded; real rows are [:n_real]
            for j, (i, _) in enumerate(pairs):
                order[i] = n_done + j
            n_done += len(out)                # padded offsets
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        # pad the reorder gather to the bucket size too (same reasoning)
        n = len(uids)
        n_pad = 1 << (n - 1).bit_length() if n > 1 else 1
        order_pad = np.concatenate([order, np.zeros(n_pad - n, np.int64)])
        return flat[jnp.asarray(order_pad, jnp.int32)].astype(jnp.int32), n

    def decode_steps(self, uids: Sequence[int], n_steps: int,
                     do_sample: bool = False, temperature: float = 1.0,
                     top_k: int = 0, fetch: bool = True
                     ) -> "np.ndarray | jax.Array":
        """Generate ``n_steps`` tokens for every uid with ONE device program
        (fused sample->forward->sample loop; see build_multistep_decode).
        All uids must be in steady decode state (no pending tokens).  Returns
        the generated ids [len(uids), n_steps]; the engine's last-logits refs
        advance so normal put()/sample_next() calls can continue after.

        ``fetch=False`` returns the DEVICE array, already shaped [S,
        n_steps] like the fetched result (the transpose is a free layout op
        on device — ADVICE r4: the old [n_steps, S] return was a silent-
        corruption footgun when S == n_steps): the call then costs only a
        dispatch, so back-to-back bursts chain on device — through a remote
        runtime the synchronous ids fetch is ~an RTT per burst, which would
        otherwise serialise host RTT into every burst."""
        uids = [int(u) for u in uids]
        S = len(uids)
        assert not self.scheduler.has_pending(), \
            "decode_steps requires a drained scheduler"
        for u in uids:
            self.scheduler.reserve(u, n_steps + 1)
        seqs = [self.scheduler.seqs[u] for u in uids]
        mb = self.scheduler.max_blocks
        bt = np.stack([s.block_table(mb) for s in seqs])
        pos0 = np.asarray([s.seen_tokens for s in seqs], np.int32)
        ctx0 = pos0 + 1

        def _build():
            from deepspeed_tpu.inference.v2.ragged_model import (
                build_multistep_decode)
            tp = self.topology.tp_world_size
            # windowed side-buffer chunks freeze page reads while writing
            # n_steps (+1 reserved) tokens at the flush — safe only when the
            # scheduler's page ring covers the frozen span
            win_ok = self.scheduler.ring_covers(n_steps + 1)
            fwd = build_multistep_decode(self.spec, n_steps,
                                         mesh=self.topology.mesh,
                                         tp=tp if tp > 1 else 1,
                                         do_sample=do_sample, top_k=top_k,
                                         window_ring_ok=win_ok)
            return jax.jit(fwd, donate_argnums=(1,))

        fn = self._multistep.get_or_create(
            (n_steps, S, bool(do_sample), int(top_k)), _build)
        ids0 = self._sample_device(uids, do_sample, temperature, top_k)
        self._rng_key, sub = jax.random.split(self._rng_key)
        out_ids, final_logits, new_kv = fn(
            self.weights, self.kv.kv, ids0, pos0, bt, ctx0, sub,
            jnp.float32(temperature))
        self.kv.update(new_kv)
        for i, u in enumerate(uids):
            self.scheduler.advance(u, n_steps)
            self._last_ref[u] = (final_logits, i)
            self._last_logits.pop(u, None)
        if not fetch:
            return out_ids.T            # device [S, n_steps]
        return np.asarray(out_ids).T    # [S, n_steps]

    def _run_pass(self) -> None:
        batch = self.scheduler.schedule_pass()
        if batch is None:
            return
        arrays = batch.device_arrays()
        # each jitted pass receives only the keys it reads (the two paths are
        # separate jit functions; shipping the other path's descriptors is
        # pure upload waste over a slow link)
        from deepspeed_tpu.inference.v2.ragged_model import (
            PAGED_PASS_KEYS, PREFILL_PASS_KEYS)
        # prefill-from-zero passes need no paged reads: packed-flash fast path
        # (build_prefill_forward) — measured 3-4x wave throughput on v5e-1.
        # ALiBi models take the paged chunk path (the packed flash kernel
        # has no per-head position bias; the paged kernels do)
        if batch.pure_prefill and not self.spec.alibi:
            if self._pass_prefill is None:
                from deepspeed_tpu.inference.v2.ragged_model import (
                    build_prefill_forward)
                self._pass_prefill = jax.jit(
                    build_prefill_forward(self.spec, mesh=self.topology.mesh,
                                          tp=self._eff_tp),
                    donate_argnums=(1,))
            pass_fn = self._pass_prefill
            arrays = {k: arrays[k] for k in PREFILL_PASS_KEYS}
        else:
            pass_fn = self._pass
            arrays = {k: arrays[k] for k in PAGED_PASS_KEYS}
        chunk_logits, decode_logits, new_kv = pass_fn(
            self.weights, self.kv.kv, arrays)
        self.kv.update(new_kv)
        finished = self.scheduler.complete_pass(batch)
        for uid in finished:
            if uid in batch.slot_uid:
                # a prompt may span several slots; its next-token logits sit
                # in the LAST slot it filled
                row = len(batch.slot_uid) - 1 - batch.slot_uid[::-1].index(uid)
                self._last_ref[uid] = (chunk_logits, row)
            else:
                self._last_ref[uid] = (decode_logits,
                                       batch.decode_uids.index(uid))

    def query(self, uid: int, max_request_tokens: int) -> Tuple[int, int]:
        return self.scheduler.query(uid, max_request_tokens)

    def can_schedule(self, uids: Sequence[int], lengths: Sequence[int]) -> bool:
        return self.scheduler.can_schedule([int(u) for u in uids], list(lengths))

    def flush(self, uids: Sequence[int]) -> None:
        for uid in uids:
            self.scheduler.flush(int(uid))
            self._last_logits.pop(int(uid), None)
            self._last_ref.pop(int(uid), None)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    # ------------------------------------------------------------------ #
    # prefix-cache support
    # ------------------------------------------------------------------ #

    def write_monitor_events(self, monitor, step: int = 0) -> None:
        """Emit the prefix-cache counters (hit rate, tokens saved, evictions,
        ...) through a ``monitor/`` backend (``MonitorMaster.write_events``
        shape). No-op with the cache off."""
        if self.prefix_cache is not None:
            monitor.write_events(self.prefix_cache.stats.events(step))

    # ------------------------------------------------------------------ #
    # continuous-batching generation loop (parity role: MII serving loop)
    # ------------------------------------------------------------------ #

    def _sample(self, logits: np.ndarray, do_sample: bool, temperature: float,
                top_k: int) -> int:
        if not do_sample:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(temperature, 1e-6)
        if top_k > 0:
            kth = np.sort(z)[-top_k]
            z = np.where(z < kth, -np.inf, z)
        z = z - z.max()
        p = np.exp(z) / np.exp(z).sum()
        return int(self._rng.choice(len(p), p=p))

    def generate(self,
                 prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32,
                 do_sample: bool = False,
                 temperature: float = 1.0,
                 top_k: int = 0,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Generate continuations for a batch of prompts with continuous
        batching: all sequences advance together; finished ones are flushed and
        their blocks recycled. Returns full token lists (prompt + generation)."""
        # fresh uid namespace: never collide with caller-owned put() sequences
        uids: List[int] = []
        nxt = 0
        while len(uids) < len(prompts):
            if nxt not in self.scheduler.seqs:
                uids.append(nxt)
            nxt += 1
        idx_of = {u: i for i, u in enumerate(uids)}
        outs: List[List[int]] = [list(map(int, p)) for p in prompts]
        if not self.can_schedule(uids, [len(p) for p in prompts]):
            raise RuntimeError("cannot schedule: insufficient KV blocks or "
                               "sequence slots")
        self._put_nofetch(uids, [np.asarray(p, np.int32) for p in prompts])
        if eos_token_id is None:
            # no early-exit condition: run the fused multi-step device loop
            # (one host sync per CHUNK tokens); the sub-chunk remainder uses
            # the per-token path so odd lengths never trigger a fresh
            # multi-step compile
            CHUNK = 32
            done = 0
            while max_new_tokens - done >= CHUNK:
                ids = self.decode_steps(uids, CHUNK, do_sample=do_sample,
                                        temperature=temperature, top_k=top_k)
                for i, u in enumerate(uids):
                    outs[idx_of[u]].extend(int(t) for t in ids[i])
                done += CHUNK
            rem = max_new_tokens - done
            for j in range(rem):
                toks = self.sample_next(uids, do_sample, temperature, top_k)
                for u, t in zip(uids, toks):
                    outs[idx_of[u]].append(int(t))
                if j < rem - 1:  # final token's forward pass is never read
                    self._put_nofetch(uids, [np.asarray([t], np.int32)
                                             for t in toks])
            self.flush(uids)
            return outs
        live = set(uids)
        for step in range(max_new_tokens):
            batch_uids = sorted(live)
            # on-device sampling: only the token ids cross the host boundary
            toks = self.sample_next(batch_uids, do_sample, temperature, top_k)
            next_toks: Dict[int, int] = {}
            for u, t in zip(batch_uids, toks):
                t = int(t)
                outs[idx_of[u]].append(t)
                if eos_token_id is not None and t == eos_token_id:
                    live.discard(u)
                    self.flush([u])   # recycle KV blocks immediately
                else:
                    next_toks[u] = t
            if not next_toks or step == max_new_tokens - 1:
                break  # last token's forward pass would never be read
            self._put_nofetch(sorted(next_toks),
                              [np.asarray([next_toks[u]], np.int32)
                               for u in sorted(next_toks)])
        self.flush(sorted(live))
        return outs


def _guess_family(model) -> str:
    fam = getattr(getattr(model, "config", None), "family", None)
    if fam:
        return fam
    name = type(model).__name__.lower()
    for fam in ("mixtral", "mistral", "llama", "gpt2", "opt", "falcon", "phi"):
        if fam in name:
            return fam
    raise ValueError(f"cannot infer model family from {type(model).__name__}; "
                     f"pass family=")
