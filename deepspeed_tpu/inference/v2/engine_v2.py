"""Inference engine v2 — continuous batching over a paged KV cache.

Parity: ``InferenceEngineV2`` (reference ``inference/v2/engine_v2.py:30``):
``put(uids, tokens) -> logits`` (:107), ``query`` (:153), ``can_schedule`` (:179),
``flush``, plus a convenience ``generate`` driving continuous batching the way
MII's serving loop drives the reference engine.

TPU-native structure per pass (one jitted call, static shapes):

    host: DynamicSplitFuseScheduler builds RaggedBatch descriptor arrays
      |                                   (``scheduler.py``)
    device: ragged forward — scan over layers; paged KV write + chunk/decode
      Pallas attention; MoE grouped GEMM      (``ragged_model.py``)
    host: sample / collect last-token logits, advance descriptors

KV pages are donated through the pass (XLA aliases them in HBM — the functional
analog of the reference writing its blocked KV cache in place).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import (TENSOR_AXIS, MeshTopology, build_topology,
                                     set_topology)
from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache, KVCacheConfig
from deepspeed_tpu.inference.v2.ragged_model import adapt_model, build_ragged_forward
from deepspeed_tpu.inference.v2.scheduler import DynamicSplitFuseScheduler
from deepspeed_tpu.utils.logging import log_dist


class InferenceEngineV2:

    def __init__(self,
                 model: Any = None,
                 config: Optional[RaggedInferenceEngineConfig] = None,
                 model_parameters: Any = None,
                 family: Optional[str] = None,
                 mesh_topology: Optional[MeshTopology] = None):
        self.config = RaggedInferenceEngineConfig.load(config)
        cfg = self.config
        tp = cfg.tensor_parallel
        if mesh_topology is not None:
            self.topology = set_topology(mesh_topology)
        else:
            n = len(jax.devices())
            self.topology = set_topology(build_topology(
                MeshConfig(tensor=tp, data=n // tp, fsdp=1)))

        model_config = getattr(model, "config", None)
        if model_config is None:
            raise ValueError("InferenceEngineV2 needs a model with .config")
        if family is None:
            family = _guess_family(model)
        self.family = family
        if model_parameters is None:
            raise ValueError("InferenceEngineV2 needs model_parameters")
        from deepspeed_tpu.utils.tree import tree_cast
        params = tree_cast(model_parameters, cfg.dtype)
        self.spec, weights = adapt_model(family, params, model_config)
        self.spec.dtype = cfg.dtype
        self.weights = self._shard_weights(weights)

        # KV cache + allocator + scheduler
        sm = cfg.state_manager
        nb = cfg.kv_cache.num_blocks
        if nb is None:
            # pool sized to hold max_tracked_sequences at max_context (CPU tests);
            # on TPU prefer an explicit num_blocks or memory-fraction sizing
            per_seq = -(-sm.max_context // cfg.kv_cache.block_size)
            nb = per_seq * sm.max_tracked_sequences
        kv_cfg = KVCacheConfig(
            num_layers=self.spec.num_layers,
            num_kv_heads=self.spec.num_kv_heads,
            head_dim=self.spec.head_dim,
            block_size=cfg.kv_cache.block_size,
            num_blocks=nb,
            dtype=cfg.dtype)
        self.kv = BlockedKVCache(kv_cfg, self.topology)
        self.allocator = BlockedAllocator(nb)
        self.scheduler = DynamicSplitFuseScheduler(sm, self.kv, self.allocator)

        eff_tp = tp if (tp > 1 and self.spec.num_kv_heads % tp == 0
                        and self.spec.num_heads % tp == 0) else 1
        fwd = build_ragged_forward(self.spec, mesh=self.topology.mesh, tp=eff_tp)
        self._pass = jax.jit(fwd, donate_argnums=(1, 2))
        self._rng = np.random.RandomState(cfg.seed)
        self._last_logits: Dict[int, np.ndarray] = {}
        log_dist(f"engine_v2: family={family} tp={eff_tp} blocks={nb} "
                 f"block_size={kv_cfg.block_size} budget={sm.max_ragged_batch_size}",
                 ranks=[0])

    # ------------------------------------------------------------------ #

    def _shard_weights(self, weights):
        """TP sharding of the canonical stacked weights via the shared AutoTP
        rule walker (``parallel/tensor_parallel.py``) — one source of truth for
        column/row assignments; non-divisible dims warn and replicate."""
        topo = self.topology
        tp = topo.tp_world_size
        if tp <= 1:
            return jax.device_put(weights, topo.replicated())
        from deepspeed_tpu.parallel.tensor_parallel import (
            RAGGED_STACKED_TP_RULES, derive_tp_specs)
        specs = derive_tp_specs(weights, RAGGED_STACKED_TP_RULES, tp)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(topo.mesh, s), specs,
            is_leaf=lambda s: isinstance(s, P))
        return jax.device_put(weights, shardings)

    # ------------------------------------------------------------------ #
    # public API (parity: engine_v2.py put/query/can_schedule/flush)
    # ------------------------------------------------------------------ #

    def put(self, uids: Sequence[int], tokens_list: Sequence[np.ndarray],
            do_checks: bool = True) -> np.ndarray:
        """Schedule these tokens and run passes until all are consumed. Returns
        next-token logits [len(uids), vocab] in the order given."""
        uids = [int(u) for u in uids]
        if do_checks and not self.scheduler.can_schedule(
                uids, [len(t) for t in tokens_list]):
            raise RuntimeError("cannot schedule: insufficient KV blocks or "
                               "sequence slots (check can_schedule first)")
        for uid, toks in zip(uids, tokens_list):
            self.scheduler.add_tokens(uid, np.asarray(toks, np.int32))

        want = set(uids)
        while self.scheduler.has_pending():
            self._run_pass()
        missing = want - set(self._last_logits)
        if missing:
            raise RuntimeError(f"no logits produced for uids {sorted(missing)}")
        return np.stack([self._last_logits[u] for u in uids])

    def _run_pass(self) -> None:
        batch = self.scheduler.schedule_pass()
        if batch is None:
            return
        arrays = batch.device_arrays()
        chunk_logits, decode_logits, new_k, new_v = self._pass(
            self.weights, self.kv.k, self.kv.v, arrays)
        self.kv.update(new_k, new_v)
        decode_np = None
        finished = self.scheduler.complete_pass(batch)
        for uid in finished:
            if batch.chunk_uid == uid and batch.chunk_is_final:
                self._last_logits[uid] = np.asarray(chunk_logits)
            else:
                if decode_np is None:
                    decode_np = np.asarray(decode_logits)
                row = batch.decode_uids.index(uid)
                self._last_logits[uid] = decode_np[row]

    def query(self, uid: int, max_request_tokens: int) -> Tuple[int, int]:
        return self.scheduler.query(uid, max_request_tokens)

    def can_schedule(self, uids: Sequence[int], lengths: Sequence[int]) -> bool:
        return self.scheduler.can_schedule([int(u) for u in uids], list(lengths))

    def flush(self, uids: Sequence[int]) -> None:
        for uid in uids:
            self.scheduler.flush(int(uid))
            self._last_logits.pop(int(uid), None)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    # ------------------------------------------------------------------ #
    # continuous-batching generation loop (parity role: MII serving loop)
    # ------------------------------------------------------------------ #

    def _sample(self, logits: np.ndarray, do_sample: bool, temperature: float,
                top_k: int) -> int:
        if not do_sample:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(temperature, 1e-6)
        if top_k > 0:
            kth = np.sort(z)[-top_k]
            z = np.where(z < kth, -np.inf, z)
        z = z - z.max()
        p = np.exp(z) / np.exp(z).sum()
        return int(self._rng.choice(len(p), p=p))

    def generate(self,
                 prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32,
                 do_sample: bool = False,
                 temperature: float = 1.0,
                 top_k: int = 0,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Generate continuations for a batch of prompts with continuous
        batching: all sequences advance together; finished ones are flushed and
        their blocks recycled. Returns full token lists (prompt + generation)."""
        # fresh uid namespace: never collide with caller-owned put() sequences
        uids: List[int] = []
        nxt = 0
        while len(uids) < len(prompts):
            if nxt not in self.scheduler.seqs:
                uids.append(nxt)
            nxt += 1
        idx_of = {u: i for i, u in enumerate(uids)}
        outs: List[List[int]] = [list(map(int, p)) for p in prompts]
        arr = self.put(uids, [np.asarray(p, np.int32) for p in prompts])
        logits_map = {u: arr[i] for i, u in enumerate(uids)}
        live = set(uids)
        for _ in range(max_new_tokens):
            next_toks: Dict[int, int] = {}
            for u in sorted(live):
                t = self._sample(logits_map[u], do_sample, temperature, top_k)
                outs[idx_of[u]].append(t)
                if eos_token_id is not None and t == eos_token_id:
                    live.discard(u)
                    self.flush([u])   # recycle KV blocks immediately
                else:
                    next_toks[u] = t
            if not next_toks:
                break
            batch_uids = sorted(next_toks)
            arr = self.put(batch_uids, [np.asarray([next_toks[u]], np.int32)
                                        for u in batch_uids])
            logits_map = {u: arr[i] for i, u in enumerate(batch_uids)}
        self.flush(sorted(live))
        return outs


def _guess_family(model) -> str:
    fam = getattr(getattr(model, "config", None), "family", None)
    if fam:
        return fam
    name = type(model).__name__.lower()
    for fam in ("mixtral", "mistral", "llama", "gpt2", "opt", "falcon", "phi"):
        if fam in name:
            return fam
    raise ValueError(f"cannot infer model family from {type(model).__name__}; "
                     f"pass family=")
