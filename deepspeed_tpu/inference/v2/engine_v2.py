"""Inference engine v2 — continuous batching over a paged KV cache.

Parity: ``InferenceEngineV2`` (reference ``inference/v2/engine_v2.py:30``):
``put(uids, tokens) -> logits`` (:107), ``query`` (:153), ``can_schedule`` (:179),
``flush``, plus a convenience ``generate`` driving continuous batching the way
MII's serving loop drives the reference engine.

TPU-native structure per pass (one jitted call, static shapes):

    host: DynamicSplitFuseScheduler builds RaggedBatch descriptor arrays
      |                                   (``scheduler.py``)
    device: ragged forward — scan over layers; paged KV write + chunk/decode
      Pallas attention; MoE grouped GEMM      (``ragged_model.py``)
    host: sample / collect last-token logits, advance descriptors

The steady-state decode hot path does NOT run that per-pass loop: it runs
bucketed fused decode programs (sampling on device, one int32 token row per
step crossing to host) driven either as ``decode_steps`` bursts or through
the async double-buffered ``DecodePipeline`` (``pipeline.py``); see
docs/SERVING.md for the full picture (bucketing grids, the one-step-late
drain, AOT warmup).

KV pages are donated through the pass (XLA aliases them in HBM — the functional
analog of the reference writing its blocked KV cache in place).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import (TENSOR_AXIS, MeshTopology, build_topology,
                                     set_topology)
from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache, KVCacheConfig
from deepspeed_tpu.inference.v2.ragged_model import adapt_model, build_ragged_forward
from deepspeed_tpu.inference.v2.scheduler import DynamicSplitFuseScheduler
from deepspeed_tpu.monitor.trace import install_from_env as _trace_from_env
from deepspeed_tpu.monitor.trace import tracer as _tracer
from deepspeed_tpu.utils.caching import LRUCache, next_pow2
from deepspeed_tpu.utils import locksan as _locksan
from deepspeed_tpu.utils.fault_injection import maybe_fail as _maybe_fail
from deepspeed_tpu.utils.logging import log_dist


import functools
import time as _time


def fetch_to_host(arr) -> np.ndarray:
    """THE device->host drain point for the v2 serving hot path.

    Every blocking fetch of a device array in ``inference/v2`` routes through
    here: the serving loops are engineered so the only thing drained per
    decode step is a bucket-sized int32 token row, and funnelling the drain
    through one function lets jaxlint rule JL007 statically police the hot
    path for stray blocking fetches (an accidental ``np.asarray(logits)``
    re-introduces the [S, V] per-step transfer this engine exists to avoid).

    Under tracing the drain records a ``serve/drain/fetch_to_host`` span, so
    host-sync cost on the serving path is always attributed by name
    (docs/OBSERVABILITY.md).
    """
    if _locksan.enabled():
        # runtime TL002 signal: a drain while sanitized locks are held
        _locksan.note_blocking("fetch_to_host")
    if not _tracer.enabled:
        return np.asarray(arr)  # jaxlint: disable=JL007 -- the intentional drain
    t0 = _time.perf_counter()
    out = np.asarray(arr)  # jaxlint: disable=JL007 -- the intentional drain
    _tracer.add("serve/drain/fetch_to_host", t0, _time.perf_counter(),
                lane="serve/drain")
    return out


@functools.partial(jax.jit, static_argnums=(3, 4))
def _dev_sample(arr, rows, key, do_sample: bool, top_k: int, temperature=1.0):
    """Gather rows + greedy / temperature / top-k sampling, ONE device call.
    arr [P, V] (or [V] with rows=None semantics handled by caller reshaping);
    rows [n] int32."""
    logits = arr[rows]
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    z = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jax.lax.top_k(z, top_k)[0][:, -1:]
        z = jnp.where(z < kth, -jnp.inf, z)
    return jax.random.categorical(key, z, axis=-1)


class InferenceEngineV2:

    def __init__(self,
                 model: Any = None,
                 config: Optional[RaggedInferenceEngineConfig] = None,
                 model_parameters: Any = None,
                 family: Optional[str] = None,
                 mesh_topology: Optional[MeshTopology] = None):
        self.config = RaggedInferenceEngineConfig.load(config)
        cfg = self.config
        # persistent XLA compile cache: configured FIRST so every program this
        # constructor (and the optional AOT warmup below) compiles lands in it
        # — a second engine start then reloads instead of recompiling
        cache_dir = cfg.compile.resolve_cache_dir()
        if cache_dir:
            from deepspeed_tpu.utils.compile_cache import setup_compile_cache
            setup_compile_cache(
                cache_dir=cache_dir,
                min_compile_time_secs=cfg.compile.min_compile_time_secs)
        # device programs built by this engine (each is called with exactly
        # one signature, so builds == XLA compiles modulo the persistent
        # cache). Warmup pre-builds the serving grid; a serving loop whose
        # batch sizes stay in-grid must never increment this again.
        self.compiles = 0
        tp = cfg.tensor_parallel
        if mesh_topology is not None:
            self.topology = set_topology(mesh_topology)
        else:
            n = len(jax.devices())
            self.topology = set_topology(build_topology(
                MeshConfig(tensor=tp, data=n // tp, fsdp=1)))

        model_config = getattr(model, "config", None)
        if model_config is None:
            raise ValueError("InferenceEngineV2 needs a model with .config")
        if family is None:
            family = _guess_family(model)
        self.family = family
        # adapter inputs, re-run by the colocated WeightBridge
        # (runtime/colocated.py) to trace the train->serve reshard program
        self.model_config = model_config
        # monotone weight-version stamp: bumped by every swap_weights();
        # the prefix cache keys/flushes on it (stale-KV refusal) and the
        # serving frontend tags post-swap streams with it
        self.weight_version = 0
        if model_parameters is None:
            raise ValueError("InferenceEngineV2 needs model_parameters")
        from deepspeed_tpu.utils.tree import tree_cast
        params = tree_cast(model_parameters, cfg.dtype)
        self.spec, weights = adapt_model(family, params, model_config,
                                         max_context=cfg.state_manager.max_context)
        self.spec.dtype = cfg.dtype
        if cfg.quantization.weight_bits in (4, 8):
            if tp > 1:
                raise NotImplementedError(
                    "weight-only int4/int8 with tensor_parallel > 1 is not "
                    "wired yet (the AutoTP rule walker shards plain arrays); "
                    "run quantized at tp=1 or bf16 under tp")
            from deepspeed_tpu.inference.v2.ragged_model import (
                quantize_weights_int4, quantize_weights_int8)
            weights = (quantize_weights_int8(weights)
                       if cfg.quantization.weight_bits == 8
                       else quantize_weights_int4(weights))
        self.weights = self._shard_weights(weights)

        # KV cache + allocator + scheduler
        sm = cfg.state_manager
        nb = cfg.kv_cache.num_blocks
        if nb is None:
            # pool sized to hold max_tracked_sequences at max_context (CPU tests);
            # on TPU prefer an explicit num_blocks or memory-fraction sizing
            per_seq = -(-sm.max_context // cfg.kv_cache.block_size)
            nb = per_seq * sm.max_tracked_sequences
        # the ONE build-time capability table (inference/v2/attention.py):
        # every surviving (feature x feature) refusal raises here; what
        # does NOT raise composes — int8 KV pages run under the prefix
        # cache, spec decode, preempt-offload and the page fabric
        from deepspeed_tpu.inference.v2.attention import AttentionKernelSpec
        AttentionKernelSpec.validate_engine_build(self.spec, cfg)
        # the pool carries ONE page beyond the allocator's reach: the scratch
        # page backing bucket-padding rows in the fused decode programs (pad
        # rows read/write only it, so padding a batch to its power-of-two
        # bucket never touches a live sequence's KV). Outside the allocator
        # on purpose — free/total accounting and the prefix cache never see
        # it, and it can never be handed to a sequence.
        kv_cfg = KVCacheConfig(
            num_layers=self.spec.num_layers,
            num_kv_heads=self.spec.num_kv_heads,
            head_dim=self.spec.head_dim,
            block_size=cfg.kv_cache.block_size,
            num_blocks=nb + 1,
            dtype=cfg.dtype,
            quantized=cfg.kv_quant.enabled)
        self.scratch_block = nb
        self.kv = BlockedKVCache(kv_cfg, self.topology)
        self.allocator = BlockedAllocator(nb)
        self.prefix_cache = None
        if cfg.prefix_cache.enabled:
            # (window refusal raised by validate_engine_build above; int8
            # pools compose — copy_page COW-copies the scale tile with the
            # page, tests/unit/test_kv_quant_stack.py)
            from deepspeed_tpu.inference.v2.prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(
                self.allocator, kv_cfg.block_size,
                max_cached_blocks=cfg.prefix_cache.max_cached_blocks,
                cow_fn=self.kv.copy_page)
        self.scheduler = DynamicSplitFuseScheduler(sm, self.kv, self.allocator,
                                                   prefix_cache=self.prefix_cache)
        # sliding-window serving (Mistral/Qwen2): the scheduler ring-reuses
        # each sequence's pages beyond the window so KV stays bounded
        self.scheduler.window = self.spec.window
        if cfg.spec_decode.enabled:
            # (window refusal raised by validate_engine_build above; int8
            # pools compose — build_verify_step quantizes-on-write and the
            # chunk kernel dequantizes in-flight)
            # the n-gram proposer drafts from each sequence's prompt
            # history — record it even without a prefix cache
            self.scheduler.record_history_always = True

        if self.spec.alibi and tp > 1:
            # the paged kernels compute ALiBi slopes from shard-LOCAL head
            # indices; under head-sharded TP every shard would reuse the
            # first shard's half-sized slope schedule (review r5: measured
            # 0.72 max abs err on 8 virtual devices) — refuse until the
            # kernels take a global head offset
            raise NotImplementedError(
                "ALiBi models with tensor_parallel > 1 are not wired in the "
                "ragged engine (shard-local slope schedules would be wrong); "
                "run tp=1 or serve through init_inference")
        eff_tp = tp if (tp > 1 and self.spec.num_kv_heads % tp == 0
                        and self.spec.num_heads % tp == 0) else 1
        self._eff_tp = eff_tp
        fwd = build_ragged_forward(self.spec, mesh=self.topology.mesh, tp=eff_tp)
        self._pass = jax.jit(fwd, donate_argnums=(1,))
        self.compiles += 1
        # flash-decoding split ladder (config.attention; docs/SERVING.md
        # "Attention kernels"): one ragged-pass program per pow2 rung.
        # Rung 1 IS self._pass — the byte-identical chunk-serial program;
        # higher rungs rebuild the pass with split-K attention bound
        # (ops/pallas/paged_splitk.py). The fused decode/multistep/verify
        # grids grow the same rung axis through their cache keys, and
        # warmup() pre-builds every (grid point x rung) so the
        # admission-driven rung choice (_attn_rung) never compiles on the
        # hot path. decode_splits == 1 (default) leaves all of this inert.
        self._pass_rungs = {1: self._pass}
        for r in self.attn_split_ladder[1:]:
            fwd_r = build_ragged_forward(self.spec, mesh=self.topology.mesh,
                                         tp=eff_tp, n_splits=r)
            self._pass_rungs[r] = jax.jit(fwd_r, donate_argnums=(1,))
            self.compiles += 1
        # bench/test knob: pin the dispatched rung (None = admission-driven)
        self.attn_rung_override: Optional[int] = None
        self._pass_prefill = None  # built on the first pure-prefill pass
        self._rng = np.random.RandomState(cfg.seed)
        self._rng_key = jax.random.PRNGKey(cfg.seed)
        self._last_logits: Dict[int, np.ndarray] = {}
        # device-resident logits refs: uid -> (device_array, row).
        # Materialised to numpy lazily (put()) or sampled on device without
        # ever shipping the [S, V] tensor to host (sample_next()).
        self._last_ref: Dict[int, Tuple[Any, int]] = {}
        # LRU-bounded compiled multistep programs: keyed by (n_steps, BUCKET,
        # do_sample, top_k) where BUCKET = next_pow2(live rows) — serving with
        # many batch sizes reuses ~log2 executables, and the LRU bound keeps a
        # long-lived process from accumulating programs for retired burst
        # lengths. Callers hold the returned program through the call, so
        # eviction can never free an executable mid-flight (Python refs).
        self._multistep: LRUCache = LRUCache(maxsize=8)
        # compiled single-step fused decode programs (DecodePipeline), keyed
        # by (bucket, do_sample, top_k); one per grid point
        self._step_progs: LRUCache = LRUCache(maxsize=16)
        # compiled verify-step programs (spec/pipeline.py), keyed by
        # (bucket, k) — the speculation grid warmup() pre-compiles
        self._verify_progs: LRUCache = LRUCache(maxsize=16)
        self._spec_warned_sampling = False
        # KV page host round-trip programs (gather, scatter) — the serving
        # frontend's preempt-offload path (serving/kv_offload.py); built
        # lazily, warmed by warmup() so a mid-steady-state preemption never
        # observes a compile. _page_buckets tracks the (op, pow2-count)
        # signatures already compiled (the compiles-counter unit here).
        self._page_progs = None
        self._page_buckets: set = set()
        # aggregate double-buffer pipeline timings (monitor/serving.py);
        # write_monitor_events emits them
        from deepspeed_tpu.monitor.serving import (AttnSplitStats,
                                                   PipelineStats,
                                                   SpecDecodeStats)
        self.pipeline_stats = PipelineStats()
        self.spec_stats = SpecDecodeStats()
        # split-ladder rung-selection counters (serve/attn/* events; fed by
        # the same perf stamps as the serve/attn/select trace spans)
        self.attn_stats = AttnSplitStats()
        # multi-tenant LoRA: adapter registry + paged weight pool
        # (inference/v2/lora/; docs/SERVING.md "Multi-tenant LoRA"). The
        # decode/verify program grid grows a rank-bucket axis; the pool's
        # host movers count compiles through the engine counter so the
        # zero-steady-state-compile gate covers adapter churn too.
        self.lora = None
        if cfg.lora.enabled:
            if tp > 1:
                # the grouped-matmul pages pack WHOLE projection columns/rows
                # per rank slice; under head-sharded TP each shard would need
                # its slice of every page — refuse until the pool is sharded
                raise NotImplementedError(
                    "multi-tenant LoRA with tensor_parallel > 1 is not wired "
                    "(adapter pages are unsharded whole-projection slices); "
                    "run lora at tp=1")
            from deepspeed_tpu.inference.v2.lora import (LoraAdapterRegistry,
                                                         LoraPagePool)

            def _count_compile():
                self.compiles += 1

            self.lora = LoraAdapterRegistry(
                LoraPagePool(self.spec, cfg.lora.targets, cfg.lora.pool_pages,
                             compile_hook=_count_compile),
                swap_buffers=cfg.lora.swap_buffers,
                max_rank=cfg.lora.max_rank)
        # serving runs don't pass through deepspeed_tpu.initialize — arm the
        # span tracer from $DSTPU_TRACE here (no-op when unset/armed)
        _trace_from_env()
        log_dist(f"engine_v2: family={family} tp={eff_tp} blocks={nb}+scratch "
                 f"block_size={kv_cfg.block_size} budget={sm.max_ragged_batch_size}",
                 ranks=[0])
        if cfg.compile.warmup:
            self.warmup(buckets=cfg.compile.warmup_buckets,
                        burst_steps=cfg.compile.warmup_decode_steps)

    # ------------------------------------------------------------------ #

    def _shard_weights(self, weights):
        """TP sharding of the canonical stacked weights via the shared AutoTP
        rule walker (``parallel/tensor_parallel.py``) — one source of truth for
        column/row assignments; non-divisible dims warn and replicate."""
        topo = self.topology
        tp = topo.tp_world_size
        if tp <= 1:
            return jax.device_put(weights, topo.replicated())
        from deepspeed_tpu.parallel.tensor_parallel import (
            RAGGED_STACKED_TP_RULES, derive_tp_specs)
        specs = derive_tp_specs(weights, RAGGED_STACKED_TP_RULES, tp)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(topo.mesh, s), specs,
            is_leaf=lambda s: isinstance(s, P))
        return jax.device_put(weights, shardings)

    # ------------------------------------------------------------------ #
    # in-place weight swap (colocated rollout; runtime/colocated.py)
    # ------------------------------------------------------------------ #

    def swap_weights(self, new_weights: Any,
                     version: Optional[int] = None) -> int:
        """Rebind ``self.weights`` to a new device tree in place — the
        train->serve sync point of the colocated rollout loop.

        Every device program this engine builds (the pass/decode/multistep/
        verify grids, warmup() included) takes the weight tree as a RUNTIME
        operand (``prog(self.weights, self.kv.kv, ...)``), so a swap whose
        tree matches the old one leaf-for-leaf in structure, shape, dtype
        and sharding reuses every cached executable: ZERO new compiles, the
        pow2/split/rank ladders survive untouched. Anything that does not
        match is refused up front — a silent mismatch would recompile the
        grid mid-steady-state (or serve garbage).

        The caller must have quiesced the engine first: no live sequences
        (KV computed under the old weights must never be decoded under the
        new ones — the ServingFrontend's swap path recompute-preempts
        in-flight requests at a run boundary exactly like preemption).
        The prefix cache is flushed by weight-version stamp, and host-side
        logits snapshots from pre-swap passes are dropped.

        Returns the new ``weight_version``."""
        if self.scheduler.seqs:
            raise RuntimeError(
                f"swap_weights with {len(self.scheduler.seqs)} live "
                "sequence(s) — their KV was computed under the old weights; "
                "quiesce first (frontend swap preempts at a run boundary, "
                "direct drivers flush() every uid)")
        old_leaves, old_def = jax.tree_util.tree_flatten(self.weights)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_weights)
        if new_def != old_def:
            raise ValueError(
                "swap_weights tree structure mismatch — the replacement "
                "tree must come from the same family adapter layout "
                f"(expected {old_def}, got {new_def})")
        paths = [jax.tree_util.keystr(kp) for kp, _ in
                 jax.tree_util.tree_flatten_with_path(self.weights)[0]]
        for path, o, n in zip(paths, old_leaves, new_leaves):
            if o.shape != n.shape or o.dtype != n.dtype:
                raise ValueError(
                    f"swap_weights leaf {path}: expected "
                    f"{o.dtype}{list(o.shape)}, got {n.dtype}{list(n.shape)} "
                    "— a shape/dtype drift would recompile every warmed "
                    "program")
            osh = getattr(o, "sharding", None)
            nsh = getattr(n, "sharding", None)
            if osh is not None and nsh != osh:
                raise ValueError(
                    f"swap_weights leaf {path}: sharding {nsh} != engine "
                    f"layout {osh} — reshard through WeightBridge "
                    "(runtime/colocated.py), whose out_shardings are taken "
                    "from this engine's weights")
        if version is None:
            version = self.weight_version + 1
        elif version <= self.weight_version:
            raise ValueError(
                f"swap_weights version {version} is not newer than the "
                f"current weight_version {self.weight_version} — versions "
                "are monotone (the prefix cache keys staleness on them)")
        self.weights = new_weights
        self.weight_version = version
        if self.prefix_cache is not None:
            # flush-by-version: cached KV pages hold old-weight state; a
            # post-swap match must miss and re-prefill (regression-pinned
            # by tests/unit/test_colocated.py)
            self.prefix_cache.set_weight_version(version)
        # host-side logits snapshots and device row refs from pre-swap
        # passes are old-weight state: drop, never resample from them
        self._last_logits.clear()
        self._last_ref.clear()
        return version

    # ------------------------------------------------------------------ #
    # public API (parity: engine_v2.py put/query/can_schedule/flush)
    # ------------------------------------------------------------------ #

    def put(self, uids: Sequence[int], tokens_list: Sequence[np.ndarray],
            do_checks: bool = True) -> np.ndarray:
        """Schedule these tokens and run passes until all are consumed. Returns
        next-token logits [len(uids), vocab] in the order given."""
        uids = [int(u) for u in uids]
        if do_checks and not self.scheduler.can_schedule(
                uids, [len(t) for t in tokens_list]):
            raise RuntimeError("cannot schedule: insufficient KV blocks or "
                               "sequence slots (check can_schedule first)")
        for uid, toks in zip(uids, tokens_list):
            self.scheduler.add_tokens(uid, np.asarray(toks, np.int32))

        want = set(uids)
        while self.scheduler.has_pending():
            self._run_pass()
        self._materialize(want)
        missing = want - set(self._last_logits)
        if missing:
            raise RuntimeError(f"no logits produced for uids {sorted(missing)}")
        return np.stack([self._last_logits[u] for u in uids])

    def _put_nofetch(self, uids: Sequence[int],
                     tokens_list: Sequence[np.ndarray]) -> None:
        """Like put(), but leaves the logits on device (see sample_next)."""
        uids = [int(u) for u in uids]
        for uid, toks in zip(uids, tokens_list):
            self.scheduler.add_tokens(uid, np.asarray(toks, np.int32))
        while self.scheduler.has_pending():
            self._run_pass()

    def _materialize(self, uids) -> None:
        """Fetch pending device logits to numpy, one transfer per pass array."""
        by_array: Dict[int, Tuple[Any, list]] = {}
        for uid in uids:
            ref = self._last_ref.pop(uid, None)
            if ref is None:
                continue
            arr, row = ref
            by_array.setdefault(id(arr), (arr, []))[1].append((uid, row))
        for arr, pairs in by_array.values():
            host = fetch_to_host(arr)
            for uid, row in pairs:
                self._last_logits[uid] = host[row]

    def sample_next(self, uids: Sequence[int], do_sample: bool = False,
                    temperature: float = 1.0, top_k: int = 0) -> np.ndarray:
        """Sample the next token for each uid ON DEVICE from its last logits,
        fetching only the token ids (4 bytes/seq instead of the [S, V] logits
        tensor — through a remote tunnel or PCIe this is the difference between
        transfer-bound and compute-bound decode)."""
        padded, n = self._sample_device_padded([int(u) for u in uids],
                                               do_sample, temperature, top_k)
        # slice AFTER the host fetch: a device-side [:n] would compile a new
        # tiny executable for every distinct live-sequence count
        return fetch_to_host(padded)[:n]

    def _sample_device(self, uids: Sequence[int], do_sample: bool,
                       temperature: float, top_k: int):
        """Sample next tokens on device, returning a device array aligned with
        ``uids`` (no host fetch). Prefer :meth:`_sample_device_padded` where a
        padded result is acceptable — the exact-length slice here compiles one
        tiny program per distinct ``len(uids)``."""
        padded, n = self._sample_device_padded(uids, do_sample, temperature,
                                               top_k)
        return padded[:n]

    def _sample_device_padded(self, uids: Sequence[int], do_sample: bool,
                              temperature: float, top_k: int):
        """Like :meth:`_sample_device` but returns ``(padded_ids, n)`` where
        ``padded_ids`` has a power-of-two length >= n: every device program in
        here is then keyed by the BUCKET size, so a serving loop whose live
        set shrinks by one each retirement reuses cached executables instead
        of recompiling per count (~seconds each through a remote-compile
        tunnel; measured 5 s/iteration in benchmarks/serving_bench.py)."""
        if not uids:
            return jnp.zeros((1,), jnp.int32), 0
        order = np.empty(len(uids), np.int64)
        parts = []
        by_array: Dict[int, Tuple[Any, list]] = {}
        host_rows, host_idx = [], []
        for i, uid in enumerate(uids):
            ref = self._last_ref.get(int(uid))
            if ref is None:
                # logits were materialised to host (a prior put()); re-upload
                host_idx.append(i)
                host_rows.append(self._last_logits[int(uid)])
                continue
            arr, row = ref
            by_array.setdefault(id(arr), (arr, []))[1].append((i, row))
        if host_rows:
            # the re-upload block is BUCKETED too (rows repeat row 0, never
            # referenced): host-rematerialized sources appear whenever a
            # preempt-offloaded sequence is restored (serving/kv_offload.py
            # parks the victim's last logits row on host), and a count-shaped
            # [n, V] upload would compile a fresh _dev_sample per distinct
            # restore count — in the middle of the steady state the
            # zero-compile gate polices. pow2 shapes land in the warmed grid.
            pad = next_pow2(len(host_rows)) - len(host_rows)
            arr = jnp.asarray(np.stack(host_rows + [host_rows[0]] * pad))
            by_array[id(arr)] = (arr, [(i, j) for j, i in enumerate(host_idx)])
        n_done = 0
        for arr, pairs in by_array.values():
            rows = [r for _, r in pairs]
            if do_sample:
                self._rng_key, sub = jax.random.split(self._rng_key)
            else:
                sub = self._rng_key
            # pad the row set to its bucket (utils.caching.next_pow2): a
            # serving loop calls this with a DIFFERENT number of live
            # sequences every time a sequence retires, and each distinct
            # length would recompile _dev_sample (~seconds through a
            # remote-compile tunnel; measured 5 s/iteration in
            # benchmarks/serving_bench.py). Extra rows resample row 0 and
            # are sliced off.
            n_real = len(rows)
            rows = rows + [rows[0]] * (next_pow2(n_real) - n_real)
            out = _dev_sample(arr, np.asarray(rows, np.int32), sub,
                              bool(do_sample), int(top_k),
                              float(temperature))
            parts.append(out)                 # padded; real rows are [:n_real]
            for j, (i, _) in enumerate(pairs):
                order[i] = n_done + j
            n_done += len(out)                # padded offsets
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        # pad the reorder gather to the bucket size too (same reasoning)
        n = len(uids)
        order_pad = np.concatenate([order,
                                    np.zeros(next_pow2(n) - n, np.int64)])
        return flat[jnp.asarray(order_pad, jnp.int32)].astype(jnp.int32), n

    def decode_steps(self, uids: Sequence[int], n_steps: int,
                     do_sample: bool = False, temperature: float = 1.0,
                     top_k: int = 0, fetch: bool = True
                     ) -> "np.ndarray | jax.Array":
        """Generate ``n_steps`` tokens for every uid with ONE device program
        (fused sample->forward->sample loop; see build_multistep_decode).
        All uids must be in steady decode state (no pending tokens).  Returns
        the generated ids [len(uids), n_steps]; the engine's last-logits refs
        advance so normal put()/sample_next() calls can continue after.

        ``fetch=False`` returns the DEVICE array, already shaped [S,
        n_steps] like the fetched result (the transpose is a free layout op
        on device — ADVICE r4: the old [n_steps, S] return was a silent-
        corruption footgun when S == n_steps): the call then costs only a
        dispatch, so back-to-back bursts chain on device — through a remote
        runtime the synchronous ids fetch is ~an RTT per burst, which would
        otherwise serialise host RTT into every burst.

        The device program runs at ``next_pow2(len(uids))`` rows (pad rows
        decode into the engine's scratch page): programs are keyed by the
        bucket, so the live count drifting with admissions/retirements reuses
        cached executables, and ``warmup()`` can pre-compile the whole grid.
        Row-independent decode keeps real rows byte-identical under padding
        (greedy); batch-sampled rows draw from a [bucket, V] noise block, so
        SAMPLED streams depend on the bucket (not on which other rows are
        pads) — a documented trade, not a bug."""
        uids = [int(u) for u in uids]
        S = len(uids)
        assert not self.scheduler.has_pending(), \
            "decode_steps requires a drained scheduler"
        # bucketed descriptors: the program below is keyed by the BUCKET, so a
        # serving loop admitting/retiring sequences reuses ~log2 executables
        db = self.scheduler.decode_batch(uids, n_steps + 1, self.scratch_block)
        sp = self._attn_rung()
        fn = self._multistep.get_or_create(
            (n_steps, db.bucket, bool(do_sample), int(top_k), sp),
            lambda: self._build_multistep(n_steps, do_sample, top_k, sp))
        # already bucket-padded: pad entries re-sample a real row's logits but
        # run against the scratch page, so they cannot touch live KV
        ids0, _ = self._sample_device_padded(uids, do_sample, temperature,
                                             top_k)
        assert ids0.shape[0] == db.bucket
        self._rng_key, sub = jax.random.split(self._rng_key)
        out_ids, final_logits, new_kv = fn(
            self.weights, self.kv.kv, ids0, db.positions, db.block_tables,
            db.ctx_lens, sub, jnp.float32(temperature))
        self.kv.update(new_kv)
        for i, u in enumerate(uids):
            self.scheduler.advance(u, n_steps)
            self._last_ref[u] = (final_logits, i)
            self._last_logits.pop(u, None)
        if not fetch:
            ids_t = out_ids.T           # device [bucket, n_steps]
            # the pad-row slice compiles one tiny gather per (bucket, S) —
            # only paid when the bucket is not exactly full
            return ids_t if db.bucket == S else ids_t[:S]
        return fetch_to_host(out_ids).T[:S]    # [S, n_steps]

    def _decode_step_prog(self, bucket: int, do_sample: bool, top_k: int,
                          rb: int = 0, sp: Optional[int] = None):
        """The fused single-step decode program (forward + on-device sampling,
        ragged_model.build_decode_step) for one bucket — the DecodePipeline's
        hot program. LRU-cached per (bucket, do_sample, top_k, rb).

        ``rb`` is the LoRA rank bucket (``lora.rank_bucket`` — pow2, engine-
        stable after registration): rb > 0 builds the grouped-matmul variant
        taking the ``(lora_pool, adapter_pt [bucket, rb])`` trailing operands;
        rb = 0 is EXACTLY the pre-LoRA program, so adapter-free engines are
        byte-unchanged. Distinct rb values are distinct keys — a separate jit
        wrapper each — so every compile stays witnessed by the counter (one
        shared jit re-specializing on the page-table shape would compile
        silently).

        ``sp`` is the flash-decoding split rung (None = this step's
        admission-driven :meth:`_attn_rung`); each rung is its own key so
        rung swaps reuse warmed executables."""
        sp = self._attn_rung() if sp is None else int(sp)

        def _build():
            from deepspeed_tpu.inference.v2.ragged_model import (
                build_decode_step)
            tp = self.topology.tp_world_size
            fwd = build_decode_step(self.spec, mesh=self.topology.mesh,
                                    tp=tp if tp > 1 else 1,
                                    do_sample=do_sample, top_k=top_k,
                                    window_ring_ok=self.scheduler.ring_covers(2),
                                    lora_targets=self._lora_targets(rb),
                                    n_splits=sp)
            self.compiles += 1
            return jax.jit(fwd, donate_argnums=(1,))

        return self._step_progs.get_or_create(
            (bucket, bool(do_sample), int(top_k), int(rb), sp), _build)

    def _lora_targets(self, rb: int):
        """The ``lora_targets`` builder knob for a rank bucket: the engine's
        configured projection set when rb > 0, None (base program) at rb=0."""
        if rb == 0:
            return None
        assert self.lora is not None, "rank-bucketed program without LoRA"
        return self.config.lora.targets

    @property
    def lora_rank_bucket(self) -> int:
        """The rank bucket current decode dispatch runs at: the registry's
        ``rank_bucket`` (0 when LoRA is off or only rank-0 adapters exist —
        the base programs)."""
        return self.lora.rank_bucket if self.lora is not None else 0

    def _lora_operands(self, uids: Sequence[int], bucket: int,
                       rb: Optional[int] = None) -> tuple:
        """The trailing ``*lora_args`` for a rank-bucketed program: the pool
        array plus the device page table for these rows. Empty at rb=0 so
        callers can splat unconditionally. Built once per pipeline RUN (the
        batch's adapter bindings are frozen for the run, like block tables —
        the in-jit gather is hoisted out of the step scan on that
        invariant)."""
        rb = self.lora_rank_bucket if rb is None else rb
        if rb == 0:
            return ()
        pt = self.lora.page_table(uids, bucket, rb)
        return (self.lora.pool.pool, jnp.asarray(pt))

    @property
    def attn_split_ladder(self) -> List[int]:
        """The pow2 flash-decoding rung grid attention dispatches over:
        ``[1, 2, 4, ..., config.attention.decode_splits]``. Rung 1 is the
        chunk-serial kernel set exactly; each higher rung cuts every
        sequence's page range into that many grid-parallel split-K partials
        (docs/SERVING.md "Attention kernels"). warmup() pre-compiles every
        program grid point at every rung, so the per-step rung choice
        (:meth:`_attn_rung`) swaps cached executables — never compiles."""
        top = self.config.attention.decode_splits
        return [1 << i for i in range(top.bit_length())]

    def _attn_rung(self) -> int:
        """The split rung for THIS step's dispatch: the largest pow2 rung
        such that the longest live context keeps ``min_ctx_per_split``
        tokens per split, clamped to the warmed ladder — short-context
        batches stay on the split=1 chunk-serial program (the merge pass is
        pure overhead there) and the long-context tail climbs the ladder as
        it grows. ``attn_rung_override`` pins the choice (bench A/B legs on
        one warmed engine). Records the selection through the shared perf
        stamps: one ``perf_counter`` pair feeds both the
        ``serve/attn/select`` trace span and ``attn_stats`` (the
        serve/attn/* monitor events), so timeline and dashboard agree."""
        top = self.config.attention.decode_splits
        if top <= 1:
            return 1
        if self.attn_rung_override is not None:
            return max(1, min(int(self.attn_rung_override), top))
        t0 = _time.perf_counter()
        live = max((s.seen_tokens for s in self.scheduler.seqs.values()),
                   default=0)
        want = max(1, live // self.config.attention.min_ctx_per_split)
        rung = min(top, 1 << (want.bit_length() - 1))
        t1 = _time.perf_counter()
        self.attn_stats.record(rung, live, t1 - t0)  # jaxlint: disable=JL001 -- host-only scheduler scan, nothing dispatched
        if _tracer.enabled:
            _tracer.add("serve/attn/select", t0, t1, lane="serve/attn",
                        rung=rung, live_ctx=live)
        return rung

    @property
    def spec_k_ladder(self) -> List[int]:
        """The draft-length grid speculation dispatches over: pow2-minus-1
        rungs (K+1 a power of two — the chunk kernel's q-block then covers
        each sequence's rows in ONE block instead of collapsing to 1-row
        blocks) up to ``config.spec_decode.k``. Each step runs the SMALLEST
        rung covering its longest draft, so a mostly-unrepetitive batch
        pays 2-row verifies, not full-k ones; warmup() pre-compiles the
        whole (bucket, rung) grid."""
        k = self.config.spec_decode.k
        ks, v = [], 1
        while v < k:
            ks.append(v)
            v = 2 * v + 1
        ks.append(k)
        return sorted(set(ks))

    def _verify_prog(self, bucket: int, k: int, rb: int = 0,
                     sp: Optional[int] = None):
        """The fused speculative verify-step program (draft scoring in ONE
        ragged forward, ragged_model.build_verify_step) for one (bucket, k)
        grid point — the SpecDecodePipeline's hot program. LRU-cached;
        warmup() pre-compiles the whole grid. ``rb`` as in
        :meth:`_decode_step_prog` — rb > 0 verifies WITH each row's adapter
        delta (the K+1 token rows share the sequence's adapter), keeping
        accepted spec tokens byte-identical to plain LoRA decode. ``sp`` as
        in :meth:`_decode_step_prog` — verify rides the SAME split rung as
        decode so spec streams stay on warmed programs across the ladder."""
        sp = self._attn_rung() if sp is None else int(sp)

        def _build():
            from deepspeed_tpu.inference.v2.ragged_model import (
                build_verify_step)
            tp = self.topology.tp_world_size
            fwd = build_verify_step(self.spec, k, mesh=self.topology.mesh,
                                    tp=tp if tp > 1 else 1,
                                    lora_targets=self._lora_targets(rb),
                                    n_splits=sp)
            self.compiles += 1
            return jax.jit(fwd, donate_argnums=(1,))

        return self._verify_progs.get_or_create(
            (bucket, int(k), int(rb), sp), _build)

    def decode_pipeline(self, uids: Sequence[int], do_sample: bool = False,
                        temperature: float = 1.0, top_k: int = 0):
        """The steady-state decode pipeline over ``uids`` (all must be in
        steady decode state). Default: the async double-buffered
        ``pipeline.DecodePipeline`` — while the device runs step N, the host
        drains step N-1's token row and builds step N+1's descriptors; the
        only per-step transfer is one int32 row.

        With ``config.spec_decode.enabled``, greedy requests get the
        ``spec.SpecDecodePipeline`` instead (draft-and-verify, variable
        per-step advance; callers branch their ``on_tokens`` shape on
        ``pipe.spec``). Speculation is greedy-only for now: ``do_sample``
        cleanly bypasses it with a one-time warning rather than silently
        degrading sampled streams."""
        if self.config.spec_decode.enabled:
            if do_sample:
                if not self._spec_warned_sampling:
                    self._spec_warned_sampling = True
                    import warnings
                    warnings.warn(
                        "spec_decode is greedy-only for now: "
                        "do_sample=True bypasses speculation and runs the "
                        "plain DecodePipeline (warned once)", stacklevel=2)
            else:
                from deepspeed_tpu.inference.v2.spec import SpecDecodePipeline
                return SpecDecodePipeline(self, uids)
        from deepspeed_tpu.inference.v2.pipeline import DecodePipeline
        return DecodePipeline(self, uids, do_sample=do_sample,
                              temperature=temperature, top_k=top_k)

    # ------------------------------------------------------------------ #
    # AOT warmup (config_v2.CompileConfig)
    # ------------------------------------------------------------------ #

    @property
    def decode_buckets(self) -> List[int]:
        """The full reachable decode bucket grid: powers of two up to the
        scheduler's decode-row capacity."""
        top = next_pow2(self.config.state_manager.max_ragged_sequence_count)
        return [1 << i for i in range(top.bit_length())]

    def warmup(self, buckets: Optional[Sequence[int]] = None,
               burst_steps: Sequence[int] = (),
               spec_ks: Optional[Sequence[int]] = None) -> int:
        """Pre-compile the serving program set so in-grid traffic never
        observes an XLA compile (and, with a persistent compile cache
        configured, so a future engine start reloads everything from disk).

        Covers: the ragged paged pass, the prefill fast path, the fused
        decode-step program for every bucket (greedy — the serving default;
        sampled variants compile on first use), fused multistep programs for
        each ``burst_steps`` length across the grid, and the module-level
        bootstrap sampler ``_dev_sample`` over the logits-source shapes the
        serving loops read (chunk/decode pass outputs, per-bucket fused
        outputs, and pow2-padded host-rematerialized blocks — restore paths
        re-upload through the same bucket grid). Also warms the KV page
        offload/restore round-trip pair.
        Each program is executed once over scratch-page-only descriptors —
        real KV state, scheduler state and logits refs are untouched.

        Explicit ``buckets`` are rounded up to powers of two (the live path
        always rounds, so a non-pow2 bucket would be dead weight).

        Returns the number of ENGINE programs built (``self.compiles``; the
        bootstrap-sampler warms are module-level jits outside the counter).

        ``spec_ks``: draft lengths to warm the speculative verify grid for
        — one ``build_verify_step`` program per (bucket, k). ``None``
        defaults to the full ``spec_k_ladder`` when speculation is enabled
        (so a spec-serving engine's steady state — including the spec-off
        comparison legs sharing the engine — adds zero timed compiles).
        """
        before = self.compiles
        grid = sorted({next_pow2(int(b)) for b in buckets}) \
            if buckets is not None else self.decode_buckets
        if spec_ks is None:
            spec_ks = self.spec_k_ladder \
                if self.config.spec_decode.enabled else []
        spec_ks = sorted({int(k) for k in spec_ks})
        # LoRA rank rungs: pow2 up to next_pow2(lora.max_rank) — the whole
        # rank-bucket axis of the program grid (registration refuses larger
        # ranks, so live dispatch can never leave the warmed ladder). rb=0
        # (the base programs) is the existing grid below.
        lora_rungs: List[int] = []
        if self.lora is not None:
            top = next_pow2(self.config.lora.max_rank)
            lora_rungs = [1 << i for i in range(top.bit_length())]
        # the flash-decoding split-rung axis (attn_split_ladder): every
        # program grid below is warmed at EVERY rung, so the per-step
        # admission-driven rung choice swaps cached executables — context
        # growth climbing the ladder adds zero steady-state compiles
        attn_rungs = self.attn_split_ladder
        # the warmed set must FIT its LRUs, or warmup evicts programs it just
        # built and the zero-compiles invariant silently breaks on first use
        self._step_progs.maxsize = max(
            self._step_progs.maxsize,
            (len(lora_rungs) + 1) * len(grid) * len(attn_rungs) + 2)
        self._multistep.maxsize = max(
            self._multistep.maxsize,
            len(burst_steps) * len(grid) * len(attn_rungs) + 2)
        self._verify_progs.maxsize = max(
            self._verify_progs.maxsize,
            (len(lora_rungs) + 1) * len(spec_ks) * len(grid)
            * len(attn_rungs) + 2)
        self._warm_passes()
        mb = self.scheduler.max_blocks
        for sp in attn_rungs:
            for b in grid:
                prog = self._decode_step_prog(b, False, 0, sp=sp)
                args = self._scratch_step_args(b, mb)
                nxt, _logits, new_kv = prog(self.weights, self.kv.kv, *args)
                self.kv.update(new_kv)
                jax.block_until_ready(nxt)
        # the LoRA (bucket, rank-bucket) grid: every rung runs once over
        # all-pad rows with an all-zero-page table (exact-zero deltas — the
        # same traced shapes live mixed-tenant batches use)
        for rb in lora_rungs:
            for sp in attn_rungs:
                for b in grid:
                    prog = self._decode_step_prog(b, False, 0, rb, sp=sp)
                    args = self._scratch_step_args(b, mb)
                    lops = self._scratch_lora_args(b, rb)
                    nxt, _logits, new_kv = prog(self.weights, self.kv.kv,
                                                *args, *lops)
                    self.kv.update(new_kv)
                    jax.block_until_ready(nxt)
        for n_steps in burst_steps:
            for sp in attn_rungs:
                for b in grid:
                    fn = self._multistep.get_or_create(
                        (n_steps, b, False, 0, sp),
                        lambda n=n_steps, s=sp: self._build_multistep(
                            n, False, 0, s))
                    args = self._scratch_step_args(b, mb)
                    out_ids, _logits, new_kv = fn(self.weights, self.kv.kv,
                                                  *args)
                    self.kv.update(new_kv)
                    jax.block_until_ready(out_ids)
        # the speculative (bucket, k) verify grid: every program runs once
        # over all-scratch rows with zero proposed drafts (accept masks and
        # page writes exercise the same traced shapes live traffic uses)
        for k in spec_ks:
            for b in grid:
                for rb in [0] + lora_rungs:
                    for sp in attn_rungs:
                        prog = self._verify_prog(b, k, rb, sp=sp)
                        args = self._scratch_verify_args(b, k, mb)
                        lops = self._scratch_lora_args(b, rb)
                        _acc, nxt, _fl, new_kv = prog(self.weights,
                                                      self.kv.kv,
                                                      *args, *lops)
                        self.kv.update(new_kv)
                        jax.block_until_ready(nxt)
        # the KV page round-trip pair (preempt-offload / page fabric) over
        # its whole bucket grid: rare path, but a preemption DURING the
        # timed steady state must not compile — warm both ops per bucket
        # over the scratch page (content round-trips to itself; int8 pools
        # round-trip their packed values+scale-tile payload the same way)
        for b in self.page_buckets:
            pages = self.fetch_pages([self.scratch_block] * b)
            self.put_pages(pages, [self.scratch_block] * b)
        # the adapter-pool movers over their own rank-sized bucket grid — a
        # mid-steady-state adapter fault/evict must never compile either
        if self.lora is not None:
            self.lora.pool.warm(self.config.lora.max_rank)
        # the greedy bootstrap sampler over every logits-source shape a
        # serving loop can hand it: without this, the FIRST pipeline run /
        # burst after startup pays a small-but-real compile (an RTT-bound
        # stall through a remote-compile tunnel) that the engine counter
        # cannot witness (_dev_sample is a module-level jit)
        sm = self.config.state_manager
        V = self.spec.vocab_size
        src_rows = {sm.num_chunk_slots, sm.max_ragged_sequence_count} | set(grid)
        for b in grid:
            rows = np.zeros((b,), np.int32)
            for nr in src_rows:
                jax.block_until_ready(_dev_sample(
                    jnp.zeros((nr, V), jnp.float32), rows, self._rng_key,
                    False, 0, 1.0))
        built = self.compiles - before
        log_dist(f"engine_v2: warmup built {built} programs "
                 f"(buckets={grid}, burst_steps={list(burst_steps)})",
                 ranks=[0])
        return built

    def _build_multistep(self, n_steps: int, do_sample: bool, top_k: int,
                         sp: int = 1):
        """Build (and count) one fused multistep program — the same builder
        decode_steps uses, shared so warmup pre-compiles identical keys.
        ``sp`` is the flash-decoding split rung the program attends at."""
        from deepspeed_tpu.inference.v2.ragged_model import (
            build_multistep_decode)
        tp = self.topology.tp_world_size
        fwd = build_multistep_decode(
            self.spec, n_steps, mesh=self.topology.mesh,
            tp=tp if tp > 1 else 1, do_sample=do_sample, top_k=top_k,
            window_ring_ok=self.scheduler.ring_covers(n_steps + 1),
            n_splits=int(sp))
        self.compiles += 1
        return jax.jit(fwd, donate_argnums=(1,))

    def _scratch_step_args(self, bucket: int, max_blocks: int):
        """All-pad-row inputs for a fused decode program: every row is the
        inert scratch-page fake sequence DecodeBatch pads with."""
        ids = jnp.zeros((bucket,), jnp.int32)
        pos = np.zeros((bucket,), np.int32)
        bt = np.full((bucket, max_blocks), self.scratch_block, np.int32)
        ctx = np.ones((bucket,), np.int32)
        return ids, pos, bt, ctx, self._rng_key, jnp.float32(1.0)

    def _scratch_lora_args(self, bucket: int, rb: int) -> tuple:
        """All-zero-page LoRA operands for warming a rank-bucketed program
        (every row the null adapter — exact-zero deltas)."""
        if rb == 0:
            return ()
        pt = np.full((bucket, rb), self.lora.pool.zero_page, np.int32)
        return (self.lora.pool.pool, jnp.asarray(pt))

    def _scratch_verify_args(self, bucket: int, k: int, max_blocks: int):
        """All-pad-row inputs for a verify-step program (spec decode
        warmup): every row the inert scratch-page fake sequence, no drafts
        proposed."""
        ids = jnp.zeros((bucket,), jnp.int32)
        draft = np.zeros((bucket, k), np.int32)
        n_draft = np.zeros((bucket,), np.int32)
        pos = np.zeros((bucket,), np.int32)
        bt = np.full((bucket, max_blocks), self.scratch_block, np.int32)
        ctx = np.ones((bucket,), np.int32)
        return ids, draft, n_draft, pos, bt, ctx

    def _warm_passes(self) -> None:
        """Run the two scheduler-pass programs once on an all-padding batch
        (one scratch-page dummy row each, so the kernels see live work): the
        shapes are fully static, so this is exactly the executable every live
        put()/mixed pass reuses."""
        from deepspeed_tpu.inference.v2.ragged.ragged_batch import RaggedBatch
        from deepspeed_tpu.inference.v2.ragged_model import (
            PAGED_PASS_KEYS, PREFILL_PASS_KEYS)
        sm = self.config.state_manager
        NC, Cs = sm.num_chunk_slots, sm.chunk_slot_size
        S, MB = sm.max_ragged_sequence_count, self.scheduler.max_blocks
        bs = self.kv.config.block_size

        def scratch_batch():
            b = RaggedBatch(num_slots=NC, slot_size=Cs, max_sequences=S,
                            max_blocks=MB)
            b.kv_dest = np.full((NC * Cs + S,), self.kv.oob_sentinel, np.int32)
            PW = NC * Cs // bs + NC
            b.page_ids = np.full((PW,), self.kv.config.num_blocks, np.int32)
            b.page_rows = np.zeros((PW,), np.int32)
            b.page_fill = np.zeros((PW,), np.int32)
            return b

        # paged/mixed pass: one decode row ticking over in the scratch page
        # — once per split rung (every rung's pass program is reachable
        # from steady state, so every one must be warm)
        b = scratch_batch()
        b.decode_block_tables[0] = self.scratch_block
        b.decode_ctx_lens[0] = 1
        b.kv_dest[NC * Cs] = self.kv.flat_write_index(self.scratch_block, 0)
        arrays = b.device_arrays()
        for pass_fn in self._pass_rungs.values():
            _, _, new_kv = pass_fn(self.weights, self.kv.kv,
                                   {k: arrays[k] for k in PAGED_PASS_KEYS})
            # direct rebind (not .update()) so JL003 sees the donated pool's
            # reference replaced before the next pass reads it
            self.kv.kv = new_kv
        if self.spec.alibi:
            return  # ALiBi engines never take the packed prefill fast path
        # prefill fast path: a one-token prompt prefilling into scratch
        b = scratch_batch()
        b.chunk_ntok[0] = 1
        b.chunk_ctx_lens[0] = 1
        b.chunk_block_tables[0] = self.scratch_block
        b.row_seg[0] = 0
        b.page_ids[0] = self.scratch_block
        b.page_fill[0] = 1
        b.kv_dest[0] = self.kv.flat_write_index(self.scratch_block, 0)
        arrays = b.device_arrays()
        logits, _, new_kv = self._ensure_prefill_pass()(
            self.weights, self.kv.kv,
            {k: arrays[k] for k in PREFILL_PASS_KEYS})
        self.kv.update(new_kv)
        jax.block_until_ready(logits)

    def _ensure_prefill_pass(self):
        """Build (once) the packed pure-prefill fast-path program — shared by
        the live pass router and warmup so both compile the identical jit."""
        if self._pass_prefill is None:
            from deepspeed_tpu.inference.v2.ragged_model import (
                build_prefill_forward)
            self._pass_prefill = jax.jit(
                build_prefill_forward(self.spec, mesh=self.topology.mesh,
                                      tp=self._eff_tp),
                donate_argnums=(1,))
            self.compiles += 1
        return self._pass_prefill

    def _run_pass(self) -> None:
        batch = self.scheduler.schedule_pass()
        if batch is None:
            return
        arrays = batch.device_arrays()
        # each jitted pass receives only the keys it reads (the two paths are
        # separate jit functions; shipping the other path's descriptors is
        # pure upload waste over a slow link)
        from deepspeed_tpu.inference.v2.ragged_model import (
            PAGED_PASS_KEYS, PREFILL_PASS_KEYS)
        # prefill-from-zero passes need no paged reads: packed-flash fast path
        # (build_prefill_forward) — measured 3-4x wave throughput on v5e-1.
        # ALiBi models take the paged chunk path (the packed flash kernel
        # has no per-head position bias; the paged kernels do)
        if batch.pure_prefill and not self.spec.alibi:
            pass_fn = self._ensure_prefill_pass()
            arrays = {k: arrays[k] for k in PREFILL_PASS_KEYS}
        else:
            # rung-keyed paged pass: the decode rows ride this step's
            # split rung (rung 1 is self._pass — byte-identical)
            pass_fn = self._pass_rungs.get(self._attn_rung(), self._pass)
            arrays = {k: arrays[k] for k in PAGED_PASS_KEYS}
        chunk_logits, decode_logits, new_kv = pass_fn(
            self.weights, self.kv.kv, arrays)
        self.kv.update(new_kv)
        finished = self.scheduler.complete_pass(batch)
        for uid in finished:
            if uid in batch.slot_uid:
                # a prompt may span several slots; its next-token logits sit
                # in the LAST slot it filled
                row = len(batch.slot_uid) - 1 - batch.slot_uid[::-1].index(uid)
                self._last_ref[uid] = (chunk_logits, row)
            else:
                self._last_ref[uid] = (decode_logits,
                                       batch.decode_uids.index(uid))

    def query(self, uid: int, max_request_tokens: int) -> Tuple[int, int]:
        return self.scheduler.query(uid, max_request_tokens)

    def can_schedule(self, uids: Sequence[int], lengths: Sequence[int]) -> bool:
        return self.scheduler.can_schedule([int(u) for u in uids], list(lengths))

    def flush(self, uids: Sequence[int]) -> None:
        for uid in uids:
            self.scheduler.flush(int(uid))
            self._last_logits.pop(int(uid), None)
            self._last_ref.pop(int(uid), None)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    # ------------------------------------------------------------------ #
    # KV page host round-trip (serving preempt-offload; serving/kv_offload)
    # ------------------------------------------------------------------ #

    def _page_programs(self):
        """(gather, scatter) jits over the whole pool with a TRACED block-id
        VECTOR, padded to a pow2 bucket: offloading a victim's whole tail is
        ONE dispatch + ONE host transfer (and one scatter back on restore),
        not one per page, and the bucket keying means arbitrary tail lengths
        reuse ~log2 executables. Pad slots point at the scratch page — reads
        of it are discarded, writes to it land on the one page no sequence
        can own. Scatter donates the pool (XLA aliases it in HBM, the same
        discipline as the pass programs). The tree_map'd bodies carry an
        int8 pool's (values, scale-tiles) tuple leaf-for-leaf — BOTH leaves
        have the page dim at axis 1, so one dispatch moves a page's bytes
        AND its scale tile together (the scale-tile fabric invariant every
        page mover keeps; docs/SERVING.md "Quantized KV")."""
        if self._page_progs is None:

            @jax.jit
            def _gather(kv, blocks):
                # page-major on the way out: host slices [i] are contiguous
                return jax.tree_util.tree_map(
                    lambda a: jnp.moveaxis(jnp.take(a, blocks, axis=1),
                                           1, 0), kv)

            @functools.partial(jax.jit, donate_argnums=(0,))
            def _scatter(kv, pages, blocks):
                return jax.tree_util.tree_map(
                    lambda a, p: a.at[:, blocks].set(jnp.moveaxis(p, 0, 1)),
                    kv, pages)

            self._page_progs = (_gather, _scatter)
        return self._page_progs

    @property
    def page_payload_spec(self) -> Tuple[Tuple[int, ...], Any]:
        """(shape, dtype) of ONE page as it travels the host fabric
        (offload buffers, export/import handoffs, failover salvage). Plain
        pools ship the page array itself ([L, 2, H_kv, bs, D], pool
        dtype); int8 pools ship ONE flat byte row per page — the int8
        values followed by the f32 scale tile (``bytes_per_block`` bytes)
        — so every host-side consumer keeps treating a page as one opaque
        copyable slice."""
        cfg = self.kv.config
        if cfg.quantized:
            return (cfg.bytes_per_block(),), np.uint8
        # jnp.dtype, not a numpy-name round trip: bf16 pools carry the
        # ml_dtypes bfloat16 numpy extension dtype
        return ((cfg.num_layers, 2, cfg.num_kv_heads, cfg.block_size,
                 cfg.head_dim), jnp.dtype(cfg.dtype))

    def _pack_pages(self, vals: np.ndarray, scales: np.ndarray) -> np.ndarray:
        """(int8 values [n, L, 2, Hkv, bs, D], f32 scale tiles
        [n, L, R8, 128]) -> packed [n, bytes_per_block] uint8 rows."""
        n = vals.shape[0]
        return np.concatenate(
            [np.ascontiguousarray(vals).reshape(n, -1).view(np.uint8),
             np.ascontiguousarray(scales).reshape(n, -1).view(np.uint8)],
            axis=1)

    def _unpack_pages(self, pages: np.ndarray):
        """Inverse of :meth:`_pack_pages`: packed uint8 rows -> (values,
        scale tiles) ready for the tuple-pool scatter."""
        cfg = self.kv.config
        n = pages.shape[0]
        L, Hkv, bs, D = (cfg.num_layers, cfg.num_kv_heads, cfg.block_size,
                         cfg.head_dim)
        vbytes = L * 2 * Hkv * bs * D
        vals = np.ascontiguousarray(pages[:, :vbytes]).view(np.int8)
        scales = np.ascontiguousarray(pages[:, vbytes:]).view(np.float32)
        from deepspeed_tpu.ops.pallas.paged_attention import (
            kv_scale_tiles_shape)
        _, r8, lanes = kv_scale_tiles_shape(1, Hkv, bs)
        return (vals.reshape(n, L, 2, Hkv, bs, D),
                scales.reshape(n, L, r8, lanes))

    def _page_bucket(self, kind: str, n: int) -> int:
        """Pad count for a page-op batch; counts the first use of each
        (op, bucket) signature as a compile (the page jits re-specialize
        per bucket, unlike the one-signature pass programs)."""
        b = next_pow2(n)
        key = (kind, b)
        if key not in self._page_buckets:
            self._page_buckets.add(key)
            self.compiles += 1
        return b

    @property
    def page_buckets(self) -> List[int]:
        """The page-op bucket grid warmup pre-compiles: pow2 up to a whole
        sequence's block-table length (the largest possible private tail)."""
        top = next_pow2(self.scheduler.max_blocks)
        return [1 << i for i in range(top.bit_length())]

    def fetch_pages(self, blocks: Sequence[int]) -> np.ndarray:
        """KV pages fetched to host in one bucketed gather — the offload
        half of the preempt-offload round trip (serving/kv_offload.py) and
        the export half of the page fabric. Plain pools return
        ``[n, L, 2, H_kv, block_size, D]``; int8 pools return packed
        ``[n, bytes_per_block]`` uint8 rows (values + scale tile per page —
        :attr:`page_payload_spec`). Rare path (runs only when admission
        preempts a victim or a handoff exports), drained through the
        policed ``fetch_to_host`` like every other v2 fetch."""
        ids = [int(b) for b in blocks]
        _maybe_fail("serve.kv_fetch")      # chaos site: page-fabric gather
        gather, _ = self._page_programs()
        bucket = self._page_bucket("gather", len(ids))
        idx = np.full((bucket,), self.scratch_block, np.int32)
        idx[:len(ids)] = ids
        res = gather(self.kv.kv, jnp.asarray(idx))
        if self.kv.config.quantized:
            # slice the bucket's scratch pad rows off BEFORE packing —
            # _pack_pages concatenates, and a pow2 bucket can be ~2x n
            vals, scales = res
            return self._pack_pages(fetch_to_host(vals)[:len(ids)],
                                    fetch_to_host(scales)[:len(ids)])
        return fetch_to_host(res)[:len(ids)]

    def put_pages(self, pages: np.ndarray, blocks: Sequence[int]) -> None:
        """Scatter host pages ``[n, ...]`` back into pool slots ``blocks``
        (one bucketed dispatch) — the restore half. Byte-exact with
        ``fetch_pages`` (same dtype both ways; pinned by
        tests/unit/test_serving_frontend.py). Pad slots write zeros into the
        inert scratch page."""
        ids = [int(b) for b in blocks]
        if not ids:
            return
        _maybe_fail("serve.kv_put")        # chaos site: page-fabric scatter
        _, scatter = self._page_programs()
        bucket = self._page_bucket("scatter", len(ids))
        idx = np.full((bucket,), self.scratch_block, np.int32)
        idx[:len(ids)] = ids
        if bucket != len(ids):
            pages = np.concatenate(
                [pages, np.zeros((bucket - len(ids),) + pages.shape[1:],
                                 pages.dtype)])
        if self.kv.config.quantized:
            vals, scales = self._unpack_pages(np.asarray(pages, np.uint8))
            payload = (jnp.asarray(vals), jnp.asarray(scales))
        else:
            payload = jnp.asarray(pages, self.kv.kv.dtype)
        # direct rebind (not kv.update) so JL003 sees the donated pool's
        # reference replaced before the next pass reads it
        self.kv.kv = scatter(self.kv.kv, payload, jnp.asarray(idx))

    def export_kv(self, uid: int) -> Tuple[np.ndarray, np.ndarray]:
        """``(pages, logits)``: the whole logical KV of a fully-prefilled
        sequence fetched to host in one bucketed gather, plus its last
        logits row — then the sequence is flushed here. The export half of a
        cross-engine prefill->decode handoff (serving/cluster.py): the pair
        is exactly what preempt-offload parks per victim, so ``import_kv``
        on ANOTHER engine restores it the same way preemption restore does
        (pages scattered into fresh pool ids, ``_last_logits`` re-seeded for
        a byte-identical bootstrap sample). With the prefix cache on, the
        flush returns this sequence's pages to the LOCAL radix tree — the
        prefill replica stays warm for the next matching prompt."""
        uid = int(uid)
        seq = self.scheduler.seqs.get(uid)
        if seq is None:
            raise KeyError(f"sequence {uid} is not tracked")
        if len(seq.pending):
            raise RuntimeError(f"sequence {uid} still has pending prefill "
                               "tokens — export_kv needs a drained sequence")
        self._materialize([uid])
        logits = self._last_logits.pop(uid)
        pages = self.fetch_pages(list(seq.blocks))
        self.flush([uid])
        return pages, logits

    def import_kv(self, uid: int, tokens: Sequence[int], pages: np.ndarray,
                  logits: np.ndarray) -> List[int]:
        """Adopt a sequence whose KV ``pages`` were computed on ANOTHER
        engine (independent pool, different block ids): allocate fresh pages
        (``scheduler.adopt_sequence``), scatter the content in with the
        bucketed ``put_pages`` (byte-exact — the fabric contract
        tests/unit/test_serving_router.py pins below the router), and
        re-seed the bootstrap logits row exactly like preemption restore.
        The sequence is then in steady decode state: ``decode_pipeline`` can
        admit it directly. Returns the allocated block ids."""
        uid = int(uid)
        page_shape, page_dtype = self.page_payload_spec
        pages = np.asarray(pages, page_dtype)
        if tuple(pages.shape[1:]) != page_shape:
            raise ValueError(
                f"handoff page shape {tuple(pages.shape[1:])} does not match "
                f"this engine's KV page layout {page_shape} — cross-engine "
                "handoff needs an identical model + block_size")
        ids = self.scheduler.adopt_sequence(uid, tokens, len(pages))
        if ids:
            self.put_pages(pages, ids)
        self._last_logits[uid] = logits
        return ids

    def fetch_page(self, block: int) -> np.ndarray:
        """One KV page (``page_payload_spec``-shaped) to host."""
        return self.fetch_pages([block])[0]

    def put_page(self, page: np.ndarray, block: int) -> None:
        """Scatter one host page back into pool slot ``block``."""
        self.put_pages(page[None], [block])

    def serving_frontend(self, config=None, uid_base: int = 1 << 20):
        """The persistent SLO-aware serving frontend over this engine
        (``serving/frontend.py``): asyncio-facing ``submit() -> token
        stream``, multi-tenant admission with priority classes, and
        KV offload-preemption. ``config`` overrides ``self.config.serving``;
        ``uid_base`` keeps a cluster's frontends in disjoint uid spaces
        (``serving/cluster.py``)."""
        from deepspeed_tpu.inference.v2.serving import ServingFrontend
        return ServingFrontend(self, config=config, uid_base=uid_base)

    def weight_bridge(self, train_engine, **kwargs):
        """A :class:`~deepspeed_tpu.runtime.colocated.WeightBridge` from a
        colocated training engine into this engine's weight layout — one
        jitted device-resident reshard per policy update, swapped in via
        ``swap_weights`` with zero recompiles (docs/SERVING.md "Colocated
        rollout")."""
        from deepspeed_tpu.runtime.colocated import WeightBridge
        return WeightBridge(train_engine, self, **kwargs)

    # ------------------------------------------------------------------ #
    # prefix-cache support
    # ------------------------------------------------------------------ #

    def write_monitor_events(self, monitor, step: int = 0) -> None:
        """Emit the serving counters through a ``monitor/`` backend
        (``MonitorMaster.write_events`` shape): prefix-cache stats when the
        cache is on, and the decode pipeline's per-step timing/transfer
        breakdown (dispatch / host-build / fetch-drain / bubble, fetch bytes)
        once any ``DecodePipeline`` has run."""
        if self.prefix_cache is not None:
            monitor.write_events(self.prefix_cache.stats.events(step))
        if self.pipeline_stats.steps:
            monitor.write_events(self.pipeline_stats.events(step))
        if self.spec_stats.steps:
            monitor.write_events(self.spec_stats.events(step))
        if self.attn_stats.selects:
            monitor.write_events(self.attn_stats.events(step))
        if self.lora is not None and self.lora.stats.adapters:
            monitor.write_events(self.lora.stats.events(step))

    # ------------------------------------------------------------------ #
    # continuous-batching generation loop (parity role: MII serving loop)
    # ------------------------------------------------------------------ #

    def _sample(self, logits: np.ndarray, do_sample: bool, temperature: float,
                top_k: int) -> int:
        if not do_sample:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(temperature, 1e-6)
        if top_k > 0:
            kth = np.sort(z)[-top_k]
            z = np.where(z < kth, -np.inf, z)
        z = z - z.max()
        p = np.exp(z) / np.exp(z).sum()
        return int(self._rng.choice(len(p), p=p))

    def generate(self,
                 prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32,
                 do_sample: bool = False,
                 temperature: float = 1.0,
                 top_k: int = 0,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Generate continuations for a batch of prompts with continuous
        batching: all sequences advance together; finished ones are flushed
        and their blocks recycled. Returns full token lists (prompt +
        generation).

        Steady-state decode runs through ``decode_pipeline`` — the SAME
        gated hot path the serving frontend drives (fused on-device
        sampling, bucketed descriptors, one-step-late drain; with
        ``spec_decode.enabled`` and greedy requests, the draft-and-verify
        ``SpecDecodePipeline``) — in slice-sized runs, retiring EOS'd (or
        budget-complete) sequences at each drained step. Greedy streams are
        byte-identical to the old per-token ``sample_next``/``put`` loop,
        spec on or off (pinned by tests/unit/test_decode_pipeline.py and
        test_spec_decode.py); sampled streams are valid draws but consume
        RNG per fused step, so they differ from the old loop's draws (the
        documented ``decode_steps`` trade)."""
        # fresh uid namespace: never collide with caller-owned put() sequences
        uids: List[int] = []
        nxt = 0
        while len(uids) < len(prompts):
            if nxt not in self.scheduler.seqs:
                uids.append(nxt)
            nxt += 1
        idx_of = {u: i for i, u in enumerate(uids)}
        outs: List[List[int]] = [list(map(int, p)) for p in prompts]
        if not self.can_schedule(uids, [len(p) for p in prompts]):
            raise RuntimeError("cannot schedule: insufficient KV blocks or "
                               "sequence slots")
        self._put_nofetch(uids, [np.asarray(p, np.int32) for p in prompts])
        pipe = self.decode_pipeline(uids, do_sample=do_sample,
                                    temperature=temperature, top_k=top_k)
        is_spec = getattr(pipe, "spec", False)
        live = set(uids)
        budget = {u: max_new_tokens for u in uids}

        def on_tokens(j, run_uids, row):
            stop = []
            for i, u in enumerate(run_uids):
                if u not in live:
                    continue        # retired earlier this run: padding noise
                # spec steps emit a variable-length token batch per row;
                # plain steps one token. Tokens past the budget (a spec
                # step's in-step overshoot) are discarded — their KV is
                # stale past the flush below, never read.
                for t in (row[i] if is_spec else row[i:i + 1]):
                    t = int(t)
                    outs[idx_of[u]].append(t)
                    budget[u] -= 1
                    done = budget[u] <= 0 or (eos_token_id is not None
                                              and t == eos_token_id)
                    if done:
                        live.discard(u)
                        stop.append(u)
                        break
            return stop

        # slice-sized runs bound the post-retirement overshoot (the device
        # finishes each in-flight burst; see DecodePipeline.run) to one
        # slice; a spec step can emit up to k+1 tokens, so its slice is
        # correspondingly shorter
        CHUNK = 32
        K1 = self.config.spec_decode.k + 1
        steps = max(1, CHUNK // K1) if is_spec else CHUNK
        if max_new_tokens <= 0:
            self.flush(pipe.uids)
            return outs
        max_ctx = self.config.state_manager.max_context
        while pipe.uids:
            if is_spec:
                # clamp the verify-run length to the remaining budget AND
                # the rows' max_context headroom (each verify step reserves
                # k+1 tokens up front); when even ONE verify step no longer
                # fits — speculation intrinsically needs k+1 write slots —
                # degrade the tail to the plain pipeline (bit-identical to
                # a verify step's row 0) instead of crashing the stream
                rem = max(budget[u] for u in pipe.uids)
                cap = min((max_ctx - self.scheduler.seqs[u].seen_tokens - 1)
                          // K1 for u in pipe.uids)
                n = min(steps, -(-rem // K1), cap)
                if n < 1:
                    uids_left = list(pipe.uids)
                    pipe.retire(uids_left)
                    from deepspeed_tpu.inference.v2.pipeline import (
                        DecodePipeline)
                    pipe = DecodePipeline(self, uids_left)
                    is_spec = False
                    continue
            else:
                n = min(steps, max(budget[u] for u in pipe.uids))
            before = set(pipe.uids)
            pipe.run(n, on_tokens=on_tokens)
            for u in before - set(pipe.uids):
                self.flush([u])     # retired mid-run: recycle KV blocks now
        self.flush(pipe.uids)
        return outs


def _guess_family(model) -> str:
    fam = getattr(getattr(model, "config", None), "family", None)
    if fam:
        return fam
    name = type(model).__name__.lower()
    for fam in ("mixtral", "mistral", "llama", "gpt2", "opt", "falcon", "phi"):
        if fam in name:
            return fam
    raise ValueError(f"cannot infer model family from {type(model).__name__}; "
                     f"pass family=")
