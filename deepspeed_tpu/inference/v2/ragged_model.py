"""Ragged model implementations for the v2 engine.

Parity: reference ``inference/v2/model_implementations/`` (llama_v2, mistral,
mixtral, opt, falcon, phi — each a hand-assembled stack of DSModule kernels over a
ragged batch) and the module registry in ``inference/v2/modules``. TPU-native
re-design: ONE generic ragged forward — a ``lax.scan`` over layer-stacked weights —
specialised per family by a :class:`RaggedModelSpec` (norm type, activation,
rope/learned positions, parallel residual, MoE) and a weight *adapter* that
re-keys the zoo model's param tree into the canonical stacked layout.

Pass structure (see ``ragged/ragged_batch.py``): tokens = [NC prompt-chunk
slots | decode rows]. Each layer writes the pass's K/V into the paged cache
(one flat scatter), then attends:

  - chunk slots -> ``AttentionKernelSpec.chunk`` (flash over pages for all
    slots in one kernel, causal by absolute position)
  - decode rows -> ``AttentionKernelSpec.decode`` (one token per sequence;
    the fused multistep loop uses ``.decode_step``/``.sidebuf``)

Every builder routes attention through ONE ``AttentionKernelSpec``
(``inference/v2/attention.py``): kernel variants key on the pool dtype at
the call (``kv_scales=None`` = bf16/f32 pages), window/alibi/TP bind once.

MoE layers use sort-based grouped GEMM (``jax.lax.ragged_dot`` when available) —
the TPU analog of the reference's CUTLASS ``moe_gemm`` + moe_scatter/gather
(``inference/v2/kernels/cutlass_ops``, ``ragged_ops/moe_{scatter,gather}``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.attention import AttentionKernelSpec
from deepspeed_tpu.ops.pallas.paged_attention import (
    _scale_tile_rows, kv_quantize_rows, kv_write_dequant)


def _kv_unpack(kp):
    """KV pool argument -> (pages, scales-or-None). The combined pool
    [L, NB, 2, Hkv, bs, D] holds K (index 0) and V (index 1) in ONE page —
    the decode kernel is per-DMA-copy bound, so one value copy per page
    (see ops/pallas/paged_attention.py module docstring). int8 pools travel
    as a (values int8, per-token-head f32 scale TILES [L, NB, R8, 128]) tuple
    through every jit boundary so the plumbing is dtype-agnostic."""
    if isinstance(kp, tuple):
        return kp
    return kp, None


@dataclass
class RaggedModelSpec:
    family: str
    num_layers: int
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    vocab_size: int
    norm: str = "rms"              # "rms" | "ln"
    # gated: "swiglu" (silu gate) | "geglu" (tanh-gelu gate, Gemma)
    # plain: "gelu" (tanh) | "gelu_exact" (erf) | "silu" | "relu"
    activation: str = "swiglu"
    rope_theta: Optional[float] = 10000.0   # None -> no rotary
    rotary_dim: Optional[int] = None        # partial rotary (phi); None = full head
    learned_pos: bool = False      # gpt2/opt learned position embeddings
    pos_offset: int = 0            # opt: positions are offset by 2 in the table
    parallel_block: bool = False   # falcon/phi: attn + mlp both from the same norm
    parallel_dual_norm: bool = False  # gpt_neox: parallel, but MLP from ln2(x)
    tied_lm_head: bool = False     # gpt2: logits = x @ embed.T
    head_bias: bool = False        # phi/gpt-j: bias added to the logits
    embed_scale_by_sqrt_dim: bool = False  # gemma: x *= sqrt(hidden) after embed
    norm_plus_one: bool = False    # gemma: RMSNorm scales by (1 + weight)
    eps: float = 1e-5
    moe: Optional[Dict[str, int]] = None    # {"num_experts": E, "top_k": k}
    # mistral/qwen2 sliding-window span (tokens); None = full attention.
    # Reference parity: inference/v2/model_implementations/mistral.
    window: Optional[int] = None
    # BLOOM lineage: per-head linear position bias applied inside the paged
    # kernels (reference csrc/transformer/inference/csrc/softmax.cu) and a
    # LayerNorm right after the embedding
    alibi: bool = False
    embed_norm: bool = False
    dtype: Any = jnp.bfloat16


# --------------------------------------------------------------------------- #
# adapters: zoo param tree -> canonical stacked weights
# --------------------------------------------------------------------------- #

def _stack(trees: List[Any]) -> Any:
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def adapt_llama(params: Dict, config,
                max_context: Optional[int] = None) -> Tuple[RaggedModelSpec, Dict]:
    """models/llama.py param tree (LlamaForCausalLM / MixtralForCausalLM).

    Parity anchors: reference ``inference/v2/model_implementations/llama_v2`` /
    ``mistral`` / ``mixtral``."""
    moe = None
    if hasattr(config, "num_local_experts"):
        moe = {"num_experts": config.num_local_experts,
               "top_k": config.num_experts_per_tok}
    # Gemma lineage rides the llama adapter: its structural differences are
    # config flags on LlamaConfig (module_inject/containers.py GemmaPolicy)
    mlp_act = getattr(config, "mlp_act", "silu")
    if mlp_act not in ("silu", "gelu"):
        raise ValueError(f"llama-lineage mlp_act '{mlp_act}' has no ragged "
                         "gated-MLP mapping (expected 'silu' or 'gelu')")
    window = getattr(config, "sliding_window", None)
    if window is not None and (max_context is not None
                               and max_context <= window):
        # no position can ever see past the window: full attention is
        # exactly equivalent, so skip the window masks (and their small
        # kernel cost) entirely
        window = None
    spec = RaggedModelSpec(
        family="mixtral" if moe else "llama",
        num_layers=config.num_hidden_layers,
        hidden_size=config.hidden_size,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        head_dim=config.head_dim,
        vocab_size=config.vocab_size,
        norm="rms",
        activation="swiglu" if mlp_act == "silu" else "geglu",
        rope_theta=config.rope_theta,
        embed_scale_by_sqrt_dim=getattr(config, "embed_scale_by_sqrt_dim", False),
        norm_plus_one=getattr(config, "norm_plus_one", False),
        eps=config.rms_norm_eps, moe=moe, window=window, dtype=config.dtype)

    layers = []
    for i in range(config.num_hidden_layers):
        lp = params[f"layers_{i}"]
        attn = lp["self_attn"]
        layer = {
            "ln1": {"scale": lp["input_layernorm"]["weight"]},
            "ln2": {"scale": lp["post_attention_layernorm"]["weight"]},
            "wq": attn["q_proj"]["kernel"],
            "wk": attn["k_proj"]["kernel"],
            "wv": attn["v_proj"]["kernel"],
            "wo": attn["o_proj"]["kernel"],
        }
        if "bias" in attn["q_proj"]:   # Qwen2 lineage: biased q/k/v
            layer["bq"] = attn["q_proj"]["bias"]
            layer["bk"] = attn["k_proj"]["bias"]
            layer["bv"] = attn["v_proj"]["bias"]
        if moe:
            mb = lp["block_sparse_moe"]
            layer["moe"] = {
                "router": mb["gate"]["kernel"],
                "w_gate": mb["w_gate"], "w_up": mb["w_up"], "w_down": mb["w_down"],
            }
        else:
            layer["mlp"] = {
                "w_gate": lp["mlp"]["gate_proj"]["kernel"],
                "w_up": lp["mlp"]["up_proj"]["kernel"],
                "w_down": lp["mlp"]["down_proj"]["kernel"],
            }
        layers.append(layer)

    weights = {
        "embed": params["embed_tokens"]["embedding"],
        "layers": _stack(layers),
        "final_norm": {"scale": params["norm"]["weight"]},
        "lm_head": params["lm_head"]["kernel"],
    }
    return spec, weights


def adapt_gpt2(params: Dict, config,
               max_context: Optional[int] = None) -> Tuple[RaggedModelSpec, Dict]:
    """models/gpt2.py param tree (GPT2LMHead): fused c_attn qkv, tied head."""
    spec = RaggedModelSpec(
        family="gpt2",
        num_layers=config.n_layer,
        hidden_size=config.n_embd,
        num_heads=config.n_head,
        num_kv_heads=config.n_head,
        head_dim=config.n_embd // config.n_head,
        vocab_size=config.vocab_size,
        norm="ln", activation="gelu", rope_theta=None, learned_pos=True,
        tied_lm_head=True, eps=1e-5, dtype=config.dtype)

    E = config.n_embd
    layers = []
    for i in range(config.n_layer):
        lp = params[f"h_{i}"]
        wqkv = lp["attn"]["c_attn"]["kernel"]     # [E, 3E]
        bqkv = lp["attn"]["c_attn"]["bias"]
        layers.append({
            "ln1": {"scale": lp["ln_1"]["scale"], "bias": lp["ln_1"]["bias"]},
            "ln2": {"scale": lp["ln_2"]["scale"], "bias": lp["ln_2"]["bias"]},
            "wq": wqkv[:, :E], "wk": wqkv[:, E:2 * E], "wv": wqkv[:, 2 * E:],
            "bq": bqkv[:E], "bk": bqkv[E:2 * E], "bv": bqkv[2 * E:],
            "wo": lp["attn"]["c_proj"]["kernel"],
            "bo": lp["attn"]["c_proj"]["bias"],
            "mlp": {
                "w_up": lp["mlp"]["c_fc"]["kernel"],
                "b_up": lp["mlp"]["c_fc"]["bias"],
                "w_down": lp["mlp"]["c_proj"]["kernel"],
                "b_down": lp["mlp"]["c_proj"]["bias"],
            },
        })

    weights = {
        "embed": params["wte"]["embedding"],
        "pos_embed": params["wpe"]["embedding"],
        "layers": _stack(layers),
        "final_norm": {"scale": params["ln_f"]["scale"],
                       "bias": params["ln_f"]["bias"]},
    }
    return spec, weights


def adapt_decoder(params: Dict, config,
                  max_context: Optional[int] = None) -> Tuple[RaggedModelSpec, Dict]:
    """models/decoder.py (DecoderLM — opt/falcon/phi/gpt_neox/gptj/
    gpt_bigcode): canonical names, so adaptation is re-rooting + stacking.
    Parity anchors: reference ``inference/v2/model_implementations/
    {opt,falcon,phi}``. Guards on the FEATURES the ragged path can't carry
    (not family names), so a config with e.g. alibi under any family is
    rejected instead of silently served wrong."""
    unsupported = []
    if getattr(config, "local_window", None) is not None:
        unsupported.append("local_window")
    if any(k == "local" for k in getattr(config, "attention_layers", None) or ()):
        unsupported.append("attention_layers with 'local' entries")
    if getattr(config, "attn_scale", None) is not None:
        unsupported.append("attn_scale")
    if unsupported:
        raise ValueError(
            f"config features {unsupported} are not supported by the ragged "
            "(paged) attention path — serve through deepspeed_tpu."
            "init_inference (v1 dense engine) instead")
    spec = RaggedModelSpec(
        family=config.family,
        num_layers=config.num_hidden_layers,
        hidden_size=config.hidden_size,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.kv_heads,
        head_dim=config.head_dim,
        vocab_size=config.vocab_size,
        norm=config.norm, activation=config.activation,
        rope_theta=config.rope_theta, rotary_dim=config.rotary_dim,
        learned_pos=config.learned_pos, pos_offset=config.pos_offset,
        parallel_block=config.parallel_block,
        parallel_dual_norm=config.parallel_dual_norm,
        tied_lm_head=config.tied_lm_head, head_bias=config.head_bias,
        alibi=getattr(config, "alibi", False),
        embed_norm=getattr(config, "embed_norm", False),
        eps=config.eps, dtype=config.dtype)

    layers = [params[f"layers_{i}"] for i in range(config.num_hidden_layers)]
    weights = {
        "embed": params["embed"]["embedding"],
        "layers": _stack(layers),
        "final_norm": params["final_norm"],
    }
    if spec.embed_norm:
        weights["embed_norm"] = params["embed_norm"]
    if config.learned_pos:
        weights["pos_embed"] = params["pos_embed"]["embedding"]
    if not config.tied_lm_head:
        weights["lm_head"] = params["lm_head"]
    if config.head_bias:
        weights["lm_head_bias"] = params["lm_head_bias"]
    return spec, weights


ADAPTERS: Dict[str, Callable] = {
    # llama lineage (qwen2 = biased qkv; gemma = structural flags — both are
    # LlamaConfig features the adapter reads)
    "llama": adapt_llama,
    "mistral": adapt_llama,
    "mixtral": adapt_llama,
    "qwen2": adapt_llama,
    "gemma": adapt_llama,
    "gpt2": adapt_gpt2,
    # generic-decoder lineage (canonical param names; re-root + stack)
    "opt": adapt_decoder,
    "falcon": adapt_decoder,
    "phi": adapt_decoder,
    "gpt_neox": adapt_decoder,
    "gptj": adapt_decoder,
    "gpt_bigcode": adapt_decoder,
    "bloom": adapt_decoder,   # ALiBi carried by the paged kernels
}

#: families whose attention needs a bias the ragged kernels don't carry —
#: serve these through the v1 dense engine instead
_UNSUPPORTED = {
    # gpt_neo alternates GLOBAL and LOCAL attention layers; the ragged spec
    # carries one window for all layers, so it stays on the v1 dense engine
    # (bloom's ALiBi is supported — the kernels bias scores per head)
    "gpt_neo": "per-layer alternating local-window attention",
}


def adapt_model(family: str, params: Dict, config,
                max_context: Optional[int] = None) -> Tuple[RaggedModelSpec, Dict]:
    if family in _UNSUPPORTED:
        raise ValueError(
            f"family '{family}' uses {_UNSUPPORTED[family]}, which the ragged "
            "(paged) attention path does not support — serve it through "
            "deepspeed_tpu.init_inference (v1 dense engine) instead")
    if family not in ADAPTERS:
        raise ValueError(f"no ragged adapter for family '{family}' "
                         f"(have {sorted(ADAPTERS)})")
    return ADAPTERS[family](params, config, max_context=max_context)


# --------------------------------------------------------------------------- #
# generic ragged forward
# --------------------------------------------------------------------------- #

def _norm(x, w, kind: str, eps: float, dtype, plus_one: bool = False):
    xf = x.astype(jnp.float32)
    scale = (1.0 + w["scale"]) if plus_one else w["scale"]
    if kind == "rms":
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + eps) * scale
    else:
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + eps) * scale + w["bias"]
    return y.astype(dtype)


_PLAIN_ACTS = {
    "gelu": jax.nn.gelu,                                      # tanh approx
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),  # erf-exact
    "silu": jax.nn.silu,
    "relu": jax.nn.relu,
}


def _plain_act(name: str) -> Callable:
    """Non-gated MLP activation. Raising on unknown names (rather than a relu
    fallback) is what keeps a new zoo activation from silently serving garbage
    through the v2 path."""
    try:
        return _PLAIN_ACTS[name]
    except KeyError:
        raise ValueError(
            f"unknown MLP activation '{name}' for the ragged path "
            f"(gated: swiglu/geglu; plain: {sorted(_PLAIN_ACTS)})") from None


def _rope_flat(x: jax.Array, positions: jax.Array, theta: float,
               rotary_dim: Optional[int]) -> jax.Array:
    """Rotary embedding on [T, H, D] with per-token positions [T] — delegates to
    the zoo's single implementation (models/decoder._partial_rope) via a unit
    batch dim so v1 dense and v2 ragged paths share the exact rotation math."""
    from deepspeed_tpu.models.decoder import _partial_rope
    return _partial_rope(x[None], positions[None], theta, rotary_dim)[0]


def _moe_ffn(x: jax.Array, w: Dict, top_k: int, dtype) -> jax.Array:
    """Sort-based token dispatch + grouped GEMM (parity: reference moe_scatter ->
    CUTLASS moe_gemm -> moe_gather, inference/v2/kernels). x: [T, hid]."""
    T, hid = x.shape
    E = w["router"].shape[-1]
    logits = x.astype(jnp.float32) @ w["router"].astype(jnp.float32)   # [T, E]
    gates, ids = jax.lax.top_k(logits, top_k)                          # [T, K]
    gates = jax.nn.softmax(gates, axis=-1)

    tok_idx = jnp.repeat(jnp.arange(T), top_k)                         # [T*K]
    expert_ids = ids.reshape(-1)
    order = jnp.argsort(expert_ids)
    src = tok_idx[order]
    xs = x[src]                                                        # [T*K, hid]
    group_sizes = jnp.bincount(expert_ids, length=E).astype(jnp.int32)

    row_e = expert_ids[order]

    def gg(lhs, rhs):
        if isinstance(rhs, dict) and "w8" in rhs:
            # int8 expert stacks (ADVICE r4: the experts are the dominant
            # streamed bytes of an MoE serving step — leaving them bf16 made
            # quantization.weight_bits a silent no-op on mixtral). The
            # per-(expert, output-column) scale applies per ROW of the
            # grouped output, indexed by the row's expert.
            raw = jax.lax.ragged_dot(lhs, rhs["w8"].astype(lhs.dtype),
                                     group_sizes,
                                     preferred_element_type=jnp.float32)
            return (raw * rhs["scale"][row_e, 0, :]).astype(lhs.dtype)
        return jax.lax.ragged_dot(lhs, rhs.astype(lhs.dtype), group_sizes)

    if "w_gate" in w:
        h = jax.nn.silu(gg(xs, w["w_gate"])) * gg(xs, w["w_up"])
    else:
        h = jax.nn.gelu(gg(xs, w["w_up"]))
    ys = gg(h, w["w_down"])                                            # [T*K, hid]
    scale = gates.reshape(-1)[order].astype(ys.dtype)
    # scatter-free combine: invert the sort permutation and sum the K
    # choices (parallel/moe.py dropless_moe — TPU scatter-add serializes)
    inv = jnp.argsort(order)
    out = (ys * scale[:, None])[inv].reshape(T, top_k, hid).sum(axis=1)
    return out.astype(dtype)



def _mm(x, w):
    """``x @ w`` where ``w`` is a plain array OR a weight-only-int8 dict
    ``{"w8" [K, N] int8, "scale" [1, N] f32}``.

    TPU-native mixed GEMM (parity role: the reference's fp16 x int8 CUTLASS
    mixed_gemm, ``inference/v2/kernels/cutlass_ops/mixed_gemm``): at decode
    shapes the GEMM is weight-READ bound, so int8 storage halves the HBM
    stream. XLA fuses the int8->bf16 convert into the dot's tile pipeline
    (measured v5e-1, M=32: int8 weight stream runs at ~700 GB/s wire rate =
    ~1.4 TB/s bf16-equivalent vs ~750 GB/s for bf16 weights — a true ~1.9x).
    int8 values up to +-127 are exact in bf16; accumulation is fp32 via
    preferred_element_type; the per-output-column scale is applied to the
    fp32 accumulator (valid: scale is constant along K)."""
    if isinstance(w, dict) and "w8" in w:
        o = jax.lax.dot_general(x, w["w8"].astype(x.dtype),
                                (((x.ndim - 1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return (o * w["scale"]).astype(x.dtype)
    if isinstance(w, dict) and "w4" in w:
        # packed int4 (two values per byte along K — the reference's
        # quantize_intX.cu storage win, /4 vs bf16 at rest): unpack with
        # sign-extending shifts, then the same mixed dot as int8
        from deepspeed_tpu.ops.quantizer import unpack_int4
        wk = unpack_int4(w["w4"], axis=-2)
        o = jax.lax.dot_general(x, wk.astype(x.dtype),
                                (((x.ndim - 1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return (o * w["scale"]).astype(x.dtype)
    return x @ w


# --------------------------------------------------------------------------- #
# multi-tenant LoRA: paged adapter weights -> per-row grouped delta
# (inference/v2/lora/; docs/SERVING.md "Multi-tenant LoRA")
# --------------------------------------------------------------------------- #

#: projections a LoRA adapter may target (attention only — the S-LoRA /
#: Punica serving pattern; MLP adapters are out of scope for the paged pool)
LORA_TARGETS = ("q", "k", "v", "o")


def lora_target_dims(spec: "RaggedModelSpec",
                     target: str) -> Tuple[int, int]:
    """``(d_in, d_out)`` of one LoRA-targeted base projection."""
    H, Hkv, D = spec.num_heads, spec.num_kv_heads, spec.head_dim
    hid = spec.hidden_size
    dims = {"q": (hid, H * D), "k": (hid, Hkv * D), "v": (hid, Hkv * D),
            "o": (H * D, hid)}
    if target not in dims:
        raise ValueError(f"unknown LoRA target {target!r} "
                         f"(supported: {LORA_TARGETS})")
    return dims[target]


def lora_page_layout(spec: "RaggedModelSpec",
                     targets: Tuple[str, ...]) -> Tuple[int, int, int]:
    """``(elements, in_max, out_max)`` of ONE adapter-weight page.

    A page is one RANK SLICE of a whole adapter — for every layer and every
    targeted projection, column ``j`` of that projection's A matrix (padded
    to ``in_max``) followed by row ``j`` of its B matrix (padded to
    ``out_max``, alpha/rank pre-folded in at registration) — flattened to
    ``[L, nproj, in_max + out_max]`` in ``spec.dtype``. Rank-r adapters own
    r pages; the pool's zero page pads ranks below the dispatch bucket AND
    backs the null adapter, so pad reads contribute exact zeros. Same design
    as a KV page: fixed size from the model spec alone, so the pool is one
    dense device array and the per-row gather is a single take."""
    dims = [lora_target_dims(spec, t) for t in targets]
    in_max = max(d[0] for d in dims)
    out_max = max(d[1] for d in dims)
    return (spec.num_layers * len(targets) * (in_max + out_max),
            in_max, out_max)


def lora_layer_operands(spec: "RaggedModelSpec", targets: Tuple[str, ...],
                        lora_pool, adapter_pt, repeat: int = 1):
    """Per-row adapter pages gathered on device, shaped for the layer scan.

    ``lora_pool`` ``[P + 2, elements]``, ``adapter_pt`` ``[S, RB]`` page
    ids (RB = the engine's pow2 rank bucket; rank padding and pad rows
    point at the pool's zero page) -> ``[L, T, RB, nproj, in_max+out_max]``
    riding the layer scan as xs. ``repeat`` expands sequence rows to token
    rows for the verify step's K+1-rows-per-sequence batch."""
    pages = lora_pool[adapter_pt]                       # [S, RB, E]
    if repeat > 1:
        pages = jnp.repeat(pages, repeat, axis=0)
    T, RB = pages.shape[0], pages.shape[1]
    _, in_max, out_max = lora_page_layout(spec, targets)
    sl = pages.reshape(T, RB, spec.num_layers, len(targets),
                       in_max + out_max)
    return jnp.moveaxis(sl, 2, 0)


def _lora_split(spec: "RaggedModelSpec", targets: Tuple[str, ...], lora_l):
    """One layer's scanned slice ``[T, RB, nproj, io]`` -> ``{target:
    (A [T, RB, d_in], B [T, RB, d_out])}`` for :func:`_lora_mm`."""
    _, in_max, out_max = lora_page_layout(spec, targets)
    out = {}
    for p, t in enumerate(targets):
        din, dout = lora_target_dims(spec, t)
        out[t] = (lora_l[:, :, p, :din],
                  lora_l[:, :, p, in_max:in_max + dout])
    return out


def _lora_mm(x, w, lora, name: str):
    """``_mm(x, w)`` plus the row's grouped LoRA delta ``(x @ A) @ B``.

    The grouped matmul of the multi-tenant decode batch: every token row
    carries ITS OWN adapter's A/B rank slices (gathered by
    :func:`lora_layer_operands`), so one einsum pair serves a batch that
    mixes tenants — no per-adapter dispatch, no batch splitting. Rows bound
    to the zero page (no adapter, rank padding, scratch pad rows) contribute
    exact zeros, which keeps pad rows inert and the null-adapter stream
    byte-identical across batch compositions. fp32 contraction: the rank
    dim is tiny, and it makes the delta independent of the batch's bucket
    shape (the byte-equality gate's requirement)."""
    y = _mm(x, w)
    if lora is None or name not in lora:
        return y
    a, b = lora[name]
    c = jnp.einsum("ti,tri->tr", x.astype(jnp.float32),
                   a.astype(jnp.float32))
    d = jnp.einsum("tr,tro->to", c, b.astype(jnp.float32))
    return y + d.astype(y.dtype)


_QUANT_KEYS = ("wq", "wk", "wv", "wo")
_QUANT_MLP_KEYS = ("w_gate", "w_up", "w_down")


def quantize_weights_int4(weights: Dict) -> Dict:
    """Packed-int4 weight-only serving store (reference parity:
    ``csrc/quantization/quantize_intX.cu`` packed 4-bit). Same tree walk as
    :func:`quantize_weights_int8`, but values quantize to [-7, 7] with
    per-output-column scales and STORE two-per-byte along K
    (``ops/quantizer.pack_int4``) — at-rest HBM is K*N/2 bytes, a measured
    4x under bf16. The matmul unpacks with sign-extending shifts (``_mm``).
    """
    from deepspeed_tpu.ops.quantizer import pack_int4

    def q4(w):
        absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2,
                         keepdims=True)
        scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
        qv = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                      -7, 7).astype(jnp.int8)
        return {"w4": pack_int4(qv, axis=-2),
                "scale": scale.astype(jnp.float32)}

    return _quantize_weight_tree(weights, q4)


def quantize_weights_int8(weights: Dict) -> Dict:
    """Weight-only int8 for the serving weight tree (in place, returns it).

    Symmetric per-output-column int8 over the stacked per-layer matrices
    ``[L, K, N] -> {"w8" int8 [L, K, N], "scale" f32 [L, 1, N]}`` plus the
    untied ``lm_head``; embeddings, norms, and biases stay in the model
    dtype (embeds are row-gathers, not streamed matmuls). Scheme parity:
    the reference quantizer's symmetric mode (``csrc/quantization``)."""
    def q(w):
        absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2,
                         keepdims=True)
        scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        w8 = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                      -127, 127).astype(jnp.int8)
        return {"w8": w8, "scale": scale.astype(jnp.float32)}

    return _quantize_weight_tree(weights, q)


def _quantize_weight_tree(weights: Dict, q) -> Dict:
    layers = weights["layers"]
    for key in _QUANT_KEYS:
        if key in layers and not isinstance(layers[key], dict):
            layers[key] = q(layers[key])
    mlp = layers.get("mlp")
    if isinstance(mlp, dict):
        for key in _QUANT_MLP_KEYS:
            if key in mlp and not isinstance(mlp[key], dict):
                mlp[key] = q(mlp[key])
    moe = layers.get("moe")
    if isinstance(moe, dict):
        # expert stacks [L, E, K, N] — the dominant streamed bytes of an MoE
        # serving step (ADVICE r4: silently skipping them made weight_bits=8
        # a near-no-op on mixtral); scale per (layer, expert, out-column).
        # The router stays fp32 (tiny, feeds top_k).
        for key in _QUANT_MLP_KEYS:
            if key in moe and not isinstance(moe[key], dict):
                moe[key] = q(moe[key])
    if "lm_head" in weights and not isinstance(weights["lm_head"], dict):
        weights["lm_head"] = q(weights["lm_head"])
    return weights


def _transformer_layer(spec: "RaggedModelSpec", w, x, positions, attend,
                       lora=None):
    """Shared per-layer transformer body for BOTH the ragged forward (put
    passes) and the fused multistep decode — one implementation so the two
    paths cannot diverge.  ``attend(q, k, v) -> (attn_raw [N, H, D],
    *state)`` performs the KV page write + attention for its pass shape;
    ``state`` is the caller's carried cache state (pools, or pools + scale
    pools for int8 KV). ``lora`` (``_lora_split`` output, or None) adds each
    row's grouped adapter delta to the targeted attention projections.
    Returns ``(x_out, state_tuple)``.
    """
    H, Hkv, D = spec.num_heads, spec.num_kv_heads, spec.head_dim
    dtype = spec.dtype
    h1 = _norm(x, w["ln1"], spec.norm, spec.eps, dtype, spec.norm_plus_one)
    q = _lora_mm(h1, w["wq"], lora, "q").reshape(-1, H, D)
    k = _lora_mm(h1, w["wk"], lora, "k").reshape(-1, Hkv, D)
    v = _lora_mm(h1, w["wv"], lora, "v").reshape(-1, Hkv, D)
    if "bq" in w:
        q = q + w["bq"].reshape(H, D)
        k = k + w["bk"].reshape(Hkv, D)
        v = v + w["bv"].reshape(Hkv, D)
    if spec.rope_theta is not None:
        q = _rope_flat(q, positions, spec.rope_theta, spec.rotary_dim)
        k = _rope_flat(k, positions, spec.rope_theta, spec.rotary_dim)

    attn_raw, *state = attend(q, k, v)
    attn_out = _lora_mm(attn_raw.reshape(-1, H * D), w["wo"], lora, "o")
    if "bo" in w:
        attn_out = attn_out + w["bo"]

    if spec.parallel_block:
        mlp_in = (_norm(x, w["ln2"], spec.norm, spec.eps, dtype,
                        spec.norm_plus_one)
                  if spec.parallel_dual_norm else h1)
    else:
        x = x + attn_out
        mlp_in = _norm(x, w["ln2"], spec.norm, spec.eps, dtype,
                       spec.norm_plus_one)

    if spec.moe is not None:
        mlp_out = _moe_ffn(mlp_in, w["moe"], spec.moe["top_k"], dtype)
    else:
        m = w["mlp"]
        if spec.activation in ("swiglu", "geglu"):
            gate_act = jax.nn.silu if spec.activation == "swiglu" else jax.nn.gelu
            hmid = gate_act(_mm(mlp_in, m["w_gate"])) * _mm(mlp_in, m["w_up"])
        else:
            act = _plain_act(spec.activation)
            hmid = _mm(mlp_in, m["w_up"])
            if "b_up" in m:
                hmid = hmid + m["b_up"]
            hmid = act(hmid)
        mlp_out = _mm(hmid, m["w_down"])
        if "b_down" in m:
            mlp_out = mlp_out + m["b_down"]

    if spec.parallel_block:
        x = x + attn_out + mlp_out
    else:
        x = x + mlp_out
    return x.astype(dtype), tuple(state)


def _embed_in(spec: "RaggedModelSpec", weights, tokens, positions):
    """Token (+ learned position) embedding with the Gemma sqrt(hidden)
    normaliser — fp32 round-trip matches models/llama.py ``_trunk``."""
    x = weights["embed"][tokens]
    if spec.learned_pos:
        x = x + weights["pos_embed"][positions + spec.pos_offset]
    if spec.embed_norm:
        x = _norm(x.astype(spec.dtype), weights["embed_norm"], spec.norm,
                  spec.eps, spec.dtype, spec.norm_plus_one)
    if spec.embed_scale_by_sqrt_dim:
        x = x.astype(jnp.float32) * (spec.hidden_size ** 0.5)
    return x.astype(spec.dtype)


def _unembed(spec: "RaggedModelSpec", weights, xs):
    """Final-hidden rows -> fp32 logits (tied or untied head, optional bias)."""
    if spec.tied_lm_head:
        logits = xs.astype(jnp.float32) @ weights["embed"].astype(jnp.float32).T
    else:
        logits = _mm(xs, weights["lm_head"]).astype(jnp.float32)
    if spec.head_bias:
        logits = logits + weights["lm_head_bias"].astype(jnp.float32)
    return logits


def _kv_write_rows(dest_tok, Hkv, bs):
    """Flat K and V row destinations in the combined head-major pool
    [L*NB*2*Hkv*bs, D] for LAYER-GLOBAL token indices ``dest_tok``
    (global_page * bs + slot): K row ((g*2 + 0)*Hkv + h)*bs + slot, V row
    ((g*2 + 1)*Hkv + h)*bs + slot. Sentinel dest (>= pool tokens) maps past
    the pool and drops."""
    page_g = dest_tok // bs
    h = jnp.arange(Hkv)[None, :]
    slot = (dest_tok % bs)[:, None]
    k_rows = ((page_g[:, None] * 2 + 0) * Hkv + h) * bs + slot
    v_rows = ((page_g[:, None] * 2 + 1) * Hkv + h) * bs + slot
    return jnp.concatenate([k_rows.reshape(-1), v_rows.reshape(-1)])


def _kv_page_write(kvp, k, v, dest_tok, Hkv, bs):
    """Scatter of new K/V rows into the FLAT combined head-major paged cache
    [L*NB*2*Hkv*bs, D]; out-of-range dest rows (padding sentinels) drop.

    The flat-rows-with-layer-offset layout is the load-bearing design choice:
    the pool rides the layer scan as CARRY and this scatter is its only
    consumer, so XLA updates the (hundreds of MB) pool in place. The earlier
    per-layer layout — pools as scan xs/ys with a per-layer dynamic-slice +
    scatter + re-stack — materialised two full pool copies per pass and was
    the single largest cost in the decode step (measured ~5 ms of a 16 ms
    step at 0.55B/32 seqs on v5e; see docs/ROUND3_NOTES.md)."""
    T = dest_tok.shape[0]
    rows = _kv_write_rows(dest_tok, Hkv, bs)
    new = jnp.concatenate([k.reshape(T * Hkv, -1), v.reshape(T * Hkv, -1)])
    return kvp.at[rows].set(new.astype(kvp.dtype), mode="drop")


def _scale_dest(rows, Hkv, bs):
    """Value-row index [*, in L*NB*2*Hkv*bs] -> flat index into the TILED
    scale pool [L*NB*R8*128]: page r8*128-strided, in-page offset = the flat
    scale index (kv*Hkv*bs + h*bs + t). OOB value rows map OOB."""
    hb2 = 2 * Hkv * bs
    r8 = _scale_tile_rows(Hkv, bs)
    return (rows // hb2) * (r8 * 128) + rows % hb2


def _kv_page_write_quant(kvp, sc, k, v, dest_tok, Hkv, bs):
    """int8 variant of :func:`_kv_page_write`: quantize the new rows on
    append (per token-head) and scatter values + scales. ``sc`` is the FLAT
    view of the tiled at-rest scale pool ([L*NB*R8*128] f32)."""
    T = dest_tok.shape[0]
    rows = _kv_write_rows(dest_tok, Hkv, bs)
    kq, ksc = kv_quantize_rows(k)                              # [T,Hkv,D]/[T,Hkv]
    vq, vsc = kv_quantize_rows(v)
    new = jnp.concatenate([kq.reshape(T * Hkv, -1), vq.reshape(T * Hkv, -1)])
    news = jnp.concatenate([ksc.reshape(-1), vsc.reshape(-1)])
    kvf = kvp.at[rows].set(new, mode="drop")
    scf = sc.at[_scale_dest(rows, Hkv, bs)].set(news, mode="drop")
    return kvf, scf


def _page_plan_gather(k, v, page_rows, page_fill, bs):
    """Gather the page plan's token windows: -> K/V [PW, Hkv, bs, D]."""
    CT = k.shape[0]
    j = jnp.arange(bs, dtype=jnp.int32)
    rows = jnp.minimum(page_rows[:, None] + j[None, :], CT - 1)     # [PW, bs]
    valid = j[None, :] < page_fill[:, None]                         # [PW, bs]
    kg = jnp.where(valid[..., None, None], k[rows], 0)              # [PW,bs,Hkv,D]
    vg = jnp.where(valid[..., None, None], v[rows], 0)
    return jnp.moveaxis(kg, 2, 1), jnp.moveaxis(vg, 2, 1)


def _page_plan_tgt(page_ids, l, NB, L, Hkv):
    """Combined-pool [L*NB*2*Hkv, bs, D] head-row targets for a page plan:
    K rows (g*2+0)*Hkv + h, V rows (g*2+1)*Hkv + h. Sentinel pages (id >=
    NB) go out of range GLOBALLY, not into the next layer's pages."""
    page_g = jnp.where(page_ids < NB, l * NB + page_ids, L * NB)
    h = jnp.arange(Hkv)[None, :]
    tgt_k = ((page_g[:, None] * 2 + 0) * Hkv + h).reshape(-1)
    tgt_v = ((page_g[:, None] * 2 + 1) * Hkv + h).reshape(-1)
    return jnp.concatenate([tgt_k, tgt_v])


def _kv_page_write_pages(kvp, k, v, l, page_ids, page_rows, page_fill,
                         NB, bs, L, Hkv):
    """Page-granular pool update for prefill-from-zero passes.

    Each plan entry (RaggedBatch.page_ids/rows/fill) covers one page written
    by one contiguous run of chunk rows, so the update is a gather of whole
    pages followed by a scatter of [bs, D] windows over ~CT/bs indices —
    TPU scatters cost per index, and this replaces the CT*Hkv single-row
    scatter (measured 57 ms -> ~6 ms per 32x128-token wave, v5e-1). Rows past
    ``fill`` are zero-filled; they are never read (all readers bound k_pos by
    ctx_len) so overwriting a freed page's stale tail is safe."""
    PW = page_ids.shape[0]
    D = k.shape[-1]
    kg, vg = _page_plan_gather(k, v, page_rows, page_fill, bs)
    kv3 = kvp.reshape(L * NB * 2 * Hkv, bs, D)
    tgt = _page_plan_tgt(page_ids, l, NB, L, Hkv)
    new = jnp.concatenate([kg.reshape(PW * Hkv, bs, D),
                           vg.reshape(PW * Hkv, bs, D)])
    kv3 = kv3.at[tgt].set(new.astype(kvp.dtype), mode="drop")
    return kv3.reshape(-1, D)


def _scale_page_tiles(ksc, vsc, Hkv, bs):
    """Per-page K/V scales [PW, Hkv, bs] x2 -> at-rest tiles [PW, R8, 128]
    (flat order kv*Hkv*bs + h*bs + t, zero-padded to the tile)."""
    PW = ksc.shape[0]
    r8 = _scale_tile_rows(Hkv, bs)
    flat = jnp.concatenate([ksc.reshape(PW, Hkv * bs),
                            vsc.reshape(PW, Hkv * bs)], axis=1)
    pad = r8 * 128 - 2 * Hkv * bs
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat.reshape(PW, r8, 128)


def _kv_page_write_pages_quant(kvp, sc, k, v, l, page_ids, page_rows,
                               page_fill, NB, bs, L, Hkv):
    """int8 variant of :func:`_kv_page_write_pages`: the gathered page
    windows quantize per token-head row; the tiled scale pool
    ([L*NB, R8, 128] view) gets one whole-tile scatter per page."""
    PW = page_ids.shape[0]
    D = k.shape[-1]
    kg, vg = _page_plan_gather(k, v, page_rows, page_fill, bs)
    kgq, kgs = kv_quantize_rows(kg)                                # [PW,Hkv,bs,D]
    vgq, vgs = kv_quantize_rows(vg)
    kv3 = kvp.reshape(L * NB * 2 * Hkv, bs, D)
    tgt = _page_plan_tgt(page_ids, l, NB, L, Hkv)
    new = jnp.concatenate([kgq.reshape(PW * Hkv, bs, D),
                           vgq.reshape(PW * Hkv, bs, D)])
    kv3 = kv3.at[tgt].set(new, mode="drop")
    page_g = jnp.where(page_ids < NB, l * NB + page_ids, L * NB)
    sc = sc.at[page_g].set(_scale_page_tiles(kgs, vgs, Hkv, bs),
                           mode="drop")
    return kv3.reshape(-1, D), sc


def _layer_dest(dest, l, NB, bs, L):
    """Per-layer global token index: padding sentinels (>= NB*bs) must stay
    out of range GLOBALLY — a naive l*NB*bs + sentinel would land inside the
    next layer's pages."""
    return jnp.where(dest >= NB * bs, L * NB * bs, l * NB * bs + dest)


# keys each jitted pass actually reads (engine ships only these; the two
# passes are separate jit programs and the other path's descriptors would be
# dead upload weight)
PAGED_PASS_KEYS = (
    "chunk_tokens", "chunk_positions", "chunk_ntok", "chunk_block_tables",
    "chunk_q0", "chunk_ctx_lens", "decode_tokens", "decode_positions",
    "decode_block_tables", "decode_ctx_lens", "kv_dest")
PREFILL_PASS_KEYS = (
    "chunk_tokens", "chunk_positions", "chunk_ntok", "decode_tokens",
    "row_seg", "page_ids", "page_rows", "page_fill")


def build_ragged_forward(spec: RaggedModelSpec,
                         mesh=None,
                         tp: int = 1,
                         n_splits: int = 1) -> Callable:
    """Returns ``fwd(weights, kv_pages, batch) ->
    (chunk_logits [NC, V], decode_logits [S, V], new_kv)`` where
    ``chunk_logits[j]`` holds the logits after slot j's last token.

    kv_pages: [L, NB, 2, Hkv, bs, D] combined head-major pages (see
    ragged/kv_cache.py), or an (int8 values, f32 scales) tuple for the
    kv_quant tier. ``batch`` is RaggedBatch.device_arrays().
    When ``tp > 1`` the paged attention kernels run under shard_map on the
    'tensor' axis (heads sharded); everything else partitions via XLA SPMD.
    """
    H, Hkv, D = spec.num_heads, spec.num_kv_heads, spec.head_dim
    hid = spec.hidden_size
    dtype = spec.dtype

    ak = AttentionKernelSpec(spec, mesh=mesh, tp=tp, n_splits=n_splits)

    def fwd(weights, kv_pages, b):
        kv_pages, kv_sc = _kv_unpack(kv_pages)
        kvq = kv_sc is not None
        NC = b["chunk_ntok"].shape[0]
        CT = b["chunk_tokens"].shape[0]
        Cs = CT // NC
        S = b["decode_tokens"].shape[0]
        L, NB, bs = kv_pages.shape[0], kv_pages.shape[1], kv_pages.shape[4]
        kvp0 = kv_pages.reshape(L * NB * 2 * Hkv * bs, D)  # flat (bitcast);
        r8 = _scale_tile_rows(Hkv, bs) if kvq else 0
        sc0 = kv_sc.reshape(L * NB * r8 * 128) if kvq else None
        tokens = jnp.concatenate([b["chunk_tokens"], b["decode_tokens"]])
        positions = jnp.concatenate([b["chunk_positions"], b["decode_positions"]])

        x = _embed_in(spec, weights, tokens, positions)

        def layer_fn(carry, scanned):
            x, kvp, sc = carry
            w, l = scanned

            def attend(q, k, v):
                dest = _layer_dest(b["kv_dest"], l, NB, bs, L)
                if kvq:
                    kvp_, sc_ = _kv_page_write_quant(kvp, sc, k, v, dest,
                                                     Hkv, bs)
                    scales = sc_.reshape(L * NB, r8, 128)
                else:
                    kvp_ = _kv_page_write(kvp, k, v, dest, Hkv, bs)
                    sc_, scales = sc, None
                kv_l = kvp_.reshape(L * NB, 2, Hkv, bs, D)
                out_c = ak.chunk(q[:CT].reshape(NC, Cs, H, D), kv_l,
                                 b["chunk_block_tables"] + l * NB,
                                 b["chunk_q0"], b["chunk_ctx_lens"],
                                 kv_scales=scales)
                out_d = ak.decode(q[CT:], kv_l,
                                  b["decode_block_tables"] + l * NB,
                                  b["decode_ctx_lens"], kv_scales=scales)
                return (jnp.concatenate([out_c.reshape(CT, H, D), out_d],
                                        axis=0), kvp_, sc_)

            x, (kvp, sc) = _transformer_layer(spec, w, x, positions, attend)
            return (x, kvp, sc), None

        (x, kvp, sc), _ = jax.lax.scan(
            layer_fn, (x, kvp0, sc0),
            (weights["layers"], jnp.arange(L, dtype=jnp.int32)))
        new_kv = kvp.reshape(L, NB, 2, Hkv, bs, D)
        if kvq:
            new_kv = (new_kv, sc.reshape(L, NB, r8, 128))

        x = _norm(x, weights["final_norm"], spec.norm, spec.eps, dtype,
                  spec.norm_plus_one)
        # only NC + S rows are ever read (parity: ragged_ops/logits_gather —
        # the reference also gathers the needed rows before the unembed GEMM)
        last_rows = (jnp.arange(NC) * Cs
                     + jnp.maximum(b["chunk_ntok"] - 1, 0))    # [NC]
        xs = jnp.concatenate([x[last_rows], x[CT:]], axis=0)   # [NC + S, hid]
        logits = _unembed(spec, weights, xs)
        return logits[:NC], logits[NC:], new_kv

    return fwd


def build_prefill_forward(spec: RaggedModelSpec,
                          mesh=None,
                          tp: int = 1) -> Callable:
    """Prefill-from-zero fast path: every token a slot can see was computed IN
    THIS PASS, so attention is one packed segment-masked flash kernel over the
    dense in-pass Q/K/V — no paged reads — and the page write happens AFTER
    attention (the pool is then a pure scatter target riding the layer scan,
    never read-then-written around an opaque kernel call).

    Same signature/outputs as :func:`build_ragged_forward` (decode_logits is
    zeros — a pure-prefill pass has no decode rows). The engine routes here
    when ``RaggedBatch.pure_prefill`` (scheduler.py). Measured v5e-1, 0.55B,
    32x128-token prompts: paged-chunk path 13 ms/layer attention vs ~1 ms
    packed — wave throughput 8k -> 30k+ tok/s.
    """
    H, Hkv, D = spec.num_heads, spec.num_kv_heads, spec.head_dim
    dtype = spec.dtype

    ak = AttentionKernelSpec(spec, mesh=mesh, tp=tp)

    def fwd(weights, kv_pages, b):
        NC = b["chunk_ntok"].shape[0]
        CT = b["chunk_tokens"].shape[0]
        Cs = CT // NC
        S = b["decode_tokens"].shape[0]
        kv_pages, kv_sc = _kv_unpack(kv_pages)
        kvq = kv_sc is not None
        L, NB, bs = kv_pages.shape[0], kv_pages.shape[1], kv_pages.shape[4]
        kvp0 = kv_pages.reshape(L * NB * 2 * Hkv * bs, D)
        r8 = _scale_tile_rows(Hkv, bs) if kvq else 0
        sc0 = kv_sc.reshape(L * NB, r8, 128) if kvq else None
        tokens = b["chunk_tokens"]
        positions = b["chunk_positions"]
        seg = b["row_seg"]

        x = _embed_in(spec, weights, tokens, positions)

        def layer_fn(carry, scanned):
            x, kvp, sc = carry
            w, l = scanned

            def attend(q, k, v):
                # attention reads the PACKED in-flight rows (full precision);
                # only the page write quantizes — the fast path's packed-vs-
                # paged variance already makes equality gates force the paged
                # path, int8 or not (docs/SERVING.md "Quantized KV")
                out = ak.packed(q, k, v, seg)
                if kvq:
                    kvp_, sc_ = _kv_page_write_pages_quant(
                        kvp, sc, k, v, l, b["page_ids"],
                        b["page_rows"], b["page_fill"], NB, bs, L, Hkv)
                else:
                    kvp_ = _kv_page_write_pages(
                        kvp, k, v, l, b["page_ids"], b["page_rows"],
                        b["page_fill"], NB, bs, L, Hkv)
                    sc_ = sc
                return out, kvp_, sc_

            x, (kvp, sc) = _transformer_layer(spec, w, x, positions, attend)
            return (x, kvp, sc), None

        (x, kvp, sc), _ = jax.lax.scan(
            layer_fn, (x, kvp0, sc0),
            (weights["layers"], jnp.arange(L, dtype=jnp.int32)))
        new_kv = kvp.reshape(L, NB, 2, Hkv, bs, D)
        if kvq:
            new_kv = (new_kv, sc.reshape(L, NB, r8, 128))

        x = _norm(x, weights["final_norm"], spec.norm, spec.eps, dtype,
                  spec.norm_plus_one)
        last_rows = (jnp.arange(NC) * Cs
                     + jnp.maximum(b["chunk_ntok"] - 1, 0))    # [NC]
        logits = _unembed(spec, weights, x[last_rows])
        decode_logits = jnp.zeros((S, logits.shape[1]), logits.dtype)
        return logits, decode_logits, new_kv

    return fwd


def _build_multistep_sidebuf(spec: RaggedModelSpec, n_steps: int,
                             do_sample: bool, top_k: int,
                             n_splits: int = 1) -> Callable:
    """Fused multistep decode WITHOUT per-step pool scatters.

    The default multistep loop writes each step's K/V into the paged pools
    with a [S*Hkv]-row scatter per layer per step; TPU scatter serializes
    per row, and at S=256 those writes cost ~2.5 ms/step — more than the
    dense compute (measured v5e-1, 0.55B GQA: dense-only 1.8 ms,
    dense+scatter 4.3 ms, full 7.0 ms). Here the pools stay FROZEN for the
    whole chunk:

      - each layer's new K/V rows accumulate in a sequence-major side buffer
        [L, S, C, Hkv, D] (one contiguous dynamic_update_slice per step);
      - attention per step = ONE fused kernel over the frozen prefix pages
        plus the side slab (``paged_decode_attention_sidebuf``): the side
        rows fold into the same online-softmax state, so the kernel reads
        one sequence's [C, Hkv, D] slab into VMEM instead of the round-4
        schedule's per-layer-per-step jnp re-read of the whole [C, S, Hkv,
        D] buffer + lse merge;
      - ONE page-granular read-modify-write flushes the side buffers into
        the pools at chunk end (~n_span pages per sequence per layer,
        amortized over the C steps).

    Used when tp == 1 and head_dim % 128 == 0 (the fused kernel's
    alignment); other configs take the general loop below. ``window`` is
    admitted (the kernel windows both pieces by the moving query position);
    the page-ring flush stays correct because the flush only touches pages
    holding positions >= prefix, which the ring never recycles mid-chunk.
    """
    H, Hkv, D = spec.num_heads, spec.num_kv_heads, spec.head_dim
    G = H // Hkv
    dtype = spec.dtype
    # side-slab CAPACITY is n_steps padded so Cb*Hkv aligns to the 8-sublane
    # tile (MQA Hkv=1 with arbitrary n_steps stays on the fast path; padded
    # rows are never visible: the kernel masks cc > j and j < n_steps, and
    # the flush only writes rows < n_steps)
    C = n_steps
    Cb = n_steps
    while (Cb * Hkv) % 8 != 0:
        Cb += 1
    scale = 1.0 / (D ** 0.5)
    ak = AttentionKernelSpec(spec, mesh=None, tp=1, n_splits=n_splits)

    def fwd(weights, kv_pages, ids0, positions0, block_tables, ctx0,
            key, temperature=1.0):
        kv_pages, kv_sc = _kv_unpack(kv_pages)
        kvq = kv_sc is not None
        S = ids0.shape[0]
        L, NB, bs = kv_pages.shape[0], kv_pages.shape[1], kv_pages.shape[4]
        MB = block_tables.shape[1]
        kvp5 = kv_pages.reshape(L * NB, 2, Hkv, bs, D)
        # scales are stored in kernel tile layout AT REST — the view below
        # is a bitcast, so the frozen-pool scans never pay a conversion
        r8 = _scale_tile_rows(Hkv, bs) if kvq else 0
        sc4 = kv_sc.reshape(L * NB, r8, 128) if kvq else None
        # engine contract: ctx0 counts tokens INCLUDING the first current
        # token; the pages hold only the frozen prefix [0, ctx0 - 1) — the
        # current token (and everything after) lives in the side buffers
        prefix = jnp.maximum(ctx0 - 1, 0)
        # side buffers live PRE-FLATTENED as [L, S, Cb*Hkv, D] rows
        # (row cc*Hkv + h): with Hkv second-minor, the per-call reshape to
        # kernel rows relayout-copies the WHOLE buffer at head counts whose
        # (Hkv, D) tile pads (measured: 14 ms/step vs 2.9 at MHA-12 — the
        # same padded-sublane trap the kv pool layout avoids, kv_cache.py).
        # int8 pools: the slab holds kv_write_dequant'd POOL values, kept
        # f32 so a bf16 slab round-trip cannot round them away from what
        # every pool read (int8 * f32 scale, in f32) computes
        side_dtype = jnp.float32 if kvq else dtype
        side_k0 = jnp.zeros((L, S, Cb * Hkv, D), side_dtype)
        side_v0 = jnp.zeros((L, S, Cb * Hkv, D), side_dtype)

        def one_pass(x_ids, pos, j, sk_all, sv_all):
            x = _embed_in(spec, weights, x_ids, pos)

            def layer_fn(carry, scanned):
                # side buffers ride the CARRY with in-place dynamic updates —
                # as scan xs/ys they are repacked (a full side-buffer copy
                # per step, measured slower than the scatter they replace)
                x, sk_all, sv_all = carry
                w, l = scanned

                def attend(q, k, v):
                    if kvq:
                        # int8 pools: the slab holds the rows' POOL values
                        # (quantize-then-dequantize), so the in-chunk tokens
                        # are attended at the same values every later
                        # pool read — and the spec verify's write-then-
                        # attend — dequantizes; the chunk-end flush
                        # re-quantizes to the identical int8 bytes
                        # (kv_write_dequant is value-idempotent)
                        k = kv_write_dequant(k)
                        v = kv_write_dequant(v)
                    # step j's rows are the contiguous flat span
                    # [j*Hkv, (j+1)*Hkv)
                    sk_new = jax.lax.dynamic_update_slice(
                        sk_all, k[None].astype(sk_all.dtype),
                        (l, 0, j * Hkv, 0))
                    sv_new = jax.lax.dynamic_update_slice(
                        sv_all, v[None].astype(sv_all.dtype),
                        (l, 0, j * Hkv, 0))
                    # the WHOLE [L, S, Cb, Hkv, D] stack goes to the kernel,
                    # which BlockSpec-indexes layer l — a dynamic_slice here
                    # would materialise the layer's slab per call (measured
                    # ~150 us/layer of pure copy traffic)
                    out = ak.sidebuf(
                        q, kvp5, block_tables + l * NB, prefix,
                        sk_new, sv_new, j, layer_idx=l,
                        kv_scales=sc4 if kvq else None)
                    return out, sk_new, sv_new

                x, (sk_all, sv_all) = _transformer_layer(spec, w, x, pos,
                                                         attend)
                return (x, sk_all, sv_all), None

            (x, sk_new, sv_new), _ = jax.lax.scan(
                layer_fn, (x, sk_all, sv_all),
                (weights["layers"], jnp.arange(L, dtype=jnp.int32)))
            x = _norm(x, weights["final_norm"], spec.norm, spec.eps, dtype,
                      spec.norm_plus_one)
            return _unembed(spec, weights, x), sk_new, sv_new

        def sample(logits, step_key):
            return _sample_logits(logits, step_key, do_sample, top_k,
                                  temperature)

        def step(carry, j):
            ids, pos, sk_all, sv_all, _ = carry
            logits, sk_all, sv_all = one_pass(ids, pos, j, sk_all, sv_all)
            nxt = sample(logits, jax.random.fold_in(key, j))
            return (nxt, pos + 1, sk_all, sv_all, logits), ids

        V = weights["embed"].shape[0]
        init_logits = jnp.zeros((S, V), jnp.float32)
        (_, _, sk_all, sv_all, final_logits), out_ids = jax.lax.scan(
            step, (ids0, positions0, side_k0, side_v0, init_logits),
            jnp.arange(C))

        # ---- chunk-end flush: side buffers -> pool, page-granular RMW ---- #
        # the kernels READ the pool inside the scan; the barrier ties the
        # flush's pool operand to the scan result so XLA orders the in-place
        # scatter after the reads instead of cloning the (GB-scale) pool
        kvp5b, sc4b, _ = jax.lax.optimization_barrier(
            (kvp5, sc4, final_logits))
        n_span = -(-C // bs) + 1
        t_idx = jnp.arange(n_span)
        lp = prefix[:, None] // bs + t_idx[None, :]             # [S, n_span]
        phys = jnp.take_along_axis(block_tables,
                                   jnp.minimum(lp, MB - 1),
                                   axis=1)                      # [S, n_span]
        page_valid = (lp * bs < prefix[:, None] + C) & (lp < MB)
        # token slot k of span page t: global pos g = lp*bs + k, side row
        # j = g - prefix (valid iff 0 <= j < C)
        g_pos = lp[:, :, None] * bs + jnp.arange(bs)[None, None, :]
        j_rel = g_pos - prefix[:, None, None]                   # [S, n_span, bs]
        tok_valid = (j_rel >= 0) & (j_rel < C)
        j_clamp = jnp.clip(j_rel, 0, C - 1)
        s_idx = jnp.arange(S)[:, None, None]
        phys_l = (phys[None] + (jnp.arange(L) * NB)[:, None, None])
        phys_l = jnp.where(page_valid[None], phys_l, L * NB)    # OOB -> drop
        idx = jnp.minimum(phys_l, L * NB - 1)

        # side [L, S, Cb*Hkv, D] flat rows -> combined new values
        # [L, S, n_span, 2, Hkv, bs, D]
        def span_of(side):
            rows = j_clamp[..., None] * Hkv + jnp.arange(Hkv)  # [S,nsp,bs,Hkv]
            newv = side[:, s_idx[..., None], rows]  # [L,S,n_span,bs,Hkv,D]
            return jnp.moveaxis(newv, 4, 3)         # [...,Hkv,bs,D]

        newv = jnp.stack([span_of(sk_all), span_of(sv_all)], axis=3)
        old = kvp5b[idx]                            # [L,S,n_span,2,Hkv,bs,D]
        tv = tok_valid[None, :, :, None, None, :, None]
        if kvq:
            # int8 pool: quantize the flushed rows; the RMW keeps the old
            # page values AND old scales where the span page's slots predate
            # the chunk. Scales combine in the at-rest TILE layout (flat
            # per-page order kv*Hkv*bs + h*bs + t, zero-padded to R8*128).
            newq, news = kv_quantize_rows(newv)     # [L,S,n_span,2,Hkv,bs]
            comb = jnp.where(tv, newq, old)
            olds = sc4b[idx]                        # [L,S,n_span,R8,128]
            n_sp = news.shape[2]
            pad = r8 * 128 - 2 * Hkv * bs
            newt = news.reshape(L, S, n_sp, 2 * Hkv * bs)
            tvf = jnp.broadcast_to(tok_valid[:, :, None, :],
                                   (S, n_sp, 2 * Hkv, bs)
                                   ).reshape(S, n_sp, 2 * Hkv * bs)
            if pad:
                newt = jnp.pad(newt, ((0, 0),) * 3 + ((0, pad),))
                tvf = jnp.pad(tvf, ((0, 0),) * 2 + ((0, pad),))
            combs = jnp.where(tvf.reshape(1, S, n_sp, r8, 128),
                              newt.reshape(L, S, n_sp, r8, 128), olds)
            kvf = kvp5b.at[phys_l.reshape(-1)].set(
                comb.reshape(-1, 2, Hkv, bs, D), mode="drop")
            scf = sc4b.at[phys_l.reshape(-1)].set(
                combs.reshape(-1, r8, 128), mode="drop")
            new_kv = (kvf.reshape(L, NB, 2, Hkv, bs, D),
                      scf.reshape(L, NB, r8, 128))
        else:
            comb = jnp.where(tv, newv.astype(kvp5b.dtype), old)
            kvf = kvp5b.at[phys_l.reshape(-1)].set(
                comb.reshape(-1, 2, Hkv, bs, D), mode="drop")
            new_kv = kvf.reshape(L, NB, 2, Hkv, bs, D)
        return (out_ids, final_logits, new_kv)

    return fwd


def build_multistep_decode(spec: RaggedModelSpec, n_steps: int,
                           mesh=None, tp: int = 1,
                           do_sample: bool = False,
                           top_k: int = 0,
                           window_ring_ok: bool = False,
                           max_side_bytes: Optional[int] = None,
                           lora_targets: Optional[Tuple[str, ...]] = None,
                           n_splits: int = 1) -> Callable:
    """Fused N-step greedy/sampled decode: the sample->embed->forward->sample
    feedback loop runs entirely on device for ``n_steps`` tokens per sequence.

    TPU-native rationale: the per-token serving loop pays one host<->device
    round trip per generated token (sample + descriptor upload); over a remote
    runtime or PCIe that round trip dwarfs the ~ms decode pass.  Fusing N steps
    amortises it N-fold — the host only pre-reserves KV pages for N tokens and
    syncs sequence lengths afterwards.  (Same motivation as the reference's
    CUDA-graph capture of the decode step, ``InferenceEngine._create_cuda_graph``
    engine.py:524, taken further: the whole token loop is one XLA program.)

    ``window_ring_ok``: with a sliding window, the side-buffer schedule
    freezes page reads for the whole chunk while writing ``n_steps`` tokens
    at the flush, so the scheduler's page ring must cover window + n_steps.
    The UNSAFE-to-assume case defaults off: windowed specs take the general
    (per-step write) loop unless the caller has checked
    ``scheduler.ring_covers(n_steps + 1)`` and passes True.

    ``max_side_bytes``: the side-buffer schedule carries two
    [L, S, C, Hkv, D] buffers through the scan (transient HBM the per-step
    schedule does not need); above this budget the general loop is used
    (default from DSTPU_SIDEBUF_MAX_MB, 6144 MB — ADVICE r4's OOM guard.
    6 GB not 2: an MHA-12 serving leg's buffers are 2.3 GB and the general
    loop is 4x slower there — measured bench regression when the gate was
    2 GB — while v5e HBM comfortably holds 6 GB transient beside a
    sub-1B serving model; larger models use the env knob).

    Returns ``fwd(weights, kv_pages, ids0 [S], positions0 [S],
    block_tables [S, MB], ctx0 [S], key) -> (out_ids [n_steps, S],
    final_logits [S, V], new_kv)`` where ``out_ids[j]`` is the token
    *consumed* by step j (ids0 first), and ``final_logits`` predict the token
    after the last generated one (so the serving loop can continue seamlessly).
    """
    general = _build_multistep_general(spec, n_steps, mesh=mesh, tp=tp,
                                       do_sample=do_sample, top_k=top_k,
                                       lora_targets=lora_targets,
                                       n_splits=n_splits)
    # LoRA programs take the general (per-step write) loop only: the
    # side-buffer schedule's decode path is the single-step pipeline's
    # domain and wiring adapter operands into its frozen-read scan buys
    # nothing (decode_steps bursts are NOT lora-wired; docs/SERVING.md)
    fits = (lora_targets is None and tp == 1 and spec.head_dim % 128 == 0
            and (spec.window is None or window_ring_ok))
    if not fits:
        return general
    sidebuf = _build_multistep_sidebuf(spec, n_steps, do_sample, top_k,
                                       n_splits=n_splits)
    if max_side_bytes is None:
        import os
        max_side_bytes = int(float(os.environ.get(
            "DSTPU_SIDEBUF_MAX_MB", "6144")) * 1e6)
    esize = jnp.dtype(spec.dtype).itemsize
    budget = max_side_bytes

    def fwd(weights, kv_pages, ids0, *rest, **kw):
        S = ids0.shape[0]
        L = _kv_unpack(kv_pages)[0].shape[0]
        side_bytes = (2 * L * S * n_steps * spec.num_kv_heads
                      * spec.head_dim * esize)
        impl = sidebuf if side_bytes <= budget else general
        return impl(weights, kv_pages, ids0, *rest, **kw)

    return fwd



def _sample_logits(logits, key, do_sample: bool, top_k: int, temperature):
    """The ONE greedy/temperature/top-k sampler shared by every fused decode
    program (multistep scan steps and the pipeline's decode-step wrapper).
    build_decode_step's byte-identical-to-burst guarantee depends on all
    sites running these exact ops with the same key fold — change it here,
    nowhere else."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    z = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jax.lax.top_k(z, top_k)[0][:, -1:]
        z = jnp.where(z < kth, -jnp.inf, z)
    return jax.random.categorical(key, z, axis=-1).astype(jnp.int32)

def build_decode_step(spec: RaggedModelSpec, mesh=None, tp: int = 1,
                      do_sample: bool = False, top_k: int = 0,
                      window_ring_ok: bool = False,
                      lora_targets: Optional[Tuple[str, ...]] = None,
                      n_splits: int = 1) -> Callable:
    """One fused decode step for the double-buffered serving pipeline:
    consume ``ids`` [S] (this step's tokens, already sampled), write their KV,
    run the forward pass, and sample the NEXT token row — all in ONE device
    program, so the only thing that ever needs to cross back to the host per
    decode step is the [S] int32 token row (4 bytes/sequence instead of the
    [S, V] logits block the per-token loop fetched).

    The forward body is exactly ``build_multistep_decode(n_steps=1)`` — the
    same one-pass math the fused bursts run, so a pipelined token stream is
    bit-identical to a ``decode_steps`` burst under greedy decoding. On top
    of it this wrapper re-derives the step's sampled next token from the
    returned logits (same key fold as the scan's step 0, so XLA CSEs it with
    the scan-internal sample) and RETURNS it, which the multistep builders
    deliberately do not: the pipeline chains step N+1's dispatch on step N's
    device-resident token row with no host round trip in between.

    Returns ``fwd(weights, kv_pages, ids [S], positions [S],
    block_tables [S, MB], ctx [S], key, temperature) ->
    (next_ids [S] int32, logits [S, V], new_kv)`` where ``logits`` predict
    ``next_ids`` (kept for the engine's continuation refs). With
    ``lora_targets`` set, ``fwd`` takes the two REQUIRED trailing LoRA
    operands ``(lora_pool, adapter_pt)`` after ``temperature`` and each
    row's grouped adapter delta rides the targeted projections.
    """
    inner = build_multistep_decode(spec, 1, mesh=mesh, tp=tp,
                                   do_sample=do_sample, top_k=top_k,
                                   window_ring_ok=window_ring_ok,
                                   lora_targets=lora_targets,
                                   n_splits=n_splits)

    def fwd(weights, kv_pages, ids, positions, block_tables, ctx,
            key, temperature=1.0, *lora_args):
        out_ids, logits, new_kv = inner(weights, kv_pages, ids, positions,
                                        block_tables, ctx, key, temperature,
                                        *lora_args)
        del out_ids  # == ids: the pipeline already holds this step's row
        # same fold as the scan's step 0, so XLA CSEs this with the
        # scan-internal sample
        nxt = _sample_logits(logits, jax.random.fold_in(key, 0), do_sample,
                             top_k, temperature)
        return nxt, logits, new_kv

    return fwd


def build_verify_step(spec: RaggedModelSpec, k: int, mesh=None,
                      tp: int = 1,
                      lora_targets: Optional[Tuple[str, ...]] = None,
                      n_splits: int = 1) -> Callable:
    """Speculative-decode verify step: score ``k`` draft tokens per sequence
    in ONE ragged forward (``inference/v2/spec/``; docs/SERVING.md
    "Speculative decoding").

    Each sequence contributes K+1 = ``k + 1`` rows — its committed current
    token (device-resident, sampled by the previous step) followed by the
    host-proposed draft. Every layer scatters all K+1 rows' K/V into the
    paged pool (the same flat-scatter the ragged pass uses), then attends
    with the batched chunk kernel: one slot per sequence, causal by absolute
    position, so row j sees exactly the frozen prefix plus in-pass rows
    0..j. That per-row visible set — and the kernel's page-ordered online
    softmax — is identical to what ``build_decode_step`` computes one token
    at a time, so for any row whose consumed prefix matches the greedy
    stream the logits are BIT-EQUAL to sequential decode (the exactness
    induction the byte-identical bench gate rests on; pinned by
    tests/unit/test_spec_decode.py).

    The greedy accept mask is computed ON DEVICE: draft token j+1 is
    accepted iff it equals ``argmax(logits[:, j])`` and every earlier draft
    was accepted (``n_draft`` bounds per-row proposals — rows past their
    proposal count never accept, so per-sequence adaptive k rides a traced
    operand instead of a recompile). The per-step host transfer is ONE
    int32 ``[2, S]`` row — accept counts and bonus tokens — mirroring the
    decode pipeline's one-row discipline; the host reconstructs the emitted
    tokens from the draft it proposed.

    Rejected rows' K/V stays in the pool as stale bytes past the advanced
    context — never read (every reader is ctx-bounded) and overwritten by
    the next write at those positions; block-granular reclamation of
    reserved-but-unused pages is the scheduler's ``rollback_reserved``.

    int8 pools compose: the per-layer write is the quantize-on-write
    append (``_kv_page_write_quant``) and the chunk kernel dequantizes
    in-flight, so every in-pass token is attended at its POOL value —
    the same value sequential decode attends (the ``kv_write_dequant``
    discipline; docs/SERVING.md "Quantized KV").

    Returns ``fwd(weights, kv_pages, ids [S], draft [S, k], n_draft [S],
    positions [S], block_tables [S, MB], ctx [S]) -> (accept_row [2, S]
    int32, next_ids [S] int32, final_logits [S, V], new_kv)`` where
    ``accept_row[0]`` counts accepted draft tokens (row i emits
    ``accept_row[0, i] + 1`` tokens: the accepted prefix plus
    ``accept_row[1] = next_ids``, the greedy bonus/correction token) and
    ``final_logits`` predict ``next_ids``'s successor source row (the
    engine's continuation refs).
    """
    H, Hkv, D = spec.num_heads, spec.num_kv_heads, spec.head_dim
    dtype = spec.dtype
    K1 = k + 1

    ak = AttentionKernelSpec(spec, mesh=mesh, tp=tp, n_splits=n_splits)

    def fwd(weights, kv_pages, ids, draft, n_draft, positions0,
            block_tables, ctx0, *lora_args):
        kv_pages, kv_sc = _kv_unpack(kv_pages)
        kvq = kv_sc is not None
        assert not (kvq and tp > 1), "int8 KV pages + TP not wired"
        S = ids.shape[0]
        if lora_targets is not None:
            # each sequence's K+1 token rows share its adapter: repeat the
            # per-sequence gather to token rows so the verify batch runs the
            # SAME grouped delta sequential decode runs row-for-row (the
            # byte-equality induction extends to LoRA streams unchanged)
            lora_pool, adapter_pt = lora_args
            lora_ops = lora_layer_operands(spec, lora_targets, lora_pool,
                                           adapter_pt, repeat=K1)
        else:
            assert not lora_args, "lora operands on a non-LoRA program"
            lora_ops = None
        L, NB, bs = kv_pages.shape[0], kv_pages.shape[1], kv_pages.shape[4]
        MB = block_tables.shape[1]
        kvp0 = kv_pages.reshape(L * NB * 2 * Hkv * bs, D)
        r8 = _scale_tile_rows(Hkv, bs) if kvq else 0
        sc0 = kv_sc.reshape(L * NB * r8 * 128) if kvq else None
        tokens = jnp.concatenate([ids[:, None], draft], axis=1)    # [S, K1]
        positions = positions0[:, None] + jnp.arange(K1, dtype=jnp.int32)[None]
        pos_flat = positions.reshape(-1)
        # flat pool write destinations for every row: the run's reservation
        # covers positions0 + K1, so the logical page index is always inside
        # the table (pad rows' all-scratch tables clamp to the scratch page)
        page = jnp.take_along_axis(block_tables,
                                   jnp.minimum(positions // bs, MB - 1),
                                   axis=1)                          # [S, K1]
        dest = (page * bs + positions % bs).reshape(-1)

        x = _embed_in(spec, weights, tokens.reshape(-1), pos_flat)

        def layer_fn(carry, scanned):
            x, kvp, sc = carry
            if lora_ops is not None:
                w, l, lora_l = scanned
                lora = _lora_split(spec, lora_targets, lora_l)
            else:
                w, l = scanned
                lora = None

            def attend(q, k_, v):
                # write-then-attend (the ragged pass's discipline): all K+1
                # rows' K/V scatter into the pool — quantize-on-write for
                # int8 pools, the same fused append the decode step runs —
                # then the chunk kernel reads pages causally (dequantizing
                # in-flight), row j's own token included: every in-pass
                # token is attended at its POOL value, exactly what
                # sequential decode attends (docs/SERVING.md "Quantized KV")
                dl = _layer_dest(dest, l, NB, bs, L)
                if kvq:
                    kvp_, sc_ = _kv_page_write_quant(kvp, sc, k_, v, dl,
                                                     Hkv, bs)
                    scales = sc_.reshape(L * NB, r8, 128)
                else:
                    kvp_ = _kv_page_write(kvp, k_, v, dl, Hkv, bs)
                    sc_, scales = sc, None
                kv_l = kvp_.reshape(L * NB, 2, Hkv, bs, D)
                out = ak.chunk(q.reshape(S, K1, H, D), kv_l,
                               block_tables + l * NB, positions0,
                               ctx0 + (K1 - 1), kv_scales=scales)
                return out.reshape(S * K1, H, D), kvp_, sc_

            x, (kvp, sc) = _transformer_layer(spec, w, x, pos_flat, attend,
                                              lora=lora)
            return (x, kvp, sc), None

        xs = (weights["layers"], jnp.arange(L, dtype=jnp.int32))
        if lora_ops is not None:
            xs = xs + (lora_ops,)
        (x, kvp, sc), _ = jax.lax.scan(layer_fn, (x, kvp0, sc0), xs)
        new_kv = kvp.reshape(L, NB, 2, Hkv, bs, D)
        if kvq:
            new_kv = (new_kv, sc.reshape(L, NB, r8, 128))

        x = _norm(x, weights["final_norm"], spec.norm, spec.eps, dtype,
                  spec.norm_plus_one)
        logits = _unembed(spec, weights, x).reshape(S, K1, -1)
        # greedy accept: the SAME argmax _sample_logits greedy runs, so an
        # accepted token is exactly the token sequential decode would emit
        pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # [S, K1]
        match = (pred[:, :k] == draft) if k else jnp.zeros((S, 0), bool)
        match = match & (jnp.arange(k, dtype=jnp.int32)[None]
                         < n_draft[:, None])
        accept = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
        next_ids = jnp.take_along_axis(pred, accept[:, None], axis=1)[:, 0]
        final_logits = jnp.take_along_axis(
            logits, accept[:, None, None], axis=1)[:, 0]           # [S, V]
        accept_row = jnp.stack([accept, next_ids]).astype(jnp.int32)
        return accept_row, next_ids, final_logits, new_kv

    return fwd


def _build_multistep_general(spec: RaggedModelSpec, n_steps: int,
                             mesh=None, tp: int = 1,
                             do_sample: bool = False,
                             top_k: int = 0,
                             lora_targets: Optional[Tuple[str, ...]] = None,
                             n_splits: int = 1) -> Callable:
    """The per-step-write multistep loop (fused attention+page-write kernel
    per layer per step): the fallback when the side-buffer schedule's gates
    fail (TP sharding, small head_dim, window-ring capacity, side-buffer HBM
    budget). With ``lora_targets`` the built ``fwd`` takes two REQUIRED
    trailing operands after ``temperature`` — ``lora_pool [P+2, E]`` and
    ``adapter_pt [S, RB]`` — and every row's grouped adapter delta rides the
    targeted projections (docs/SERVING.md "Multi-tenant LoRA")."""
    H, Hkv, D = spec.num_heads, spec.num_kv_heads, spec.head_dim
    dtype = spec.dtype

    ak = AttentionKernelSpec(spec, mesh=mesh, tp=tp, n_splits=n_splits)

    def fwd(weights, kv_pages, ids0, positions0, block_tables, ctx0,
            key, temperature=1.0, *lora_args):
        kv_pages, kv_sc = _kv_unpack(kv_pages)
        kvq = kv_sc is not None
        assert not (kvq and tp > 1), "int8 KV pages + TP not wired"
        S = ids0.shape[0]
        L, NB, bs = kv_pages.shape[0], kv_pages.shape[1], kv_pages.shape[4]
        r8 = _scale_tile_rows(Hkv, bs) if kvq else 0
        if lora_targets is not None:
            # hoisted out of the step scan: the gather is loop-invariant
            # (a batch's adapter bindings are frozen for the whole run)
            lora_pool, adapter_pt = lora_args
            lora_ops = lora_layer_operands(spec, lora_targets, lora_pool,
                                           adapter_pt)
        else:
            assert not lora_args, "lora operands on a non-LoRA program"
            lora_ops = None

        def one_pass(x_ids, pos, ctx, kvp, sc):
            # kvp flat [L*NB*2*Hkv*bs, D]. The attention + page-write is one
            # fused unit (paged_decode_attention_step): pool aliased through
            # the kernel, new rows scattered in place after — the pool flows
            # through the layer scan with no copies (see the kernel docstring
            # for why a pre-kernel scatter forces XLA to clone the pool).
            x = _embed_in(spec, weights, x_ids, pos)

            def layer_fn(carry, scanned):
                x, kvp, sc = carry
                if lora_ops is not None:
                    w, l, lora_l = scanned
                    lora = _lora_split(spec, lora_targets, lora_l)
                else:
                    w, l = scanned
                    lora = None

                def attend(q, k, v):
                    if kvq:
                        # the current token is attended from registers:
                        # hand the kernel its POOL value (the in-kernel
                        # re-quantization for the page write is
                        # value-idempotent) so this path agrees with the
                        # write-then-attend paths on the attended VALUES
                        k = kv_write_dequant(k)
                        v = kv_write_dequant(v)
                        out, kv5, sc4 = ak.decode_step(
                            q, k, v, kvp.reshape(L * NB, 2, Hkv, bs, D),
                            block_tables + l * NB, ctx,
                            kv_scales=sc.reshape(L * NB, r8, 128))
                        return (out, kv5.reshape(L * NB * 2 * Hkv * bs, D),
                                sc4.reshape(L * NB * r8 * 128))
                    out, kv5 = ak.decode_step(
                        q, k, v, kvp.reshape(L * NB, 2, Hkv, bs, D),
                        block_tables + l * NB, ctx)
                    return (out, kv5.reshape(L * NB * 2 * Hkv * bs, D), sc)

                x, (kvp, sc) = _transformer_layer(spec, w, x, pos, attend,
                                                  lora=lora)
                return (x, kvp, sc), None

            xs = (weights["layers"], jnp.arange(L, dtype=jnp.int32))
            if lora_ops is not None:
                xs = xs + (lora_ops,)
            (x, kvp, sc), _ = jax.lax.scan(layer_fn, (x, kvp, sc), xs)
            x = _norm(x, weights["final_norm"], spec.norm, spec.eps, dtype,
                      spec.norm_plus_one)
            logits = _unembed(spec, weights, x)
            return logits, kvp, sc

        def sample(logits, step_key):
            return _sample_logits(logits, step_key, do_sample, top_k,
                                  temperature)

        def step(carry, j):
            ids, pos, ctx, kvp, sc, _ = carry
            logits, kvp, sc = one_pass(ids, pos, ctx, kvp, sc)
            nxt = sample(logits, jax.random.fold_in(key, j))
            return (nxt, pos + 1, ctx + 1, kvp, sc, logits), ids

        V = weights["embed"].shape[0]
        init_logits = jnp.zeros((ids0.shape[0], V), jnp.float32)
        kvp0 = kv_pages.reshape(L * NB * 2 * Hkv * bs, D)
        sc0 = kv_sc.reshape(L * NB * r8 * 128) if kvq else None
        (_, _, _, kvp, sc, final_logits), out_ids = jax.lax.scan(
            step, (ids0, positions0, ctx0, kvp0, sc0, init_logits),
            jnp.arange(n_steps))
        new_kv = kvp.reshape(L, NB, 2, Hkv, bs, D)
        if kvq:
            new_kv = (new_kv, sc.reshape(L, NB, r8, 128))
        return (out_ids, final_logits, new_kv)

    return fwd
