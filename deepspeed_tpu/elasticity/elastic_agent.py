"""Elastic training agent: supervise, restart, and resize on failure.

Parity: ``DSElasticAgent`` (reference ``elasticity/elastic_agent.py:28``,
extending torch's ``LocalElasticAgent``): integrates with torchelastic
rendezvous so that when workers die or nodes join/leave, the job restarts at a
new world size while ``compute_elastic_config`` keeps the global batch
invariant. XLA world membership is static per process set, so the TPU-native
agent is a host-side supervisor: it runs the training callable, and on failure
recomputes the valid (micro-batch, GAS, world-size) combination for the
surviving resources and restarts from the latest checkpoint — the
checkpoint-based recovery story of SURVEY §5.3/§5.4.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils import fault_injection
from deepspeed_tpu.utils.logging import logger


@dataclass
class RunRecord:
    world_size: int
    micro_batch: int
    gas: int
    error: Optional[str] = None
    restarts: int = 0
    #: universal-checkpoint dir the attempt was told to resume from (None on
    #: a cold start or when no complete checkpoint survived)
    resume_from: Optional[str] = None


class DSElasticAgent:
    """Supervise ``run_fn(world_size, micro_batch, gas, resume)``.

    ``ds_config`` must contain an ``elasticity`` block (the reference schema:
    max_train_batch_size, micro_batch_sizes, min/max_gpus...). On each
    (re)start the agent asks :func:`compute_elastic_config` for the valid
    batch split at the current world size; ``device_counts`` simulates
    membership changes (next entry after each failure).

    **Checkpoint-based recovery** (the preemption-tolerance story,
    docs/ELASTICITY.md): pass ``ckpt_dir`` (where the killed run's rolling/
    user checkpoints live) and restarts become elastic RESUMES — before each
    restart the agent finds the newest COMPLETE tag (torn tags from a
    mid-write death are skipped), converts it to a universal checkpoint
    (``ds_to_universal``), and passes ``resume_from=<universal dir>`` to
    ``run_fn``, which loads it at the NEW world size via
    ``load_universal_into_engine`` — step k on N devices resumes at step k
    on M devices with the global batch invariant.
    """

    def __init__(self, ds_config: Dict[str, Any], run_fn: Callable,
                 device_counts: List[int], max_restarts: int = 3,
                 backoff_s: float = 0.0, ckpt_dir: Optional[str] = None,
                 universal_dir: Optional[str] = None):
        self.ds_config = ds_config
        self.run_fn = run_fn
        self.device_counts = list(device_counts)
        if not self.device_counts:
            raise ValueError("device_counts must be non-empty")
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.ckpt_dir = ckpt_dir
        self.universal_dir = universal_dir or (
            os.path.join(ckpt_dir, "universal") if ckpt_dir else None)
        # honor the run's checkpoint.verify_load on the resume scan: a
        # checksum-corrupt newest tag must fall back to an older complete
        # one, not feed corrupted bytes into the resumed run
        self.verify_load = bool(
            (ds_config.get("checkpoint") or {}).get("verify_load", False))
        self.records: List[RunRecord] = []

    def _prepare_resume(self, attempt: int) -> Optional[str]:
        """Newest complete checkpoint -> universal fragments for this attempt.
        Returns the universal dir to resume from, or None when no loadable
        checkpoint exists (the run restarts from scratch, with a warning)."""
        if self.ckpt_dir is None:
            return None
        from deepspeed_tpu.checkpoint.state import find_resume_tag
        from deepspeed_tpu.checkpoint.universal import ds_to_universal
        tag = find_resume_tag(self.ckpt_dir, verify=self.verify_load)
        if tag is None:
            logger.warning(f"elastic agent: no complete checkpoint in "
                           f"{self.ckpt_dir}; restarting from scratch")
            return None
        # per-attempt dir: a conversion torn by ANOTHER preemption mid-convert
        # must never be mistaken for a complete universal checkpoint
        out = os.path.join(self.universal_dir, f"attempt{attempt}_{tag}")
        return ds_to_universal(self.ckpt_dir, out, tag=tag)

    def _resolve(self, world_size: int):
        final_batch, _valid, micro_batch = compute_elastic_config(
            self.ds_config, world_size=world_size, return_microbatch=True)
        gas = final_batch // (micro_batch * world_size)
        return final_batch, micro_batch, gas

    def run(self) -> RunRecord:
        """Run until success or restart budget exhausted (parity: the
        torchelastic restart loop with rendezvous rounds)."""
        attempt = 0
        idx = 0
        while True:
            world = self.device_counts[min(idx, len(self.device_counts) - 1)]
            rec = RunRecord(world_size=world, micro_batch=0, gas=0,
                            restarts=attempt)
            try:
                # injection point: a failure at (re)start — rendezvous loss,
                # a preempted replacement VM — exercises the restart budget
                fault_injection.maybe_fail("agent.run")
                # resolve INSIDE the retry scope: an incompatible resized world
                # size must advance to the next membership, not abort the agent
                final_batch, rec.micro_batch, rec.gas = self._resolve(world)
                logger.info(f"elastic agent: starting ws={world} "
                            f"micro={rec.micro_batch} gas={rec.gas} "
                            f"(global batch {final_batch}), attempt {attempt}")
                kwargs = {}
                if self.ckpt_dir is not None:
                    rec.resume_from = self._prepare_resume(attempt) \
                        if attempt > 0 else None
                    kwargs["resume_from"] = rec.resume_from
                self.run_fn(world_size=world, micro_batch=rec.micro_batch,
                            gas=rec.gas, resume=attempt > 0, **kwargs)
                self.records.append(rec)
                return rec
            except Exception as e:
                rec.error = f"{type(e).__name__}: {e}"
                self.records.append(rec)
                attempt += 1
                idx += 1
                if attempt > self.max_restarts:
                    logger.error(f"elastic agent: giving up after "
                                 f"{self.max_restarts} restarts: {rec.error}")
                    raise
                logger.warning(f"elastic agent: run failed ({rec.error}); "
                               f"restarting with next membership")
                if self.backoff_s:
                    time.sleep(self.backoff_s)
