"""Elastic training agent: supervise, restart, and resize on failure.

Parity: ``DSElasticAgent`` (reference ``elasticity/elastic_agent.py:28``,
extending torch's ``LocalElasticAgent``): integrates with torchelastic
rendezvous so that when workers die or nodes join/leave, the job restarts at a
new world size while ``compute_elastic_config`` keeps the global batch
invariant. XLA world membership is static per process set, so the TPU-native
agent is a host-side supervisor: it runs the training callable, and on failure
recomputes the valid (micro-batch, GAS, world-size) combination for the
surviving resources and restarts from the latest checkpoint — the
checkpoint-based recovery story of SURVEY §5.3/§5.4.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils.logging import logger


@dataclass
class RunRecord:
    world_size: int
    micro_batch: int
    gas: int
    error: Optional[str] = None
    restarts: int = 0


class DSElasticAgent:
    """Supervise ``run_fn(world_size, micro_batch, gas, resume)``.

    ``ds_config`` must contain an ``elasticity`` block (the reference schema:
    max_train_batch_size, micro_batch_sizes, min/max_gpus...). On each
    (re)start the agent asks :func:`compute_elastic_config` for the valid
    batch split at the current world size; ``device_counts`` simulates
    membership changes (next entry after each failure).
    """

    def __init__(self, ds_config: Dict[str, Any], run_fn: Callable,
                 device_counts: List[int], max_restarts: int = 3,
                 backoff_s: float = 0.0):
        self.ds_config = ds_config
        self.run_fn = run_fn
        self.device_counts = list(device_counts)
        if not self.device_counts:
            raise ValueError("device_counts must be non-empty")
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.records: List[RunRecord] = []

    def _resolve(self, world_size: int):
        final_batch, _valid, micro_batch = compute_elastic_config(
            self.ds_config, world_size=world_size, return_microbatch=True)
        gas = final_batch // (micro_batch * world_size)
        return final_batch, micro_batch, gas

    def run(self) -> RunRecord:
        """Run until success or restart budget exhausted (parity: the
        torchelastic restart loop with rendezvous rounds)."""
        attempt = 0
        idx = 0
        while True:
            world = self.device_counts[min(idx, len(self.device_counts) - 1)]
            rec = RunRecord(world_size=world, micro_batch=0, gas=0,
                            restarts=attempt)
            try:
                # resolve INSIDE the retry scope: an incompatible resized world
                # size must advance to the next membership, not abort the agent
                final_batch, rec.micro_batch, rec.gas = self._resolve(world)
                logger.info(f"elastic agent: starting ws={world} "
                            f"micro={rec.micro_batch} gas={rec.gas} "
                            f"(global batch {final_batch}), attempt {attempt}")
                self.run_fn(world_size=world, micro_batch=rec.micro_batch,
                            gas=rec.gas, resume=attempt > 0)
                self.records.append(rec)
                return rec
            except Exception as e:
                rec.error = f"{type(e).__name__}: {e}"
                self.records.append(rec)
                attempt += 1
                idx += 1
                if attempt > self.max_restarts:
                    logger.error(f"elastic agent: giving up after "
                                 f"{self.max_restarts} restarts: {rec.error}")
                    raise
                logger.warning(f"elastic agent: run failed ({rec.error}); "
                               f"restarting with next membership")
                if self.backoff_s:
                    time.sleep(self.backoff_s)
