"""``ds_elastic`` CLI: inspect elastic configs.

Parity: reference ``bin/ds_elastic`` — given a DeepSpeed config with an
``elasticity`` block, print the resolved final batch size, compatible world
sizes, and the micro-batch/GAS split at a hypothetical world size.
"""

from __future__ import annotations

import argparse
import json

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config


def main():
    p = argparse.ArgumentParser(description="DeepSpeed-TPU elasticity inspector")
    p.add_argument("-c", "--config", required=True, help="config json path")
    p.add_argument("-w", "--world-size", type=int, default=0,
                   help="resolve micro-batch/GAS at this world size")
    args = p.parse_args()
    with open(args.config) as f:
        ds_config = json.load(f)
    if args.world_size:
        final, valid, micro = compute_elastic_config(
            ds_config, world_size=args.world_size, return_microbatch=True)
        gas = final // (micro * args.world_size)
        print(json.dumps({"final_batch_size": final,
                          "valid_world_sizes": valid,
                          "world_size": args.world_size,
                          "micro_batch": micro,
                          "gradient_accumulation_steps": gas}, indent=2))
    else:
        final, valid = compute_elastic_config(ds_config)
        print(json.dumps({"final_batch_size": final,
                          "valid_world_sizes": valid}, indent=2))


if __name__ == "__main__":
    main()
