"""Elastic training math: valid (micro-batch, GAS, world-size) combinations.

Parity: ``deepspeed/elasticity/elasticity.py`` — given a target
``max_train_batch_size``, a preference list of ``micro_batch_sizes``, and host
bounds, compute a final global batch size plus the set of world sizes it can run
at unchanged (``_get_compatible_gpus_v01`` :83, v0.2 with model-parallel :126,
``compute_elastic_config`` :233). Keeping the global batch invariant as hosts
join/leave is what makes resumption loss-curve-neutral.

On TPU "gpus" are chips; world-size granularity is a host (a multiple of
``chips_per_host``), which plays the role the v0.2 model-parallel divisor plays
in the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

ELASTICITY_DEFAULT_VERSION = 0.2

# Highly-composite-style ladder used to propose batch sizes with many divisors
# (the reference uses a hard-coded highly-composite-number list for the same
# purpose: maximize the number of compatible world sizes).
_COMPOSITE_LADDER = [1, 2, 4, 6, 8, 12, 16, 24, 32, 36, 48, 60, 64, 96, 120,
                     128, 180, 240, 256, 360, 480, 512, 720, 840, 1024, 1260,
                     1680, 2520, 5040]


class ElasticityError(ValueError):
    pass


def _candidate_batch_sizes(micro_batches: List[int],
                           max_acceptable_batch_size: int) -> List[int]:
    """Batch sizes ≤ max that are (micro_batch x composite) for some micro batch."""
    candidates = set()
    for mb in micro_batches:
        for k in _COMPOSITE_LADDER:
            b = mb * k
            if b <= max_acceptable_batch_size:
                candidates.add(b)
            else:
                break
    return sorted(candidates)


def _valid_world_sizes(batch_size: int, micro_batches: List[int],
                       min_gpus: int, max_gpus: int,
                       granularity: int = 1) -> List[int]:
    """World sizes w (multiples of granularity) s.t. batch = mb * gas * w for
    some preferred micro batch and integer gas ≥ 1."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        per_mb = batch_size // mb  # gas * world
        w = granularity
        while w <= min(per_mb, max_gpus):
            if per_mb % w == 0 and w >= min_gpus:
                valid.add(w)
            w += granularity
    return sorted(valid)


def _get_compatible_gpus_v01(micro_batches: List[int],
                             max_acceptable_batch_size: int,
                             min_gpus: int = 1,
                             max_gpus: Optional[int] = None,
                             prefer_larger: bool = True
                             ) -> Tuple[int, List[int]]:
    """v0.1: pick the batch size with the most compatible world sizes.

    Parity: ``elasticity.py:83``."""
    max_gpus = max_gpus or max_acceptable_batch_size
    best: Tuple[int, List[int]] = (0, [])
    for b in _candidate_batch_sizes(micro_batches, max_acceptable_batch_size):
        valid = _valid_world_sizes(b, micro_batches, min_gpus, max_gpus)
        better = len(valid) > len(best[1])
        tie = len(valid) == len(best[1]) and valid
        if better or (tie and ((b > best[0]) == prefer_larger)):
            best = (b, valid)
    if not best[1]:
        raise ElasticityError(
            f"no compatible world sizes for micro_batches={micro_batches}, "
            f"max_batch={max_acceptable_batch_size}, gpus=[{min_gpus},{max_gpus}]")
    return best


def _get_compatible_gpus_v02(micro_batches: List[int],
                             max_acceptable_batch_size: int,
                             current_num_gpus: int,
                             min_gpus: int = 1,
                             max_gpus: Optional[int] = None,
                             prefer_larger: bool = True,
                             num_gpus_per_node: int = 1,
                             model_parallel_size: int = 1
                             ) -> Tuple[int, List[int], int]:
    """v0.2: model-parallel-aware — world sizes step in units of
    mp_size-compatible node groups. Parity: ``elasticity.py:126``."""
    max_gpus = max_gpus or max_acceptable_batch_size
    if model_parallel_size > 1:
        # data-parallel degree steps in groups of mp ranks; on TPU this is the
        # tp-span in chips, constrained to divide or be divided by the host size
        dp_gran = model_parallel_size // num_gpus_per_node \
            if model_parallel_size >= num_gpus_per_node else 1
        dp_gran = max(dp_gran, 1)
        granularity = model_parallel_size * dp_gran
    else:
        granularity = num_gpus_per_node
    best: Tuple[int, List[int]] = (0, [])
    for b in _candidate_batch_sizes(micro_batches, max_acceptable_batch_size):
        valid = _valid_world_sizes(b, micro_batches, min_gpus, max_gpus,
                                   granularity=granularity)
        better = len(valid) > len(best[1])
        tie = len(valid) == len(best[1]) and valid
        if better or (tie and ((b > best[0]) == prefer_larger)):
            best = (b, valid)
    if not best[1]:
        raise ElasticityError(
            f"no compatible world sizes (granularity={granularity}) for "
            f"micro_batches={micro_batches}, max_batch={max_acceptable_batch_size}")
    final_batch, valid = best
    if current_num_gpus in valid:
        chosen = current_num_gpus
    else:
        under = [w for w in valid if w <= current_num_gpus]
        chosen = max(under) if under else min(valid)
    return final_batch, valid, chosen


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Resolve the elastic section of a config dict.

    Parity: ``compute_elastic_config`` (``elasticity.py:233``). Returns
    ``(final_batch_size, valid_world_sizes[, micro_batch_size])``; when
    ``world_size`` is given, also validates it and picks the micro batch."""
    e = ds_config.get("elasticity", {})
    if not e or not e.get("enabled", False):
        raise ElasticityError("elasticity section missing or disabled")
    micro_batches = sorted(e.get("micro_batch_sizes", [2, 4, 6]), reverse=True)
    max_batch = e["max_train_batch_size"]
    min_gpus = e.get("min_gpus", 1)
    max_gpus = e.get("max_gpus", max_batch)
    prefer_larger = e.get("prefer_larger_batch", True)
    version = float(e.get("version", ELASTICITY_DEFAULT_VERSION))
    if any(mb <= 0 for mb in micro_batches):
        raise ElasticityError(f"micro batches must be positive: {micro_batches}")
    if version >= 0.2:
        final_batch, valid, _ = _get_compatible_gpus_v02(
            micro_batches, max_batch, current_num_gpus=world_size or min_gpus,
            min_gpus=min_gpus, max_gpus=max_gpus, prefer_larger=prefer_larger,
            num_gpus_per_node=e.get("num_gpus_per_node", 1),
            model_parallel_size=e.get("model_parallel_size", 1))
    else:
        final_batch, valid = _get_compatible_gpus_v01(
            micro_batches, max_batch, min_gpus, max_gpus, prefer_larger)
    if world_size > 0 and world_size not in valid:
        raise ElasticityError(
            f"world size {world_size} not in compatible set {valid}")
    if return_microbatch or world_size > 0:
        micro = None
        for mb in micro_batches:
            if world_size and final_batch % (mb * world_size) == 0:
                micro = mb
                break
        if micro is None:
            micro = micro_batches[0]
        if return_microbatch:
            return final_batch, valid, micro
    return final_batch, valid


def validate_elastic_nodes(n_nodes: int, min_nodes: int, max_nodes: int):
    """Launcher-side bound check (parity: ``launcher/runner.py:373-392``)."""
    if min_nodes > 0 and n_nodes < min_nodes:
        raise ElasticityError(f"{n_nodes} nodes < min_elastic_nodes {min_nodes}")
    if max_nodes > 0 and n_nodes > max_nodes:
        raise ElasticityError(f"{n_nodes} nodes > max_elastic_nodes {max_nodes}")
