"""Elastic training (parity: ``deepspeed/elasticity/``)."""

from deepspeed_tpu.elasticity.elasticity import (ElasticityError,
                                                 compute_elastic_config,
                                                 validate_elastic_nodes)

__all__ = ["ElasticityError", "compute_elastic_config", "validate_elastic_nodes"]
