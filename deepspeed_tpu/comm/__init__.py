"""deepspeed_tpu.comm — collectives + mesh topology.

Parity: the ``deepspeed.comm`` package (``deepspeed/comm/comm.py``) and the
process-group factory (``deepspeed/utils/groups.py``), rebuilt on jax device meshes
and XLA collectives.
"""

from deepspeed_tpu.comm.comm import (
    all_gather_into_tensor,
    reduce_scatter_tensor,
    all_to_all_single,
    send_recv,
    send,
    recv,
    all_reduce,
    all_gather,
    reduce_scatter,
    all_to_all,
    broadcast,
    ppermute,
    ring_shift,
    axis_index,
    axis_size,
    barrier,
    get_rank,
    get_world_size,
    init_distributed,
    is_initialized,
    configure,
    log_summary,
)
from deepspeed_tpu.comm.mesh import (
    MeshTopology,
    build_topology,
    get_topology,
    set_topology,
    reset_topology,
    PIPE_AXIS,
    DATA_AXIS,
    FSDP_AXIS,
    EXPERT_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
    BATCH_AXES,
    ALL_AXES,
)
from deepspeed_tpu.comm.logging import CommsLogger, get_comms_logger, calc_bw_log
