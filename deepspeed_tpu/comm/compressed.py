"""1-bit compressed allreduce with error feedback.

Parity (re-designed): reference ``runtime/comm/nccl.py:51
NcclBackend.compressed_allreduce`` (also ``mpi.py``/``hccl.py`` and the cupy
compression backend ``runtime/compression/cupy.py``) — the communication core
of the 1-bit optimizers: each worker sends only the *sign bits* of its tensor
plus one fp32 scale per chunk, with both worker-side and server-side error
feedback so the quantization error is re-injected on the next step and the
iterates converge as if uncompressed (arXiv:2102.02888).

TPU-native: a ``shard_map`` collective over a mesh axis. Transport is real
1-bit — signs packed 8-per-byte via ``packbits`` — so on-wire volume is
1/32 of fp32 (+1 scale per worker chunk), matching the reference's NCCL
gather of bit tensors. Two phases, like the reference:

  1. scatter-reduce: sign-compress (with worker error), all-to-all so worker k
     holds every worker's k-th chunk, decompress + sum;
  2. allgather: sign-compress the local reduced chunk (with server error),
     all-gather compressed, decompress -> every worker holds the full result.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _compress(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sign-bits (packed uint8) + L1-mean scale. x must be 1-d, len % 8 == 0."""
    scale = jnp.mean(jnp.abs(x))
    bits = (x >= 0).astype(jnp.uint8)
    return jnp.packbits(bits), scale


def _decompress(packed: jax.Array, scale: jax.Array, n: int) -> jax.Array:
    bits = jnp.unpackbits(packed)[:n].astype(jnp.float32)
    return (bits * 2.0 - 1.0) * scale


def compressed_allreduce(x: jax.Array, error_worker: jax.Array,
                         error_server: jax.Array, axis_name: str
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Mean of ``x`` across ``axis_name`` via 1-bit compression.

    Must run inside ``shard_map``. ``error_worker``/``error_server`` are this
    rank's persistent error-feedback buffers (same shape as ``x`` and
    ``x.size/n`` respectively). Returns ``(avg, new_error_worker,
    new_error_server)``.
    """
    n = jax.lax.psum(1, axis_name)
    orig_shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    if flat.shape[0] % (n * 8) != 0:
        raise ValueError(f"compressed_allreduce needs size divisible by "
                         f"{n * 8}, got {flat.shape[0]} (pad the buffer)")
    corrected = flat / n + error_worker.reshape(-1)

    # phase 1: compress chunks, a2a so rank k receives everyone's chunk k
    chunks = corrected.reshape(n, -1)
    chunk_len = chunks.shape[1]
    packed, scales = jax.vmap(_compress)(chunks)
    local_deq = jax.vmap(lambda p, s: _decompress(p, s, chunk_len))(packed, scales)
    new_error_worker = (corrected - local_deq.reshape(-1)).reshape(-1)

    recv_packed = jax.lax.all_to_all(packed, axis_name, 0, 0).reshape(n, -1)
    recv_scales = jax.lax.all_to_all(scales[:, None], axis_name, 0, 0).reshape(n)
    server_sum = jnp.sum(
        jax.vmap(lambda p, s: _decompress(p, s, chunk_len))(recv_packed, recv_scales),
        axis=0)

    # phase 2: compress the reduced chunk with server error, allgather
    server_corrected = server_sum + error_server.reshape(-1)
    s_packed, s_scale = _compress(server_corrected)
    s_deq = _decompress(s_packed, s_scale, chunk_len)
    new_error_server = server_corrected - s_deq

    all_packed = jax.lax.all_gather(s_packed, axis_name)
    all_scales = jax.lax.all_gather(s_scale, axis_name)
    result = jax.vmap(lambda p, s: _decompress(p, s, chunk_len))(
        all_packed, all_scales).reshape(-1)
    return (result.reshape(orig_shape), new_error_worker.reshape(orig_shape),
            new_error_server.reshape(error_server.shape))


def compressed_allreduce_emulated(x: jax.Array, error: jax.Array
                                  ) -> Tuple[jax.Array, jax.Array]:
    """Single-worker sign compression with error feedback.

    The 1-bit optimizers in the SPMD engine receive *already-reduced* grads
    (XLA inserts the DP reduction), so the communication-compression effect is
    applied to the reduced tensor: sign(x + error) * L1-mean, error carried to
    the next step. This is exactly ``compressed_allreduce`` at world size 1;
    the multi-worker shard_map form above serves manual-collective engines.
    """
    corrected = x.astype(jnp.float32) + error
    scale = jnp.mean(jnp.abs(corrected))
    out = jnp.sign(corrected) * scale
    out = jnp.where(corrected == 0.0, scale, out)  # sign(0) -> +1 like packbits
    return out, corrected - out
