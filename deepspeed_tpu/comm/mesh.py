"""Device-mesh topology manager.

This replaces the reference's process-group factory (``deepspeed/utils/groups.py:51
initialize`` and friends: ``_create_expert_and_data_parallel``,
``_get_sequence_parallel_group``, ``_create_zero_param_parallel_group``) with a single
``jax.sharding.Mesh`` carrying named axes. Where the reference carves the world into
NCCL communicators, we carve a device array into mesh axes; XLA lowers collectives
onto ICI within a slice and DCN across slices automatically.

Axes (outer -> inner):
  pipe    pipeline stages            (reference: PipelineParallelGrid, pipe/topology.py:251)
  data    replicated data parallel   (reference: data_parallel_group)
  fsdp    ZeRO sharding axis         (reference: ZeRO partitions over the DP group)
  expert  expert parallel            (reference: expert_parallel_group, groups.py:113)
  seq     sequence parallel          (reference: sequence_parallel_group, groups.py:468)
  tensor  tensor/model parallel      (reference: model_parallel_group / mpu)

The reference composes ZeRO's DP group from seq x dp (``runtime/engine.py:1513``);
here the equivalent is the ("data", "fsdp") tuple used for batch sharding, and
optimizer-state sharding rides ("fsdp",) (stage>=1) — expressed as shardings, not
groups.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.utils.logging import logger

# Canonical axis names
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
FSDP_SUB_AXIS = "fsdp_sub"  # ZeRO++ hpZ secondary partition / MiCS sub-group axis
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
TENSOR_AXIS = "tensor"

ALL_AXES: Tuple[str, ...] = (PIPE_AXIS, DATA_AXIS, FSDP_AXIS, FSDP_SUB_AXIS,
                             EXPERT_AXIS, SEQ_AXIS, TENSOR_AXIS)

# Composite "batch" axes: a global batch is sharded across everything that consumes
# distinct data (data-parallel replicas and fsdp shards).
BATCH_AXES: Tuple[str, ...] = (DATA_AXIS, FSDP_AXIS, FSDP_SUB_AXIS)

# Full ZeRO state-sharding axes: hpZ/MiCS factorize fsdp into (inter, intra);
# with fsdp_sub == 1 (default) this collapses to plain fsdp sharding.
FSDP_AXES: Tuple[str, ...] = (FSDP_AXIS, FSDP_SUB_AXIS)


@dataclass(frozen=True)
class MeshTopology:
    """Resolved topology: the Mesh plus convenience world-size accessors.

    Parity with the reference's group-size queries:
      get_data_parallel_world_size  -> dp_world_size (data*fsdp, like seq_dp composition)
      get_model_parallel_world_size -> tensor
      get_expert_parallel_world_size-> expert
      get_sequence_parallel_world_size -> seq
      get_pipe_parallel_world_size  -> pipe
    """

    mesh: Mesh
    sizes: Dict[str, int]

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.sizes.values())))

    @property
    def dp_world_size(self) -> int:
        """Number of distinct data shards = data * fsdp (ZeRO shards see distinct data)."""
        return self.sizes[DATA_AXIS] * self.fsdp_world_size

    @property
    def replica_world_size(self) -> int:
        return self.sizes[DATA_AXIS]

    @property
    def fsdp_world_size(self) -> int:
        return self.sizes[FSDP_AXIS] * self.sizes.get(FSDP_SUB_AXIS, 1)

    @property
    def fsdp_sub_size(self) -> int:
        """hpZ secondary-partition / MiCS sub-group size (1 = not factorized)."""
        return self.sizes.get(FSDP_SUB_AXIS, 1)

    @property
    def tp_world_size(self) -> int:
        return self.sizes[TENSOR_AXIS]

    @property
    def sp_world_size(self) -> int:
        return self.sizes[SEQ_AXIS]

    @property
    def ep_world_size(self) -> int:
        return self.sizes[EXPERT_AXIS]

    @property
    def pp_world_size(self) -> int:
        return self.sizes[PIPE_AXIS]

    # ------------------------------------------------------------------ #

    def batch_spec(self, extra: Sequence[Optional[str]] = ()) -> P:
        """PartitionSpec for a [batch, ...] array: batch over (data, fsdp), optionally
        sequence dim over seq axis: batch_spec([SEQ_AXIS]) -> P(('data','fsdp'),'seq')."""
        return P(BATCH_AXES, *extra)

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def build_topology(config: Optional[MeshConfig] = None,
                   devices: Optional[List[jax.Device]] = None) -> MeshTopology:
    """Build the device mesh from config.

    Device order: ``jax.devices()`` order, reshaped so inner (trailing) mesh axes map
    to adjacent devices — on real TPU slices adjacent device ids share ICI links, so
    tensor/seq/expert collectives (latency sensitive, per-layer) ride the fastest
    links while pipe (outermost) may span DCN. This mirrors the reference's axis
    nesting in ``PipeModelDataParallelTopology`` (``runtime/pipe/topology.py:244``).
    """
    config = config or MeshConfig()
    devices = devices if devices is not None else jax.devices()
    sizes = config.resolve(len(devices))
    order = tuple(config.axis_order)
    if FSDP_SUB_AXIS not in order and FSDP_AXIS in order:
        # accept pre-hpZ six-axis orders
        i = order.index(FSDP_AXIS)
        order = order[:i + 1] + (FSDP_SUB_AXIS,) + order[i + 1:]
    if set(order) != set(ALL_AXES):
        raise ValueError(f"mesh.axis_order must be a permutation of {ALL_AXES}, got {order}")
    shape = tuple(sizes[a] for a in order)
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, order)
    logger.info(f"mesh topology: {dict(zip(order, shape))} over {len(devices)} devices")
    return MeshTopology(mesh=mesh, sizes=sizes)


# --------------------------------------------------------------------------- #
# Global topology registry (parity: module-level groups in utils/groups.py)
# --------------------------------------------------------------------------- #

_TOPOLOGY: Optional[MeshTopology] = None


def set_topology(topo: MeshTopology) -> MeshTopology:
    global _TOPOLOGY
    _TOPOLOGY = topo
    return topo


def get_topology() -> MeshTopology:
    global _TOPOLOGY
    if _TOPOLOGY is None:
        _TOPOLOGY = build_topology()
    return _TOPOLOGY


def reset_topology():
    global _TOPOLOGY
    _TOPOLOGY = None
