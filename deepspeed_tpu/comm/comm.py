"""Backend-agnostic collectives API with profiling.

Parity: ``deepspeed/comm/comm.py`` — the module-level collective API
(``all_reduce``, ``all_gather_into_tensor``, ``reduce_scatter_tensor``,
``all_to_all_single``, ``barrier``, ...), each wrapped by a ``timed_op``-style
profiler (``comm/comm.py:101``), plus ``init_distributed`` (``comm/comm.py:604``).

TPU translation: the collectives here are the *inside-jit* primitives
(``jax.lax.psum`` etc.) used from ``shard_map``-ped code; axis names replace process
groups. Since an op inside jit cannot be wall-clocked individually, the comms logger
records at trace time (op, bytes, axis) and derives algorithmic/bus bandwidth from
the XLA profiler or from whole-step timing — see ``CommsLogger.calc_bw_log``
(parity: ``deepspeed/utils/comms_logging.py:34``).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.logging import CommsLogger, get_comms_logger
from deepspeed_tpu.utils.logging import logger

AxisName = Union[str, Sequence[str]]


def _leaf_bytes(tree: Any) -> int:
    return sum(getattr(x, "size", 0) * getattr(getattr(x, "dtype", None), "itemsize", 0)
               for x in jax.tree_util.tree_leaves(tree))


def timed_op(op_name: str):
    """Record collective call metadata at trace time (parity: comm.py:101 timed_op)."""

    def decorator(fn):

        @functools.wraps(fn)
        def wrapper(tensor, axis_name, *args, **kwargs):
            clog = get_comms_logger()
            if clog.enabled:
                clog.record(op_name, _leaf_bytes(tensor), axis_name,
                            kwargs.get("log_name", None))
            kwargs.pop("log_name", None)
            return fn(tensor, axis_name, *args, **kwargs)

        return wrapper

    return decorator


# --------------------------------------------------------------------------- #
# In-jit collectives (used from shard_map-ped code; axis name = mesh axis)
# --------------------------------------------------------------------------- #


@timed_op("all_reduce")
def all_reduce(tensor, axis_name: AxisName, op: str = "sum"):
    """Parity: ``deepspeed.comm.all_reduce``. op in {sum, avg, max, min}."""
    if op == "sum":
        return lax.psum(tensor, axis_name)
    if op in ("avg", "mean"):
        return lax.pmean(tensor, axis_name)
    if op == "max":
        return lax.pmax(tensor, axis_name)
    if op == "min":
        return lax.pmin(tensor, axis_name)
    raise ValueError(f"unsupported reduce op {op}")


@timed_op("all_gather_into_tensor")
def all_gather(tensor, axis_name: AxisName, axis: int = 0, tiled: bool = True):
    """Parity: ``deepspeed.comm.all_gather_into_tensor`` (flat concat layout)."""
    return lax.all_gather(tensor, axis_name, axis=axis, tiled=tiled)


@timed_op("reduce_scatter_tensor")
def reduce_scatter(tensor, axis_name: AxisName, axis: int = 0, tiled: bool = True):
    """Parity: ``deepspeed.comm.reduce_scatter_tensor``."""
    return lax.psum_scatter(tensor, axis_name, scatter_dimension=axis, tiled=tiled)


@timed_op("all_to_all_single")
def all_to_all(tensor, axis_name: AxisName, split_axis: int, concat_axis: int, tiled: bool = True):
    """Parity: ``deepspeed.comm.all_to_all_single``."""
    return lax.all_to_all(tensor, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


@timed_op("broadcast")
def broadcast(tensor, axis_name: AxisName, src: int = 0):
    """Parity: ``deepspeed.comm.broadcast``: take src's shard on the axis."""
    # All devices compute the same selection; psum of masked value broadcasts src.
    idx = lax.axis_index(axis_name)
    mask = (idx == src).astype(tensor.dtype)
    return lax.psum(tensor * mask, axis_name)


@timed_op("ppermute")
def ppermute(tensor, axis_name: AxisName, perm):
    """Ring shift / send-recv analog (parity: ``deepspeed.comm.send/recv`` pairs and
    ``runtime/pipe/p2p.py``); perm is a list of (src, dst) pairs."""
    return lax.ppermute(tensor, axis_name, perm)


def ring_shift(tensor, axis_name: str, shift: int = 1):
    """Shift shards around the ring by `shift` (positive = to higher index)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return ppermute(tensor, axis_name, perm)


def send_recv(tensor, src: int, dst: int, axis_name: AxisName):
    """Static point-to-point transfer: ``dst`` receives ``src``'s value; every
    other rank receives zeros (ppermute semantics).

    Parity: ``deepspeed.comm.send``/``recv`` and ``runtime/pipe/p2p.py``. Under
    SPMD there is no one-sided P2P — a send/recv PAIR is one collective
    ``ppermute`` with the static (src, dst) route, which is exactly how the
    reference's pipeline uses p2p (stage -> stage+1). All ranks must call this
    with the same (src, dst)."""
    return ppermute(tensor, axis_name, [(src, dst)])


def send(tensor, dst: int, axis_name: AxisName, *, src: int):
    """Reference-shaped alias of :func:`send_recv`. SPMD has no implicit
    "caller" rank, so the sender must be named explicitly — omitting ``src``
    is a TypeError rather than silently routing rank 0's data."""
    return send_recv(tensor, src, dst, axis_name)


def recv(tensor_like, src: int, axis_name: AxisName, *, dst: int):
    """Reference-shaped alias of :func:`send_recv`; ``dst`` (the receiver)
    must be named explicitly (see :func:`send`)."""
    return send_recv(tensor_like, src, dst, axis_name)


# reference-spelled aliases (deepspeed.comm API names; comm.py:246-330);
# **kwargs forward timed_op extras like log_name
def all_gather_into_tensor(tensor, axis_name: AxisName, axis: int = 0, **kw):
    """Parity alias: ``deepspeed.comm.all_gather_into_tensor``."""
    return all_gather(tensor, axis_name, axis=axis, tiled=True, **kw)


def reduce_scatter_tensor(tensor, axis_name: AxisName, axis: int = 0, **kw):
    """Parity alias: ``deepspeed.comm.reduce_scatter_tensor``."""
    return reduce_scatter(tensor, axis_name, axis=axis, tiled=True, **kw)


def all_to_all_single(tensor, axis_name: AxisName, split_axis: int = 0,
                      concat_axis: int = 0, **kw):
    """Parity alias: ``deepspeed.comm.all_to_all_single``."""
    return all_to_all(tensor, axis_name, split_axis, concat_axis, tiled=True, **kw)


def axis_index(axis_name: AxisName):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return lax.psum(1, axis_name)


# --------------------------------------------------------------------------- #
# Host-level (outside jit) helpers
# --------------------------------------------------------------------------- #


def barrier():
    """Cross-process barrier (parity: ``deepspeed.comm.barrier``)."""
    if jax.process_count() > 1:
        # effectful global sync across hosts
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu.barrier")


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


_INITIALIZED = False


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: Optional[str] = None,
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout: Optional[float] = None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config: Optional[dict] = None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Multi-host rendezvous. Parity: ``deepspeed/comm/comm.py:604 init_distributed``
    including MPI/env discovery (:673); on TPU pods ``jax.distributed.initialize``
    autodetects coordinator/process ids from the TPU metadata server, so explicit env
    is only needed off-cloud (COORDINATOR_ADDRESS / RANK / WORLD_SIZE)."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    # Scheduler env discovery (parity: mpi_discovery, reference comm.py:673):
    # srun/mpirun assign ranks through their own variables; fold them into the
    # RANK/WORLD_SIZE contract the rest of the stack reads. SLURM vars are
    # only trusted inside an srun step (SLURM_STEP_ID): a plain `python
    # train.py` inside an sbatch allocation inherits SLURM_NTASKS but is a
    # single process — folding it in would make a previously-working script
    # wait forever for peers.
    env_rank = os.environ.get("RANK")
    env_world = os.environ.get("WORLD_SIZE")
    if auto_mpi_discovery:
        in_srun_step = os.environ.get("SLURM_STEP_ID") is not None
        rank_vars = ["OMPI_COMM_WORLD_RANK", "PMI_RANK"]
        world_vars = ["OMPI_COMM_WORLD_SIZE", "PMI_SIZE"]
        if in_srun_step:
            rank_vars.insert(0, "SLURM_PROCID")
            world_vars.insert(0, "SLURM_NTASKS")
        for var in rank_vars:
            if env_rank is None and os.environ.get(var) is not None:
                env_rank = os.environ[var]
        for var in world_vars:
            if env_world is None and os.environ.get(var) is not None:
                env_world = os.environ[var]
    in_multiproc = (world_size > 1 or int(env_world or "1") > 1
                    or os.environ.get("COORDINATOR_ADDRESS"))
    if in_multiproc:
        kwargs = {}
        coord = os.environ.get("COORDINATOR_ADDRESS")
        if coord is None and os.environ.get("MASTER_ADDR"):
            coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', distributed_port)}"
        if coord:
            kwargs["coordinator_address"] = coord
        if rank >= 0 or env_rank is not None:
            kwargs["process_id"] = rank if rank >= 0 else int(env_rank)
        if world_size > 0 or env_world is not None:
            kwargs["num_processes"] = world_size if world_size > 0 else int(env_world)
        if verbose:
            logger.info(f"init_distributed: jax.distributed.initialize({kwargs})")
        jax.distributed.initialize(**kwargs)
    _INITIALIZED = True


def configure(config=None, enabled: Optional[bool] = None, prof_all: Optional[bool] = None,
              prof_ops: Optional[list] = None, verbose: Optional[bool] = None, debug=None):
    """Configure the comms logger (parity: ``deepspeed.comm.configure``,
    called from ``DeepSpeedEngine.__init__`` engine.py:247)."""
    clog = get_comms_logger()
    if config is not None and getattr(config, "comms_logger", None) is not None:
        cc = config.comms_logger
        clog.configure(enabled=cc.enabled, prof_all=cc.prof_all,
                       prof_ops=list(cc.prof_ops), verbose=cc.verbose)
    clog.configure(enabled=enabled, prof_all=prof_all, prof_ops=prof_ops, verbose=verbose)


def log_summary(show_straggler: bool = False, world_size: Optional[int] = None):
    """Print per-op communication summary (parity: ``comm/comm.py:422``).

    ``world_size`` scales the busbw factors; defaults to the active mesh topology's
    world size."""
    get_comms_logger().log_summary(show_straggler=show_straggler, world_size=world_size)
