"""Communication logger.

Parity: ``deepspeed/utils/comms_logging.py`` — ``CommsLogger`` (:67) and
``calc_bw_log`` (:34). On TPU, collectives run inside jit so per-op wall timing is
not observable from Python; instead we record per-call (op, bytes, axis) at trace
time and, when the user provides measured latencies (e.g. from the XLA profiler or
whole-step timing), derive algorithmic and bus bandwidth with the same formulas the
reference uses (allreduce busbw factor 2(n-1)/n, allgather/rs (n-1)/n).
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int) -> tuple:
    """Return (msg_size, algbw GB/s, busbw GB/s). Parity: comms_logging.py:34."""
    duration_s = max(duration_s, 1e-12)
    if comm_op in ("all_to_all_single", "all_to_all"):
        algbw = size_bytes / duration_s
        busbw = algbw * ((n - 1) / max(n, 1))
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor", "all_gather_object"):
        size_bytes = size_bytes * n
        algbw = size_bytes / duration_s
        busbw = algbw * ((n - 1) / max(n, 1))
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        algbw = size_bytes / duration_s
        busbw = algbw * (2 * (n - 1) / max(n, 1))
    else:  # pt2pt, broadcast, ppermute
        algbw = size_bytes / duration_s
        busbw = algbw
    return size_bytes, algbw / 1e9, busbw / 1e9


class CommsLogger:
    """Records collective call sites; parity: ``CommsLogger`` comms_logging.py:67."""

    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.prof_ops: List[str] = []
        # op -> msg_size -> [count, total_bytes, latencies...]
        self.comms_dict: Dict[str, Dict[int, List]] = defaultdict(lambda: defaultdict(lambda: [0, 0, []]))

    def configure(self, enabled=None, verbose=None, prof_all=None, prof_ops=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops

    def _should_log(self, op_name: str, log_name: Optional[str]) -> bool:
        if not self.enabled:
            return False
        if self.prof_all:
            return True
        name = log_name or op_name
        return name in self.prof_ops or op_name in self.prof_ops

    def record(self, op_name: str, size_bytes: int, axis_name: Any = None,
               log_name: Optional[str] = None, duration_s: Optional[float] = None):
        if not self._should_log(op_name, log_name):
            return
        rec = self.comms_dict[log_name or op_name][size_bytes]
        rec[0] += 1
        rec[1] += size_bytes
        if duration_s is not None:
            rec[2].append(duration_s)
        if self.verbose:
            logger.info(f"comm op: {op_name} | axis: {axis_name} | msg size: {size_bytes}")

    def append(self, record_name: str, latency: float, msg_size: int):
        """Direct record with measured latency (host-level collectives).
        Parity: ``CommsLogger.append`` (comms_logging.py)."""
        self.record(record_name, msg_size, duration_s=latency)

    def log_summary(self, show_straggler: bool = False, world_size: Optional[int] = None):
        if world_size is None:
            try:
                from deepspeed_tpu.comm.mesh import get_topology
                world_size = get_topology().world_size
            except Exception:
                world_size = 1
        lines = [f"{'Op':<28}{'MsgSize':>14}{'Count':>8}{'TotalBytes':>16}{'AvgLat(ms)':>12}"
                 f"{'algbw(GB/s)':>12}{'busbw(GB/s)':>12}"]
        for op, by_size in sorted(self.comms_dict.items()):
            for size, (count, total, lats) in sorted(by_size.items()):
                if lats:
                    avg = sum(lats) / len(lats)
                    _, algbw, busbw = calc_bw_log(op, size, avg, world_size)
                    lines.append(f"{op:<28}{size:>14}{count:>8}{total:>16}{avg*1e3:>12.3f}"
                                 f"{algbw:>12.2f}{busbw:>12.2f}")
                else:
                    lines.append(f"{op:<28}{size:>14}{count:>8}{total:>16}{'n/a':>12}{'n/a':>12}{'n/a':>12}")
        log_dist("\n".join(lines), ranks=[0])

    def reset(self):
        self.comms_dict.clear()


_LOGGER: Optional[CommsLogger] = None


def get_comms_logger() -> CommsLogger:
    global _LOGGER
    if _LOGGER is None:
        _LOGGER = CommsLogger()
    return _LOGGER
