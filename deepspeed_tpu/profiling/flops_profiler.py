"""Flops profiler: per-module flops/MACs/params breakdown + end-to-end numbers.

Parity: ``deepspeed/profiling/flops_profiler/profiler.py:28 FlopsProfiler``.
The reference monkey-patches ``torch.nn.functional`` and installs nn.Module hooks
to attribute MACs and latency to each module in the tree. The TPU-native analog:

  - **per-module attribution** via ``flax.linen.intercept_methods`` during an
    abstract (``jax.eval_shape``) trace — no device compute, analytic MAC formulas
    per layer type (the same Dense/Conv/Norm formulas the reference applies to
    ``F.linear``/``F.conv``/``F.layer_norm``);
  - **end-to-end flops** from the compiled computation's XLA ``cost_analysis()``
    (exact, fusion-aware — strictly better than summed analytic counts);
  - **latency / throughput / MFU** from a timed execution of the jitted function.

Per-module *latency* is the one reference feature with no XLA equivalent (modules
are fused away inside one program); the per-module table reports flops/MACs/params
and the end-to-end block reports measured latency, tput and MFU.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _numel(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _shape_of(x):
    return tuple(getattr(x, "shape", ()))


# --------------------------------------------------------------------------- #
# Analytic MACs per flax layer type (parity: the _FUNCS patch table,
# profiler.py "MODULE_HOOK_MAPPING" / functional patches)
# --------------------------------------------------------------------------- #

def _dense_macs(mod, args, out) -> int:
    x = args[0]
    in_f = int(x.shape[-1])
    return _numel(_shape_of(out)) * in_f


def _dense_general_macs(mod, args, out) -> int:
    x = args[0]
    axis = mod.axis if isinstance(mod.axis, (tuple, list)) else (mod.axis,)
    contracted = 1
    for ax in axis:
        contracted *= int(x.shape[ax])
    return _numel(_shape_of(out)) * contracted


def _conv_macs(mod, args, out) -> int:
    x = args[0]
    in_f = int(x.shape[-1])
    k = _numel(mod.kernel_size)
    groups = int(getattr(mod, "feature_group_count", 1) or 1)
    return _numel(_shape_of(out)) * k * in_f // groups


def _norm_flops(mod, args, out) -> int:
    return 5 * _numel(_shape_of(args[0]))


def _embed_macs(mod, args, out) -> int:
    return 0  # gather only


_MAC_FNS: Dict[str, Callable] = {
    "Dense": _dense_macs,
    "DenseGeneral": _dense_general_macs,
    "Conv": _conv_macs,
    "ConvTranspose": _conv_macs,
    "Embed": _embed_macs,
}
_FLOP_FNS: Dict[str, Callable] = {
    "LayerNorm": _norm_flops,
    "RMSNorm": _norm_flops,
    "GroupNorm": _norm_flops,
    "BatchNorm": _norm_flops,
}


@dataclass
class ModuleProfile:
    path: str
    type_name: str
    macs: int = 0
    flops: int = 0
    params: int = 0
    calls: int = 0
    children: List[str] = field(default_factory=list)


class FlopsProfiler:
    """Parity: ``FlopsProfiler`` (``profiling/flops_profiler/profiler.py:28``).

    Usage (matches the reference's start/stop/print discipline)::

        prof = FlopsProfiler(config=cfg.flops_profiler)
        prof.start_profile(module, variables, batch)   # abstract trace
        prof.measure(fn, *args)                        # optional: timed compiled run
        prof.print_model_profile()
        prof.end_profile()
    """

    def __init__(self, config=None):
        self.config = config
        self.modules: Dict[str, ModuleProfile] = {}
        self.total_macs = 0
        self.total_flops_analytic = 0
        self.total_params = 0
        self.xla_flops: Optional[float] = None
        self.latency_s: Optional[float] = None
        self.started = False

    # -------------------------------------------------------------- #
    # abstract per-module trace
    # -------------------------------------------------------------- #

    def start_profile(self, module=None, variables=None, batch=None, **apply_kwargs):
        """Trace ``module.apply(variables, batch)`` abstractly, attributing MACs
        to every submodule (parity: start_profile + module hooks)."""
        self.modules = {}
        self.total_macs = 0
        self.total_flops_analytic = 0
        self.total_params = 0
        self.started = True
        if module is None:
            return

        import flax.linen as nn

        profiles = self.modules

        def interceptor(next_fn, args, kwargs, context):
            mod = context.module
            is_call = context.method_name == "__call__"
            path = "/".join(str(p) for p in mod.path) or "<root>"
            out = next_fn(*args, **kwargs)
            if not is_call:
                return out
            tname = type(mod).__name__
            prof = profiles.get(path)
            if prof is None:
                prof = profiles[path] = ModuleProfile(path=path, type_name=tname)
                parent = "/".join(path.split("/")[:-1]) or "<root>"
                if parent != path and parent in profiles:
                    profiles[parent].children.append(path)
            prof.calls += 1
            try:
                if tname in _MAC_FNS:
                    macs = int(_MAC_FNS[tname](mod, args, out))
                    prof.macs += macs
                    prof.flops += 2 * macs
                elif tname in _FLOP_FNS:
                    prof.flops += int(_FLOP_FNS[tname](mod, args, out))
            except Exception:  # defensive: unknown arg structures
                pass
            return out

        def run(v, b):
            with nn.intercept_methods(interceptor):
                return module.apply(v, b, **apply_kwargs)

        jax.eval_shape(run, variables, batch)

        # roll leaf counts up the tree and count params
        for path, prof in sorted(self.modules.items(), key=lambda kv: -kv[0].count("/")):
            parent = "/".join(path.split("/")[:-1]) or "<root>"
            if parent != path and parent in self.modules:
                self.modules[parent].macs += prof.macs
                self.modules[parent].flops += prof.flops
        root = self.modules.get("<root>")
        if root is not None:
            self.total_macs = root.macs
            self.total_flops_analytic = root.flops
        else:
            self.total_macs = sum(p.macs for p in self.modules.values()
                                  if "/" not in p.path)
            self.total_flops_analytic = sum(p.flops for p in self.modules.values()
                                            if "/" not in p.path)
        if variables is not None:
            params = variables.get("params", variables) if isinstance(variables, dict) else variables
            self.total_params = sum(_numel(_shape_of(x))
                                    for x in jax.tree_util.tree_leaves(params))

    # -------------------------------------------------------------- #
    # compiled end-to-end measurement
    # -------------------------------------------------------------- #

    def measure(self, fn: Callable, *args, n_iters: int = 3) -> Dict[str, float]:
        """Compile ``fn(*args)``, read XLA cost analysis, time execution.

        Parity: the reference's latency hooks + ``get_total_duration``; here the
        flop count comes from the compiler itself."""
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = jitted.lower(*args).compile()
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, list):  # older jax returns [dict]
                ca = ca[0]
            self.xla_flops = float(ca.get("flops", 0.0))
        except Exception:
            self.xla_flops = None
        out = compiled(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n_iters):
            out = compiled(*args)
        jax.block_until_ready(out)
        self.latency_s = (time.perf_counter() - t0) / n_iters
        return {"flops": self.xla_flops or 0.0, "latency_s": self.latency_s}

    # -------------------------------------------------------------- #
    # accessors (API parity)
    # -------------------------------------------------------------- #

    def get_total_flops(self, as_string: bool = False):
        total = self.xla_flops if self.xla_flops else self.total_flops_analytic
        return _num_to_string(total) if as_string else total

    def get_total_macs(self, as_string: bool = False):
        return _num_to_string(self.total_macs) if as_string else self.total_macs

    def get_total_params(self, as_string: bool = False):
        return _num_to_string(self.total_params) if as_string else self.total_params

    def get_total_duration(self, as_string: bool = False):
        d = self.latency_s or 0.0
        return f"{d * 1e3:.2f} ms" if as_string else d

    def stop_profile(self):
        self.started = False

    def end_profile(self):
        self.modules = {}
        self.started = False

    # -------------------------------------------------------------- #
    # monitor events (the profile lands in the same sink as the
    # pipeline stats — train/flops/* beside train/pipeline/*)
    # -------------------------------------------------------------- #

    def events(self, step: int = 0, top_modules: int = 8):
        """Monitor-ready ``(name, value, step)`` tuples: end-to-end totals
        plus the ``top_modules`` heaviest modules by MACs (leaf attribution,
        mirroring the printed table). Call BEFORE ``end_profile`` (which
        drops the per-module tree). The engine routes these through
        ``MonitorMaster`` at the profile step, so flops sit next to the
        pipeline phase stats in every backend instead of print-only."""
        ev = [
            ("train/flops/params", float(self.total_params), step),
            ("train/flops/macs", float(self.total_macs), step),
            ("train/flops/flops", float(self.get_total_flops()), step),
        ]
        if self.xla_flops is not None:
            ev.append(("train/flops/flops_xla", float(self.xla_flops), step))
        if self.latency_s:
            ev.append(("train/flops/latency_ms", self.latency_s * 1e3, step))
            flops = self.xla_flops or self.total_flops_analytic
            if flops:
                ev.append(("train/flops/achieved_tflops",
                           flops / self.latency_s / 1e12, step))
        ranked = sorted((p for p in self.modules.values()
                         if p.path != "<root>" and (p.macs or p.flops)),
                        key=lambda p: (-p.macs, -p.flops, p.path))
        for prof in ranked[:max(0, int(top_modules))]:
            ev.append((f"train/flops/module/{prof.path}",
                       float(prof.flops), step))
        return ev

    # -------------------------------------------------------------- #
    # report
    # -------------------------------------------------------------- #

    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None):
        """Parity: ``print_model_profile`` — summary block + per-module tree."""
        lines = []
        lines.append("-" * 72)
        lines.append("DeepSpeed-TPU Flops Profiler")
        lines.append("-" * 72)
        lines.append(f"profile step:                   {profile_step}")
        lines.append(f"params:                         {self.get_total_params(True)}")
        lines.append(f"MACs (analytic):                {self.get_total_macs(True)}")
        lines.append(f"flops (analytic):               {_num_to_string(self.total_flops_analytic)}")
        if self.xla_flops is not None:
            lines.append(f"flops (XLA cost analysis):      {_num_to_string(self.xla_flops)}")
        if self.latency_s:
            lines.append(f"latency:                        {self.latency_s * 1e3:.2f} ms")
            flops = self.xla_flops or self.total_flops_analytic
            if flops:
                lines.append(f"achieved:                       {flops / self.latency_s / 1e12:.2f} TFLOPS")
        if detailed and self.modules:
            lines.append("")
            lines.append(f"{'module':<44} {'params':>9} {'MACs':>9} {'flops':>9}")
            for path in sorted(self.modules):
                depth = path.count("/") + 1
                if module_depth >= 0 and depth > module_depth:
                    continue
                p = self.modules[path]
                indent = "  " * (depth - 1)
                name = f"{indent}{path.split('/')[-1]} ({p.type_name})"
                lines.append(f"{name:<44} {_num_to_string(p.params):>9} "
                             f"{_num_to_string(p.macs):>9} {_num_to_string(p.flops):>9}")
        lines.append("-" * 72)
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report + "\n")
        else:
            logger.info("\n" + report)
        return report


def _num_to_string(num) -> str:
    num = float(num)
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= div:
            return f"{num / div:.2f} {unit}"
    return f"{num:.0f}"


def get_model_profile(module, batch, variables=None, rng=None,
                      measure: bool = False) -> Tuple[float, int, int]:
    """One-shot convenience (parity: ``get_model_profile`` profiler.py).

    Returns ``(flops, macs, params)`` for ``module`` applied to ``batch``."""
    if variables is None:
        from deepspeed_tpu.utils.rng import default_rng
        rng = rng if rng is not None else default_rng()
        abstract = jax.eval_shape(module.init, rng, batch)
        variables = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype), abstract)
    prof = FlopsProfiler()
    prof.start_profile(module, variables, batch)
    if measure:
        prof.measure(lambda v, b: module.apply(v, b), variables, batch)
    prof.end_profile_keep_totals = True
    return prof.get_total_flops(), prof.get_total_macs(), prof.get_total_params()
