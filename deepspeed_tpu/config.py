"""Typed configuration tree for deepspeed_tpu.

One JSON/dict configures every feature, mirroring the reference's single-dict
philosophy (``deepspeed/runtime/config.py:94`` ``DeepSpeedConfig`` and the pydantic
``DeepSpeedConfigModel`` at ``deepspeed/runtime/config_utils.py:16``).  We keep the
same key spellings (``train_batch_size``, ``zero_optimization.stage``,
``bf16.enabled`` ...) so existing DeepSpeed configs parse unchanged, but the tree is
plain dataclasses: no pydantic dependency, scientific-notation string coercion, alias
and deprecated-key migration, and central batch-size resolution
(micro x GAS x dp == train_batch_size, see ``_batch_assertion`` in the reference).

TPU-specific additions live under the ``mesh`` key: device-mesh geometry replaces the
reference's process-group plumbing (``deepspeed/utils/groups.py``).
"""

from __future__ import annotations

import copy
import dataclasses
import json
import os
from dataclasses import dataclass, field, fields
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple, Union

from deepspeed_tpu.utils.logging import logger


class ConfigError(ValueError):
    """Raised on invalid / inconsistent config input."""


def _coerce_number(value: Any, target: type) -> Any:
    """Coerce scientific-notation strings and floats to the target numeric type.

    The reference accepts ``"1e-5"`` for floats and ``1e9``/"1e9" for ints
    (``ScientificNotationEncoder`` / ``pp_int`` in ``runtime/config_utils.py``).
    """
    if target is int:
        if isinstance(value, bool):
            raise ConfigError(f"expected int, got bool {value!r}")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
        if isinstance(value, str):
            try:
                f = float(value)
            except ValueError:
                raise ConfigError(f"expected int, got {value!r}") from None
            if f.is_integer():
                return int(f)
        raise ConfigError(f"expected int, got {value!r}")
    if target is float:
        if isinstance(value, bool):
            raise ConfigError(f"expected float, got bool {value!r}")
        if isinstance(value, (int, float)):
            return float(value)
        if isinstance(value, str):
            try:
                return float(value)
            except ValueError:
                raise ConfigError(f"expected float, got {value!r}") from None
        raise ConfigError(f"expected float, got {value!r}")
    if target is bool:
        if isinstance(value, bool):
            return value
        raise ConfigError(f"expected bool, got {value!r}")
    return value


class ConfigModel:
    """Mixin giving dataclasses ``from_dict`` with key validation and coercion.

    Parity: ``DeepSpeedConfigModel`` (reference ``runtime/config_utils.py:16``) —
    extra-key warnings, field aliases via metadata, deprecated-key migration.
    """

    # mapping of deprecated/alias key -> canonical field name
    _aliases: Dict[str, str] = {}
    # mapping of deprecated key -> (canonical field name, value migration fn);
    # used where the legacy value shape differs (e.g. bool -> sub-config dict)
    _migrations: Dict[str, Tuple[str, Any]] = {}

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]], path: str = "") -> "ConfigModel":
        data = dict(data or {})
        kwargs: Dict[str, Any] = {}
        field_map = {f.name: f for f in fields(cls)}  # type: ignore[arg-type]
        for alias, canonical in cls._aliases.items():
            if alias in data:
                if canonical in data:
                    raise ConfigError(f"{path}: both '{alias}' and '{canonical}' set")
                logger.warning(f"config key '{path}.{alias}' is deprecated; use '{canonical}'")
                data[canonical] = data.pop(alias)
        for legacy, (canonical, migrate) in cls._migrations.items():
            if legacy in data:
                if canonical in data:
                    raise ConfigError(f"{path}: both '{legacy}' and '{canonical}' set")
                logger.warning(f"config key '{path}.{legacy}' is deprecated; use '{canonical}'")
                data[canonical] = migrate(data.pop(legacy))
        for key, value in data.items():
            if key not in field_map:
                logger.warning(f"unknown config key '{path}.{key}' ignored" if path else f"unknown config key '{key}' ignored")
                continue
            f = field_map[key]
            kwargs[key] = _convert_field(f, value, f"{path}.{key}" if path else key)
        obj = cls(**kwargs)  # type: ignore[call-arg]
        return obj

    def to_dict(self) -> Dict[str, Any]:
        def enc(v):
            if isinstance(v, Enum):
                return v.value
            if dataclasses.is_dataclass(v) and not isinstance(v, type):
                return {f.name: enc(getattr(v, f.name)) for f in fields(v)}
            if isinstance(v, (list, tuple)):
                return [enc(x) for x in v]
            if isinstance(v, dict):
                return {k: enc(x) for k, x in v.items()}
            return v
        return enc(self)  # type: ignore[return-value]


def _convert_field(f: dataclasses.Field, value: Any, path: str) -> Any:
    t = f.type
    origin = getattr(t, "__origin__", None)
    # resolve string annotations lazily (from __future__ annotations)
    if isinstance(t, str):
        t = eval(t, globals())  # noqa: S307 - annotations are module-local
        origin = getattr(t, "__origin__", None)
    if origin is Union:
        args = [a for a in t.__args__ if a is not type(None)]
        if value is None:
            return None
        t = args[0]
        origin = getattr(t, "__origin__", None)
    if isinstance(t, type) and issubclass(t, ConfigModel):
        if isinstance(value, t):
            return value
        if not isinstance(value, dict):
            raise ConfigError(f"{path}: expected dict, got {value!r}")
        return t.from_dict(value, path)
    if isinstance(t, type) and issubclass(t, Enum):
        try:
            return t(value)
        except ValueError as e:
            raise ConfigError(f"{path}: {e}") from e
    if t in (int, float, bool):
        try:
            return _coerce_number(value, t)
        except ConfigError as e:
            raise ConfigError(f"{path}: {e}") from e
    if origin in (list, tuple):
        return list(value) if origin is list else tuple(value)
    return value


# --------------------------------------------------------------------------- #
# Precision
# --------------------------------------------------------------------------- #


@dataclass
class FP16Config(ConfigModel):
    """Parity: reference ``fp16`` block (``runtime/config.py`` get_fp16_enabled etc.).

    On TPU bf16 is the native mixed-precision mode; fp16 + dynamic loss scaling is
    implemented for capability parity but off by default.
    """

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 -> dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0.0


@dataclass
class BF16Config(ConfigModel):
    """Parity: reference ``bf16`` block; ``accumulate_grads_via_hooks`` analog is moot
    (grad accumulation is a jitted scan on TPU)."""

    enabled: bool = False
    immediate_grad_update: bool = False


# --------------------------------------------------------------------------- #
# ZeRO
# --------------------------------------------------------------------------- #


class OffloadDeviceEnum(str, Enum):
    """Parity: ``runtime/zero/offload_config.py:12``."""

    none = "none"
    cpu = "cpu"
    nvme = "nvme"


@dataclass
class OffloadParamConfig(ConfigModel):
    """Parity: ``DeepSpeedZeroOffloadParamConfig`` (``offload_config.py:19``)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


@dataclass
class OffloadOptimizerConfig(ConfigModel):
    """Parity: ``DeepSpeedZeroOffloadOptimizerConfig`` (``offload_config.py:50``)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0
    # Delayed Param Update (ZeRO-Offload paper §5, DeepSpeed's DPU): run the
    # host optimizer for step N concurrently with device step N+1; host-flow
    # params apply one step late. Trades exact SGD semantics (one-step
    # staleness on the offloaded leaves) for step time ~= max(device, host)
    # instead of device + transfer + host.
    delayed_param_update: bool = False
    # Three-stage group pipeline inside the host step (docs/TRAINING.md
    # "Offloaded optimizer pipeline"): while group g runs its host kernel,
    # group g+1's grad D2H is in flight and group g-1's updated master is
    # already uploading/casting back. False restores the fully serial
    # fetch-all / step-all / upload-all step (identical math — the bench's
    # byte-equality baseline).
    overlap_step: bool = True
    # Worker threads for the host optimizer kernel (leaves are chunked and
    # stepped concurrently; both the native OpenMP kernels via ctypes and
    # numpy's vectorized inner loops release the GIL). 0 = auto
    # (min(4, cpu_count())).
    host_workers: int = 0
    # Leaves per pipeline group. 0 = buffer_count (the same sub-group sizing
    # the NVMe swapper uses, so grad fetches, kernel runs, and state swaps
    # all move through the pipeline in lock-step groups).
    group_size: int = 0
    # NVMe IO failure discipline (docs/ELASTICITY.md): bounded retries per
    # failed read/write (then the error SURFACES at the step), and a deadline
    # on AIO waits (0 = no deadline) so a dead disk hangs the step with a
    # clean IOTimeout instead of forever.
    io_retries: int = 2
    io_timeout_s: float = 0.0

    _aliases = {"delayed_update": "delayed_param_update"}


@dataclass
class ZeroConfig(ConfigModel):
    """Parity: ``DeepSpeedZeroConfig`` (reference ``runtime/zero/config.py:82``).

    On TPU the stages collapse into sharding policy (see
    ``deepspeed_tpu/runtime/zero/partition.py``):
      stage 0 -> replicated params + psum grads (plain DP)
      stage 1 -> optimizer states sharded over the fsdp axis
      stage 2 -> + gradients reduce-scattered (XLA emits reduce_scatter when the
                 optimizer shards are the only consumers)
      stage 3 -> + parameters sharded, allgathered on demand by the SPMD partitioner

    Bucket sizes become XLA all-gather/reduce-scatter combiner thresholds; the
    prefetch/persistence knobs become compiler-visible scheduling hints.
    """

    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    # Explicit ZeRO-3 collective schedule (runtime/zero/prefetch.py). None =
    # unscheduled (implicit XLA placement, bit-for-bit the pre-schedule path).
    # 0 = serial schedule (each wave's gather tied to its own input: gather-
    # then-compute, no lookahead); d >= 1 = gathers issued d waves ahead of
    # compute (double-buffered at d=1). With the schedule armed,
    # allgather_bucket_size / reduce_bucket_size become the real wave/bucket
    # byte bounds of the scheduled collectives instead of XLA combiner hints.
    stage3_prefetch_depth: Optional[int] = None
    stage3_gather_16bit_weights_on_model_save: bool = False
    stage3_module_granularity_threshold: int = 0
    zero_hpz_partition_size: int = 1  # hierarchical (secondary) partition size, ZeRO++
    zero_quantized_weights: bool = False  # qwZ
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False  # qgZ
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True
    round_robin_gradients: bool = False
    use_multi_rank_bucket_allreduce: bool = True
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    override_module_apply: bool = True

    _aliases = {
        "stage3_gather_fp16_weights_on_model_save": "stage3_gather_16bit_weights_on_model_save",
    }
    # Reference accepted `cpu_offload: true` booleans pre-offload_config
    # (runtime/zero/config.py deprecated fields); migrate to the dict form.
    _migrations = {
        "cpu_offload": ("offload_optimizer",
                        lambda v: {"device": "cpu"} if v is True else (v or None)),
        "cpu_offload_param": ("offload_param",
                              lambda v: {"device": "cpu"} if v is True else (v or None)),
    }

    def __post_init__(self):
        if not 0 <= self.stage <= 3:
            raise ConfigError(f"zero_optimization.stage must be in [0,3], got {self.stage}")
        if self.stage3_prefetch_depth is not None:
            if self.stage3_prefetch_depth < 0:
                raise ConfigError(
                    "zero_optimization.stage3_prefetch_depth must be >= 0 "
                    f"(or null to disable the schedule), got {self.stage3_prefetch_depth}")
            if self.stage != 3:
                raise ConfigError(
                    "zero_optimization.stage3_prefetch_depth requires stage 3 "
                    f"(params are not sharded at stage {self.stage})")


# --------------------------------------------------------------------------- #
# Optimizer / scheduler
# --------------------------------------------------------------------------- #


@dataclass
class OptimizerConfig(ConfigModel):
    """Parity: the ``optimizer`` block consumed by
    ``DeepSpeedEngine._configure_basic_optimizer`` (``runtime/engine.py:1258``).

    ``type`` is one of the registry names in ``deepspeed_tpu/ops`` (adam, adamw,
    lamb, lion, adagrad, sgd, onebitadam, zerooneadam, onebitlamb, muon)."""

    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)
    legacy_fusion: bool = False


@dataclass
class SchedulerConfig(ConfigModel):
    """Parity: ``scheduler`` block -> ``deepspeed_tpu/runtime/lr_schedules.py``
    (reference ``deepspeed/runtime/lr_schedules.py``)."""

    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


# --------------------------------------------------------------------------- #
# Activation checkpointing
# --------------------------------------------------------------------------- #


@dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """Parity: ``runtime/activation_checkpointing/checkpointing.py:1070 configure``.

    On TPU this maps to ``jax.checkpoint`` policies: ``partition_activations`` ->
    sharded remat saveables; ``cpu_checkpointing`` -> host offload of residuals
    (XLA memory_kind pinned_host); contiguous buffers are an XLA concern.
    """

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


# --------------------------------------------------------------------------- #
# Observability
# --------------------------------------------------------------------------- #


@dataclass
class CommsLoggerConfig(ConfigModel):
    """Parity: ``deepspeed/comm/config.py`` ``CommsConfig``."""

    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@dataclass
class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


@dataclass
class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


@dataclass
class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


@dataclass
class PrometheusConfig(ConfigModel):
    """Live telemetry endpoint (``monitor/export.py``,
    docs/OBSERVABILITY.md "Live telemetry"): a pull-based Prometheus-text
    snapshot of the latest monitor events, served from an embedded HTTP
    endpoint (``GET /metrics``) so a dashboard scrapes the run without
    touching CSV files. No reference analog — the reference's monitor is
    write-side only."""

    enabled: bool = False
    # bind address/port for the scrape endpoint; port 0 = OS-assigned
    # (read back from ``PrometheusExporter.port``)
    addr: str = "127.0.0.1"
    port: int = 0
    # metric-name prefix (``serve/frontend/queue_depth`` ->
    # ``<prefix>_serve_frontend_queue_depth``)
    prefix: str = "dstpu"
    # when set, close() writes a final ``metrics.prom`` snapshot under
    # ``output_path/job_name`` (the CSV convention)
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


@dataclass
class TraceConfig(ConfigModel):
    """Span tracing (``monitor/trace.py``, docs/OBSERVABILITY.md): a
    Perfetto-exportable timeline across the train/serve/offload/checkpoint
    pipelines plus a crash flight recorder. No direct reference analog — the
    reference leans on torch.profiler; here the async pipelines carry their
    own zero-sync span instrumentation. Also armable without config via the
    ``DSTPU_TRACE=<dir>`` env var (subprocess benches)."""

    enabled: bool = False
    # where trace_{pid}.json / trace_crash.json land; nonempty implies enabled
    dir: str = ""
    # spans retained per thread — bounded memory AND the flight-recorder
    # window a crash dump preserves
    ring_size: int = 16384
    # per-request serve/req/u<uid> lanes exported under their own track;
    # older (retired) requests recycle onto pooled serve/req/recycled/<k>
    # tracks so a long serving run's timeline stays bounded in rows
    req_lane_window: int = 64


@dataclass
class MonitorConfig(ConfigModel):
    """Monitor-subsystem knobs beyond the per-backend sections (which stay
    top-level for reference parity: ``tensorboard``/``wandb``/``csv_monitor``)."""

    trace: TraceConfig = field(default_factory=TraceConfig)


@dataclass
class FlopsProfilerConfig(ConfigModel):
    """Parity: ``profiling/config.py`` ``DeepSpeedFlopsProfilerConfig``."""

    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


# --------------------------------------------------------------------------- #
# Elasticity / autotuning
# --------------------------------------------------------------------------- #


@dataclass
class ElasticityConfig(ConfigModel):
    """Parity: ``elasticity/config.py`` ``ElasticityConfig``."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch_size: bool = True


@dataclass
class AutotuningConfig(ConfigModel):
    """Parity: ``autotuning/config.py``."""

    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    arg_mappings: Dict[str, str] = field(default_factory=dict)
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: int = 1024
    min_train_micro_batch_size_per_gpu: int = 1


# --------------------------------------------------------------------------- #
# Data efficiency / curriculum
# --------------------------------------------------------------------------- #


@dataclass
class CurriculumLearningConfig(ConfigModel):
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DataEfficiencyConfig(ConfigModel):
    """Parity: ``runtime/data_pipeline/config.py``."""

    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = field(default_factory=dict)
    data_routing: Dict[str, Any] = field(default_factory=dict)


# --------------------------------------------------------------------------- #
# Mesh (TPU-specific: replaces the reference's process-group plumbing)
# --------------------------------------------------------------------------- #


@dataclass
class MeshConfig(ConfigModel):
    """Device-mesh geometry.

    Axis sizes multiply to the total device count; -1 for ``data`` means "absorb the
    remainder" (like the reference deriving dp_world_size from
    world_size / (mp * ep * sp), ``utils/groups.py``).

    Axes (outer to inner; inner axes map to ICI-adjacent devices):
      pipe   - pipeline stages (DCN-spanning allowed)
      data   - pure data parallel (replicated params)
      fsdp   - ZeRO sharding axis (params/grads/opt states)
      expert - expert parallel (MoE all-to-all)
      seq    - sequence parallel (Ulysses / ring attention)
      tensor - tensor/model parallel
    """

    pipe: int = 1
    data: int = -1
    fsdp: int = 1
    fsdp_sub: int = 1  # hpZ secondary partition / MiCS sub-group (inner fsdp axis)
    expert: int = 1
    seq: int = 1
    tensor: int = 1
    # device order: "default" follows jax.devices(); on real slices XLA device order
    # is already ICI-contiguous in the trailing axes.
    axis_order: Tuple[str, ...] = ("pipe", "data", "fsdp", "fsdp_sub", "expert",
                                   "seq", "tensor")

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in ("pipe", "data", "fsdp", "fsdp_sub",
                                               "expert", "seq", "tensor")}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ConfigError(f"mesh: only one axis may be -1, got {wild}")
        fixed = 1
        for a, s in sizes.items():
            if s != -1:
                if s < 1:
                    raise ConfigError(f"mesh.{a} must be >= 1 or -1, got {s}")
                fixed *= s
        if wild:
            if n_devices % fixed != 0:
                raise ConfigError(f"mesh: {n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise ConfigError(f"mesh axes product {fixed} != device count {n_devices}")
        return sizes


# --------------------------------------------------------------------------- #
# Hybrid engine (RLHF) + progressive layer drop
# --------------------------------------------------------------------------- #


@dataclass
class HybridEngineConfig(ConfigModel):
    """Parity: ``hybrid_engine`` block (``runtime/hybrid_engine.py`` /
    ``runtime/config.py`` hybrid engine section)."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


@dataclass
class ProgressiveLayerDropConfig(ConfigModel):
    """Parity: ``progressive_layer_drop`` block (engine.py:1812 hook)."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


# --------------------------------------------------------------------------- #
# Training step-loop pipelining (docs/TRAINING.md)
# --------------------------------------------------------------------------- #


@dataclass
class TrainPipelineConfig(ConfigModel):
    """Async training step loop: prefetch-to-device input staging + the
    one-step-late metric drain. No reference analog — the reference's
    DataLoader workers pipeline collate only; here the staged batches are
    already device-resident and sharded. ``wall_clock_breakdown`` overrides
    the drain back to fully synchronous regardless of these knobs."""

    # Global batches staged ahead by the PrefetchLoader producer thread
    # (collate + curriculum/PLD + sharded device_put off the critical path).
    # 2 = classic double buffering. 0 = synchronous staging (no thread) —
    # identical math, every stage on the caller's thread.
    prefetch: int = 2


# --------------------------------------------------------------------------- #
# Checkpoint
# --------------------------------------------------------------------------- #


@dataclass
class RollingCheckpointConfig(ConfigModel):
    """Continuous rolling checkpoints on a step cadence (the spot/preemptible
    resume story, docs/ELASTICITY.md). No direct reference analog — the
    reference leaves the save cadence to user training loops; here the engine
    owns it so the cadence interleaves correctly with the async step loop
    (metric drain) and the offload pipeline (upload-lane quiesce)."""

    # save every N global steps through the configured checkpoint engine
    # (0 = disabled). Pair with ``engine: "async"`` so only the device
    # snapshot runs on the step loop's critical path.
    every_n_steps: int = 0
    # retention: newest K rolling tags survive pruning (the tag ``latest``
    # points at is never pruned)
    keep_last: int = 2
    # where the rolling tags live; REQUIRED when every_n_steps > 0
    save_dir: str = ""
    # bounded writer lag/backpressure: at most this many snapshots may be
    # queued-but-uncommitted before the NEXT save blocks until the oldest
    # commit lands — the queue can never grow without bound when the disk
    # is slower than the cadence
    max_pending: int = 1
    # tag names: f"{tag_prefix}{global_step}"
    tag_prefix: str = "rolling_step"


@dataclass
class CheckpointConfig(ConfigModel):
    """Parity: ``checkpoint`` block (``runtime/config.py`` checkpoint section) +
    checkpoint-engine choice (``runtime/checkpoint_engine/``)."""

    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    engine: str = "native"  # native | async
    # writer threads for the async engine (ignored by the native engine)
    writers: int = 2
    # bounded retry budget per checkpoint file write (transient IO failures
    # recover; the budget exhausting surfaces the error at commit)
    writer_retries: int = 2
    writer_backoff_s: float = 0.05
    # checksum shards against the tag's manifest on every load (the
    # ``verify=True`` path; per-call override via load_checkpoint(verify=))
    verify_load: bool = False
    rolling: RollingCheckpointConfig = field(
        default_factory=RollingCheckpointConfig)


# --------------------------------------------------------------------------- #
# Top-level config
# --------------------------------------------------------------------------- #


@dataclass
class DeepSpeedTPUConfig(ConfigModel):
    """The full config tree. Parity: ``DeepSpeedConfig`` (``runtime/config.py:94``)."""

    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None
    steps_per_print: int = 10
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    sparse_gradients: bool = False
    communication_data_type: Optional[str] = None
    seq_parallel_communication_data_type: str = "fp32"
    disable_allgather: bool = False
    dump_state: bool = False
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    seed: int = 42
    # The engine may alias (donate) the caller's model_parameters buffers into
    # its fp32 master state instead of copying — saves 4 bytes/param of HBM at
    # init for billion-parameter models, but the caller's tree is dead after
    # initialize(). Analog of the reference's ZeRO-3 taking ownership of module
    # params at zero.Init / engine wrap (partition_parameters.py).
    donate_model_parameters: bool = False

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = field(default_factory=FP16Config)
    bf16: BF16Config = field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = field(default_factory=ZeroConfig)
    activation_checkpointing: ActivationCheckpointingConfig = field(default_factory=ActivationCheckpointingConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = field(default_factory=CSVConfig)
    prometheus: PrometheusConfig = field(default_factory=PrometheusConfig)
    monitor: MonitorConfig = field(default_factory=MonitorConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    elasticity: ElasticityConfig = field(default_factory=ElasticityConfig)
    autotuning: AutotuningConfig = field(default_factory=AutotuningConfig)
    data_efficiency: DataEfficiencyConfig = field(default_factory=DataEfficiencyConfig)
    curriculum_learning: CurriculumLearningConfig = field(default_factory=CurriculumLearningConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    hybrid_engine: HybridEngineConfig = field(default_factory=HybridEngineConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = field(
        default_factory=ProgressiveLayerDropConfig)
    train_pipeline: TrainPipelineConfig = field(
        default_factory=TrainPipelineConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)

    # precision of gradient accumulation buffer (parity: data_types.grad_accum_dtype)
    data_types: Dict[str, Any] = field(default_factory=dict)

    # compression (parity: compression_training block, compression/config.py) —
    # raw dict, parsed by deepspeed_tpu.compression (dict-schema like the reference)
    compression_training: Optional[Dict[str, Any]] = None

    # Extra XLA compile options for the jitted train step (merged OVER the
    # ZeRO-bucket-derived combiner thresholds; TPU backend only). The config-
    # driven analog of the reference's env-var XLA/NCCL tuning surface — lets
    # a user pin e.g. {"xla_tpu_scoped_vmem_limit_kib": 65536} per run.
    xla_compile_options: Dict[str, Any] = field(default_factory=dict)

    _migrations = {"fp16_enabled": ("fp16", lambda v: {"enabled": bool(v)})}

    # ------------------------------------------------------------------ #

    @classmethod
    def load(cls, config: Union[str, Dict[str, Any], "DeepSpeedTPUConfig", None]) -> "DeepSpeedTPUConfig":
        if config is None:
            config = {}
        if isinstance(config, DeepSpeedTPUConfig):
            return config
        if isinstance(config, (str, os.PathLike)):
            with open(config, "r") as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise ConfigError(f"config must be a dict or a path to a JSON file, got {type(config)}")
        return cls.from_dict(copy.deepcopy(config))  # type: ignore[return-value]

    # ------------------------------------------------------------------ #
    # Batch resolution. Parity: reference _configure_train_batch_size /
    # _batch_assertion (runtime/config.py).
    # ------------------------------------------------------------------ #

    def resolve_batch(self, dp_world_size: int) -> Tuple[int, int, int]:
        """Return (train_batch_size, micro_batch_per_replica, grad_accum_steps).

        Any two determine the third; exactly like the reference, all three set must
        satisfy train == micro * gas * dp_world_size.
        """
        tb, mb, gas = self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ConfigError(
                    f"train_batch_size({tb}) != micro_batch({mb}) * gradient_accumulation_steps({gas})"
                    f" * dp_world_size({dp_world_size})")
        elif tb is not None and mb is not None:
            if tb % (mb * dp_world_size) != 0:
                raise ConfigError(f"train_batch_size({tb}) not divisible by micro_batch({mb}) * dp({dp_world_size})")
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise ConfigError(f"train_batch_size({tb}) not divisible by gas({gas}) * dp({dp_world_size})")
            mb = tb // (gas * dp_world_size)
        elif mb is not None:
            gas = gas or 1
            tb = mb * gas * dp_world_size
        elif tb is not None:
            gas = 1
            if tb % dp_world_size != 0:
                raise ConfigError(f"train_batch_size({tb}) not divisible by dp_world_size({dp_world_size})")
            mb = tb // dp_world_size
        else:
            raise ConfigError(
                "at least one of train_batch_size / train_micro_batch_size_per_gpu must be set")
        self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps = tb, mb, gas
        return tb, mb, gas

    # ------------------------------------------------------------------ #

    @property
    def compute_dtype(self):
        import jax.numpy as jnp
        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    @property
    def grad_accum_dtype(self):
        import jax.numpy as jnp
        name = (self.data_types or {}).get("grad_accum_dtype")
        if name is None:
            return jnp.float32
        return {"fp32": jnp.float32, "fp16": jnp.float16, "bf16": jnp.bfloat16}[name]

    def __post_init__(self):
        if self.bf16.enabled and self.fp16.enabled:
            raise ConfigError("bf16 and fp16 cannot both be enabled")
