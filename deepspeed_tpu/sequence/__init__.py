"""Reference-spelled ``deepspeed.sequence`` package (Ulysses SP).

Parity: ``deepspeed/sequence/layer.py`` — ``DistributedAttention`` and
``single_all_to_all`` live in ``parallel/ulysses.py`` (plus the TPU-natural
ring-attention CP in ``parallel/ring.py``, absent from the reference).
"""
from deepspeed_tpu.sequence import layer  # noqa: F401
from deepspeed_tpu.parallel.ulysses import (DistributedAttention,  # noqa: F401
                                            single_all_to_all, ulysses_attention)

__all__ = ["DistributedAttention", "single_all_to_all", "ulysses_attention",
           "layer"]
