"""Parity spelling: ``deepspeed.sequence.layer``."""
from deepspeed_tpu.parallel.ulysses import (DistributedAttention,  # noqa: F401
                                            single_all_to_all, ulysses_attention)
