"""Search strategies over config candidates.

Parity: reference ``autotuning/tuner/{base_tuner,index_based_tuner,
model_based_tuner}.py`` — GridSearchTuner (exhaustive, ordered), RandomTuner
(shuffled), ModelBasedTuner (fits a surrogate on observed results and explores
the most promising remaining candidate).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple


class BaseTuner:
    def __init__(self, space: List[Dict[str, Any]], seed: int = 0):
        self.space = list(space)
        self.results: List[Tuple[Dict[str, Any], Optional[float]]] = []
        self.seed = seed

    def has_next(self) -> bool:
        return len(self.results) < len(self.space)

    def next_trial(self) -> Dict[str, Any]:
        raise NotImplementedError

    def record(self, candidate: Dict[str, Any], score: Optional[float]):
        """score None => infeasible (OOM/compile failure)."""
        self.results.append((candidate, score))

    def best(self) -> Tuple[Optional[Dict[str, Any]], Optional[float]]:
        feasible = [(c, s) for c, s in self.results if s is not None]
        if not feasible:
            return None, None
        return max(feasible, key=lambda t: t[1])


class GridSearchTuner(BaseTuner):
    """Exhaustive in declared order (index_based_tuner.py)."""

    def next_trial(self) -> Dict[str, Any]:
        return self.space[len(self.results)]


class RandomTuner(BaseTuner):
    """Shuffled exhaustive (index_based_tuner.py RandomTuner)."""

    def __init__(self, space, seed: int = 0):
        super().__init__(space, seed)
        order = list(range(len(self.space)))
        random.Random(seed).shuffle(order)
        self._order = order

    def next_trial(self) -> Dict[str, Any]:
        return self.space[self._order[len(self.results)]]


class ModelBasedTuner(BaseTuner):
    """Nearest-neighbour surrogate (model_based_tuner.py, simplified): after
    each observation, pick the unexplored candidate closest (in normalized
    knob space) to the current best — exploit-first with grid fallback."""

    def __init__(self, space, seed: int = 0):
        super().__init__(space, seed)
        self._tried: set = set()

    def _key(self, c: Dict[str, Any]) -> tuple:
        return tuple(sorted((k, str(v)) for k, v in c.items()))

    def _distance(self, a: Dict[str, Any], b: Dict[str, Any]) -> float:
        d = 0.0
        for k in set(a) | set(b):
            va, vb = a.get(k), b.get(k)
            if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
                denom = max(abs(va), abs(vb), 1e-9)
                d += abs(va - vb) / denom
            elif va != vb:
                d += 1.0
        return d

    def next_trial(self) -> Dict[str, Any]:
        remaining = [c for c in self.space if self._key(c) not in self._tried]
        best, score = self.best()
        if best is None:
            cand = remaining[0]
        else:
            cand = min(remaining, key=lambda c: self._distance(c, best))
        self._tried.add(self._key(cand))
        return cand

    def record(self, candidate, score):
        self._tried.add(self._key(candidate))
        super().record(candidate, score)


def build_tuner(tuner_type: str, space: List[Dict[str, Any]], seed: int = 0
                ) -> BaseTuner:
    key = tuner_type.lower().replace("_", "")
    if key in ("gridsearch", "grid"):
        return GridSearchTuner(space, seed)
    if key == "random":
        return RandomTuner(space, seed)
    if key in ("modelbased", "model"):
        return ModelBasedTuner(space, seed)
    raise ValueError(f"unknown tuner_type '{tuner_type}' "
                     "(gridsearch|random|model_based)")
