"""The autotuner: explore configs, score by compile-time memory + measured
throughput.

Parity (re-designed): reference ``Autotuner`` (autotuner.py:42) launches one
training JOB per candidate through the launcher, reads metrics files back, and
prunes by profiled model memory (``model_info_profile_run``). On TPU/XLA the
expensive part collapses: a candidate's memory footprint comes from
``jit(...).lower().compile().memory_analysis()`` WITHOUT running a step, so
infeasible configs are rejected at compile time; surviving candidates are then
timed by invoking the already-compiled executable (one XLA compile per
candidate total). ``fast`` shortens the timed run to one step;
``compile_only=True`` skips timing and ranks by negative memory — in-process,
no launcher round-trip (the reference's ResourceManager/scheduler.py exists
for multi-node experiment placement; here experiments are sequential jit
sessions).
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deepspeed_tpu.autotuning.tuner import build_tuner
from deepspeed_tpu.config import DeepSpeedTPUConfig
from deepspeed_tpu.utils.logging import logger


@dataclass
class Experiment:
    """One candidate trial (parity: the exp json the reference writes)."""

    config_overrides: Dict[str, Any]
    score: Optional[float] = None          # metric value; None = infeasible
    metrics: Dict[str, Any] = field(default_factory=dict)
    error: Optional[str] = None


DEFAULT_TUNING_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": None,  # filled from config bounds
}


class Autotuner:
    """Searches (zero stage, micro-batch, remat) for the best feasible config.

    ``tune(model, batch)`` returns ``(best_config_dict, experiments)``.
    """

    def __init__(self, base_config, tuning_space: Optional[Dict[str, List]] = None,
                 results_dir: Optional[str] = None):
        self.base = base_config if isinstance(base_config, DeepSpeedTPUConfig) \
            else DeepSpeedTPUConfig.load(base_config)
        at = self.base.autotuning
        self.at = at
        self.results_dir = results_dir or at.results_dir
        space = dict(tuning_space or {})
        space.setdefault("zero_optimization.stage", [0, 1, 2, 3])
        if space.get("train_micro_batch_size_per_gpu") is None:
            mbs, hi = [], at.max_train_micro_batch_size_per_gpu
            m = max(1, at.min_train_micro_batch_size_per_gpu)
            while m <= hi:
                mbs.append(m)
                m *= 2
            space["train_micro_batch_size_per_gpu"] = mbs
        self.tuning_space = space

    # -- candidate enumeration ------------------------------------------- #
    def candidates(self) -> List[Dict[str, Any]]:
        keys = sorted(self.tuning_space)
        combos = itertools.product(*(self.tuning_space[k] for k in keys))
        return [dict(zip(keys, vals)) for vals in combos]

    def _apply(self, overrides: Dict[str, Any]) -> DeepSpeedTPUConfig:
        raw = copy.deepcopy(self.base.to_dict())
        # autotuner owns the micro-batch/GAS split: fix the global batch and
        # let GAS absorb the rest (reference does the same batch algebra)
        raw.pop("gradient_accumulation_steps", None)
        for dotted, val in overrides.items():
            node = raw
            parts = dotted.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = val
        return DeepSpeedTPUConfig.load(raw)

    # -- scoring ---------------------------------------------------------- #
    def _compile_probe(self, model, cfg: DeepSpeedTPUConfig, batch
                       ) -> Dict[str, Any]:
        """Build the engine + lower/compile the fused step; no step executed.
        Returns memory estimates (parity: the model-info profile run that
        writes activation_mem_per_gpu, engine.py:1786,1852)."""
        import jax
        import deepspeed_tpu
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        engine._ensure_state(batch)
        sharded = engine._shard_global_batch(batch)
        step = engine._build_fused_step()
        lowered = jax.jit(step, donate_argnums=(0,)).lower(engine.state, sharded)
        compiled = lowered.compile()
        mem = {}
        try:
            ma = compiled.memory_analysis()
            for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                      "output_size_in_bytes", "generated_code_size_in_bytes"):
                v = getattr(ma, k, None)
                if v is not None:
                    mem[k] = int(v)
        except Exception:  # backend without memory analysis
            pass
        return {"engine": engine, "compiled": compiled,
                "sharded_batch": sharded, "memory": mem}

    def _measure_compiled(self, probe, batch_size: int, steps: int,
                          sync: bool = True) -> float:
        """Time the ALREADY-compiled step (no second XLA compile): the probe's
        Compiled executable is invoked directly.

        ``sync=True`` (default) blocks on the device before and after the
        timed loop so the score measures execution; ``sync=False`` is the
        dispatch-latency escape hatch (JL001) for callers overlapping
        candidate timing with other host work."""
        compiled = probe["compiled"]
        state, sharded = probe["engine"].state, probe["sharded_batch"]
        state, m = compiled(state, sharded)  # warmup execution
        import jax
        if sync:
            jax.block_until_ready(m["loss"])
        t0 = time.time()
        for _ in range(steps):
            state, m = compiled(state, sharded)
        if sync:
            jax.block_until_ready(m["loss"])
        dt = (time.time() - t0) / steps
        # the warmup call DONATED the engine's state buffers (JL003): rebind
        # the engine to the live post-measurement state so it never dangles
        probe["engine"].state = state
        return batch_size / dt  # samples/sec

    def run_experiment(self, model, overrides: Dict[str, Any], batch,
                       measure_steps: int = 3, compile_only: bool = False,
                       sync: bool = True) -> Experiment:
        """Compile probe always runs (feasibility + memory metrics); the
        throughput measurement runs on feasible candidates unless
        ``compile_only`` (dry mode: rank by negative memory)."""
        exp = Experiment(config_overrides=dict(overrides))
        try:
            cfg = self._apply(overrides)
            probe = self._compile_probe(model, cfg, batch)
            exp.metrics.update(probe["memory"])
            if compile_only:
                temp = probe["memory"].get("temp_size_in_bytes", 0)
                args = probe["memory"].get("argument_size_in_bytes", 0)
                exp.score = -float(temp + args)
            else:
                exp.score = self._measure_compiled(
                    probe, probe["engine"].train_batch_size(), measure_steps,
                    sync=sync)
                exp.metrics["throughput_samples_per_sec"] = exp.score
        except Exception as e:  # OOM / invalid combination => infeasible
            exp.error = f"{type(e).__name__}: {e}"
            logger.info(f"autotuning: candidate {overrides} infeasible: {exp.error}")
        return exp

    # -- main loop (parity: Autotuner.tune autotuner.py) ------------------- #
    def tune(self, model, batch, tuner_type: Optional[str] = None,
             max_trials: Optional[int] = None, compile_only: Optional[bool] = None,
             measure_steps: int = 3, sync: bool = True):
        from deepspeed_tpu.comm.mesh import reset_topology
        tuner_type = tuner_type or self.at.tuner_type
        max_trials = max_trials or self.at.tuner_num_trials
        # default: measure throughput on every compile-feasible candidate;
        # "fast" shortens the measurement, compile_only=True skips it entirely
        # (memory-only dry ranking)
        compile_only = False if compile_only is None else compile_only
        if self.at.fast and not compile_only:
            measure_steps = min(measure_steps, 1)
        tuner = build_tuner(tuner_type, self.candidates())
        experiments: List[Experiment] = []
        stagnant = 0
        best_score = None
        while tuner.has_next() and len(experiments) < max_trials:
            cand = tuner.next_trial()
            reset_topology()  # each experiment builds its own engine/mesh
            exp = self.run_experiment(model, cand, batch,
                                      measure_steps=measure_steps,
                                      compile_only=compile_only, sync=sync)
            experiments.append(exp)
            tuner.record(cand, exp.score)
            if exp.score is not None and (best_score is None or exp.score > best_score):
                best_score = exp.score
                stagnant = 0
            else:
                stagnant += 1
            if stagnant >= self.at.tuner_early_stopping:
                logger.info("autotuning: early stopping "
                            f"({stagnant} trials without improvement)")
                break
        best, score = tuner.best()
        self._write_results(experiments, best, score)
        best_config = self._apply(best).to_dict() if best else None
        return best_config, experiments

    def _write_results(self, experiments, best, score):
        os.makedirs(self.results_dir, exist_ok=True)
        payload = {
            "best_overrides": best,
            "best_score": score,
            "experiments": [
                {"overrides": e.config_overrides, "score": e.score,
                 "metrics": e.metrics, "error": e.error}
                for e in experiments],
        }
        with open(os.path.join(self.results_dir, "autotuning_results.json"),
                  "w") as f:
            json.dump(payload, f, indent=2)
        logger.info(f"autotuning: best {best} score={score}; "
                    f"results in {self.results_dir}")
