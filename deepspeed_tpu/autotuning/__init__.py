"""Autotuning: search over ZeRO stage / micro-batch / remat configurations.

Parity: reference ``deepspeed/autotuning/`` (``Autotuner`` autotuner.py:42,
``ResourceManager`` scheduler.py:33, tuners in ``autotuning/tuner/``).
"""

from deepspeed_tpu.autotuning.autotuner import Autotuner, Experiment
from deepspeed_tpu.autotuning.tuner import (GridSearchTuner, ModelBasedTuner,
                                            RandomTuner, build_tuner)

__all__ = ["Autotuner", "Experiment", "GridSearchTuner", "RandomTuner",
           "ModelBasedTuner", "build_tuner"]
