"""Config keys and defaults for the deepspeed_tpu config tree.

Capability parity with the reference's ``deepspeed/runtime/constants.py`` (453 LoC of
string keys): we keep the same JSON key spellings so a DeepSpeed-style config dict can
be consumed unchanged, while the typed tree itself lives in ``deepspeed_tpu/config.py``.
"""

#############################################
# Batch / schedule
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE = "type"
OPTIMIZER_PARAMS = "params"
SCHEDULER = "scheduler"
MAX_GRAD_NORM = "max_grad_norm"
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"

#############################################
# Precision
#############################################
FP16 = "fp16"
BF16 = "bf16"
FP32 = "fp32"

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Sub-systems
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
COMMS_LOGGER = "comms_logger"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"
FLOPS_PROFILER = "flops_profiler"
AUTOTUNING = "autotuning"
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
MEMORY_BREAKDOWN = "memory_breakdown"
DUMP_STATE = "dump_state"

#############################################
# TPU-specific (no reference analog: mesh geometry replaces process groups)
#############################################
MESH = "mesh"

#############################################
# Routing / misc defaults
#############################################
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
TRAIN_BATCH_SIZE_DEFAULT = None
