"""Accelerator abstraction: the ``get_accelerator()`` surface over JAX/TPU.

Parity: reference ``accelerator/abstract_accelerator.py:10 DeepSpeedAccelerator``
(~60 abstract methods: device/RNG/stream/event/memory/dtype/graph/tensor-type/
pinning/op-builder APIs) + ``real_accelerator.py:52 get_accelerator()`` — the
layer EVERY reference subsystem calls for device portability. The TPU-native
implementation answers the same questions from jax:

- streams/events collapse: XLA owns scheduling, so ``Stream``/``Event`` are
  lightweight synchronisation shims (``synchronize`` blocks on ready arrays);
- pinned memory maps to the page-aligned host buffers the AIO engine uses;
- ``create_op_builder`` resolves the kernel registry (Pallas/XLA/native C++)
  instead of JIT-compiling CUDA extensions;
- graph capture == jit (always on).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np


class Stream:
    """Parity shim: XLA's latency-hiding scheduler owns real streams."""

    def synchronize(self):
        for d in jax.local_devices():
            try:
                jax.device_put(0.0, d).block_until_ready()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Event:
    """Parity shim for accelerator events: record/elapsed via host clock +
    device barrier (the reference uses these for wall-clock timers; our timer
    module already synchronises on fetched losses)."""

    def __init__(self, enable_timing: bool = True):
        self._t: Optional[float] = None

    def record(self, stream=None):
        import time
        TPUAccelerator._sync_all()
        self._t = time.perf_counter()  # monotonic: intervals survive clock steps

    def elapsed_time(self, end: "Event") -> float:
        if self._t is None or end._t is None:
            raise RuntimeError("event not recorded")
        return (end._t - self._t) * 1000.0

    def synchronize(self):
        TPUAccelerator._sync_all()


class TPUAccelerator:
    """The concrete accelerator (parity: ``tpu_accelerator`` would sit beside
    cuda/cpu/npu accelerators in the reference's registry)."""

    def __init__(self):
        self._name = "tpu"
        self._comm_backend = "xla"

    # -- identity ------------------------------------------------------- #
    def is_available(self) -> bool:
        try:
            return len(jax.devices()) > 0
        except Exception:
            return False

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def device(self, device_index: Optional[int] = None):
        devs = jax.devices()
        return devs[device_index or 0]

    def device_count(self) -> int:
        return len(jax.devices())

    def current_device(self) -> int:
        return 0  # one process drives its addressable devices under SPMD

    def current_device_name(self) -> str:
        return self.device_name(0)

    def set_device(self, device_index: int) -> None:
        pass  # placement is sharding-driven, not a thread-local device

    def communication_backend_name(self) -> str:
        return self._comm_backend

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True  # software fp16 with loss scaling (bf16 is native)

    def is_triton_supported(self) -> bool:
        return False  # Pallas is the kernel language here

    # -- RNG (parity: manual_seed/initial_seed...) ----------------------- #
    def manual_seed(self, seed: int):
        return jax.random.PRNGKey(seed)

    def manual_seed_all(self, seed: int):
        return jax.random.PRNGKey(seed)

    def initial_seed(self) -> int:
        return 0

    # -- synchronisation ------------------------------------------------- #
    @staticmethod
    def _sync_all():
        x = jax.device_put(np.zeros(()))
        x.block_until_ready()

    def synchronize(self, device_index: Optional[int] = None):
        self._sync_all()

    def Stream(self, **kwargs) -> Stream:
        return Stream()

    def stream(self, stream: Stream):
        return stream

    def current_stream(self, device_index: Optional[int] = None) -> Stream:
        return Stream()

    def default_stream(self, device_index: Optional[int] = None) -> Stream:
        return Stream()

    def Event(self, enable_timing: bool = True) -> Event:
        return Event(enable_timing)

    # -- memory ----------------------------------------------------------- #
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        d = self.device(device_index)
        stats = getattr(d, "memory_stats", lambda: None)()
        return dict(stats or {})

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("peak_bytes_in_use", 0))

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        s = self.memory_stats(device_index)
        return int(s.get("bytes_limit", 0)) - int(s.get("bytes_in_use", 0))

    def empty_cache(self):
        pass  # XLA's allocator has no user-facing cache flush

    def reset_peak_memory_stats(self, device_index: Optional[int] = None):
        pass

    # -- host ("pinned") memory ------------------------------------------ #
    def pin_memory(self, array, align_bytes: int = 4096):
        """Page-aligned host copy (the AIO/O_DIRECT staging contract;
        parity: tensor.pin_memory via deepspeed_pin_tensor.cpp)."""
        from deepspeed_tpu.ops.native.aio import aligned_empty
        arr = np.asarray(array)
        out = aligned_empty(arr.shape, arr.dtype)
        out[...] = arr
        return out

    def is_pinned(self, array) -> bool:
        return isinstance(array, np.ndarray) and \
            (array.ctypes.data % 4096 == 0)

    # -- dtype surface ---------------------------------------------------- #
    def supported_dtypes(self) -> List[Any]:
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    # -- graphs (parity: CUDA graph APIs; jit is always-on capture) -------- #
    def create_graph(self):
        return None

    def capture_to_graph(self, graph, **kwargs):
        import contextlib
        return contextlib.nullcontext()

    def replay_graph(self, graph):
        pass

    # -- op builder registry ---------------------------------------------- #
    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops"

    def create_op_builder(self, class_name: str):
        """Resolve a named op implementation (parity:
        ``create_op_builder``/``get_op_builder``, abstract_accelerator.py:263).
        Returns the module/callable providing that op on TPU."""
        registry = {
            "AsyncIOBuilder": "deepspeed_tpu.ops.native.aio",
            "CPUAdamBuilder": "deepspeed_tpu.ops.native.cpu_optimizer",
            "CPUAdagradBuilder": "deepspeed_tpu.ops.native.cpu_optimizer",
            "CPULionBuilder": "deepspeed_tpu.ops.native.cpu_optimizer",
            "FusedAdamBuilder": "deepspeed_tpu.ops.adam",
            "FusedLambBuilder": "deepspeed_tpu.ops.lamb",
            "QuantizerBuilder": "deepspeed_tpu.ops.quantizer",
            "SparseAttnBuilder": "deepspeed_tpu.ops.sparse_attention",
            "EvoformerAttnBuilder": "deepspeed_tpu.ops.evoformer",
            "TransformerBuilder": "deepspeed_tpu.ops.transformer_layer",
            "InferenceBuilder": "deepspeed_tpu.ops.attention",
            "RaggedOpsBuilder": "deepspeed_tpu.ops.pallas.paged_attention",
        }
        import importlib
        mod = registry.get(class_name)
        if mod is None:
            raise ValueError(f"unknown op builder '{class_name}'; "
                             f"known: {sorted(registry)}")
        return importlib.import_module(mod)

    def get_op_builder(self, class_name: str):
        return self.create_op_builder(class_name)

    # -- misc -------------------------------------------------------------- #
    def on_accelerator(self, array) -> bool:
        return isinstance(array, jax.Array)

    def range_push(self, msg: str):
        pass  # profiler annotations ride jax.named_scope

    def range_pop(self):
        pass

    def lazy_call(self, callback):
        callback()

    def visible_devices_envs(self) -> List[str]:
        return ["TPU_VISIBLE_DEVICES", "JAX_PLATFORMS"]


_ACCELERATOR: Optional[TPUAccelerator] = None


def get_accelerator() -> TPUAccelerator:
    """Parity: ``deepspeed.accelerator.get_accelerator()``
    (real_accelerator.py:52)."""
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = TPUAccelerator()
    return _ACCELERATOR
