from deepspeed_tpu.utils.logging import logger, log_dist, print_rank_0, warning_once
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from deepspeed_tpu.utils.tree import (
    tree_size_bytes,
    tree_param_count,
    global_norm,
    tree_cast,
    tree_zeros_like,
)

__all__ = [
    "logger",
    "log_dist",
    "print_rank_0",
    "warning_once",
    "SynchronizedWallClockTimer",
    "ThroughputTimer",
    "tree_size_bytes",
    "tree_param_count",
    "global_norm",
    "tree_cast",
    "tree_zeros_like",
]
